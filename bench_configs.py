"""BASELINE.md config-matrix measurements (configs 1-8).

Usage: python bench_configs.py [1|2|3|4|5|6|7|8|all]

Each config prints one JSON line; results are recorded in BASELINE.md.
Config definitions come from BASELINE.json / BASELINE.md:

1. Single 1GB .dat, RS(10,4) ec.encode on CPU (native AVX2 backend —
   the klauspost/reedsolomon stand-in) through the repo's own
   write_ec_files path (file IO included).
2. Sustained on-device jax encode (bench.py methodology: chained
   full-parity dependence, >VMEM working set) + the same 1GB
   write_ec_files end-to-end with backend=jax (includes host IO and
   the axon tunnel's ~0.5 GB/s h2d, so it is tunnel-bound; noted).
3. Rebuild with 2 missing shards: host rebuild_ec_files on the 1GB
   volume (native), plus the on-device reconstruct kernel rate.
4. 8-way sharded encode on a virtual CPU mesh (correctness +
   scaling-shape check; per-chip GB/s comes from config 2 — multi-chip
   hardware is not reachable from this image).
5. Mixed workload: p99 needle-read latency while an ec.encode runs on
   the same volume server, with the -compactionMBps throttler engaged
   vs unthrottled vs idle.
"""

import json
import os
import sys
import tempfile
import threading
import time

import numpy as np

GB = 1 << 30
DAT_SIZE = 1 * GB


def _make_dat(path: str, size: int = DAT_SIZE) -> None:
    """Synthetic .dat: 8B superblock + pseudo-random bytes (cheap:
    tiled PCG block, content irrelevant to throughput)."""
    rng = np.random.default_rng(7)
    block = rng.integers(0, 256, 16 << 20, dtype=np.uint8).tobytes()
    with open(path, "wb") as f:
        f.write(b"\x03\x00\x00\x00\x00\x00\x00\x00")
        written = 8
        while written < size:
            n = min(len(block), size - written)
            f.write(block[:n])
            written += n


def _encode_once(base: str, backend: str) -> float:
    from seaweedfs_tpu.ec import encoder
    t0 = time.perf_counter()
    encoder.write_ec_files(base, backend=backend)
    return time.perf_counter() - t0


def config1() -> dict:
    with tempfile.TemporaryDirectory() as d:
        base = os.path.join(d, "1")
        _make_dat(base + ".dat")
        dt = _encode_once(base, "native")
        gbps = DAT_SIZE / GB / dt
    return {"config": 1, "metric": "ec_encode_cpu_native_1gb",
            "wall_s": round(dt, 2), "value": round(gbps, 3),
            "unit": "GB/s"}


def config2() -> dict:
    # end-to-end 1GB through write_ec_files with the jax backend
    with tempfile.TemporaryDirectory() as d:
        base = os.path.join(d, "1")
        _make_dat(base + ".dat")
        dt = _encode_once(base, "jax")
        e2e_gbps = DAT_SIZE / GB / dt
    # sustained on-device rate: reuse bench.py (prints its own line)
    import subprocess
    try:
        out = subprocess.run([sys.executable, "bench.py"],
                             cwd=os.path.dirname(os.path.abspath(__file__)),
                             capture_output=True, text=True, timeout=900)
    except subprocess.TimeoutExpired as e:
        raise RuntimeError("bench.py timed out after 900s") from e
    device = {}
    for line in out.stdout.strip().splitlines():
        try:
            device = json.loads(line)
            break
        except json.JSONDecodeError:
            continue
    if out.returncode != 0 or "value" not in device:
        raise RuntimeError(
            f"bench.py failed (rc={out.returncode}): "
            f"{out.stderr.strip()[-400:]}")
    return {"config": 2, "metric": "ec_encode_jax_1gb",
            "device_gbps": device.get("value"),
            "e2e_wall_s": round(dt, 2),
            "e2e_gbps": round(e2e_gbps, 3),
            "note": "e2e includes disk + axon tunnel h2d (~0.5GB/s cap)"}


def config3() -> dict:
    from seaweedfs_tpu.ec import encoder
    with tempfile.TemporaryDirectory() as d:
        base = os.path.join(d, "1")
        _make_dat(base + ".dat")
        encoder.write_ec_files(base, backend="native")
        # drop 2 shards (one data, one parity) and rebuild
        for sid in (3, 11):
            os.remove(encoder.shard_file_name(base, sid))
        t0 = time.perf_counter()
        rebuilt = encoder.rebuild_ec_files(base, backend="native")
        dt = time.perf_counter() - t0
        assert sorted(rebuilt) == [3, 11]
        shard_bytes = os.path.getsize(encoder.shard_file_name(base, 0))
    return {"config": 3, "metric": "ec_rebuild_2shards_cpu_native",
            "wall_s": round(dt, 2),
            "value": round(2 * shard_bytes / GB / dt, 3),
            "unit": "GB/s rebuilt"}


def config4() -> dict:
    # virtual 8-device CPU mesh: shard the lane dimension, validate the
    # sharded program and report its (CPU-bound) rate for the record
    from seaweedfs_tpu.util import cpu_mesh
    cpu_mesh.force_cpu_platform(8)
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from seaweedfs_tpu.ops import rs_kernel
    from seaweedfs_tpu.ops.rs_code import coding_matrix, DATA_SHARDS
    devs = np.array(jax.devices("cpu")[:8])
    mesh = Mesh(devs, ("shard",))
    m2 = rs_kernel.m2_bits(np.asarray(coding_matrix())[DATA_SHARDS:])
    lanes = 8 << 20
    data = np.random.default_rng(0).integers(
        0, 256, (DATA_SHARDS, lanes), dtype=np.uint8)
    sharding = NamedSharding(mesh, P(None, "shard"))
    x = jax.device_put(data, sharding)

    @jax.jit
    def enc(d):
        return rs_kernel.gf_linear(m2, d)

    enc(x).block_until_ready()  # compile
    t0 = time.perf_counter()
    reps = 3
    for _ in range(reps):
        out = enc(x)
        out.block_until_ready()
    dt = (time.perf_counter() - t0) / reps
    # correctness vs numpy
    from seaweedfs_tpu.ops.rs_code import ReedSolomon
    ref = ReedSolomon(backend="numpy").encode(data)
    assert np.array_equal(np.asarray(out), ref)
    return {"config": 4, "metric": "ec_encode_8way_cpu_mesh",
            "devices": 8, "value": round(
                DATA_SHARDS * lanes / GB / dt, 3),
            "unit": "GB/s (virtual CPU mesh; shape/collective check, "
                    "not TPU perf)"}


def config5() -> dict:
    from seaweedfs_tpu.storage.store import Store
    from seaweedfs_tpu.storage.needle import Needle
    from seaweedfs_tpu.ec import store_ec

    def run_case(throttle_mbps):
        with tempfile.TemporaryDirectory() as d:
            store = Store([d])
            store.add_volume(1)
            v = store.find_volume(1)
            blob = os.urandom(64 << 10)
            for i in range(1, 1501):
                v.write_needle(Needle(id=i, cookie=7, data=blob))
            lat = []
            stop = threading.Event()

            def reader():
                i = 1
                while not stop.is_set():
                    t0 = time.perf_counter()
                    v.read_needle(Needle(id=(i % 1500) + 1, cookie=7))
                    lat.append(time.perf_counter() - t0)
                    i += 1
                    time.sleep(0.002)

            th = threading.Thread(target=reader, daemon=True)
            th.start()
            if throttle_mbps is not None:
                from seaweedfs_tpu.util.throttler import Throttler
                throttler = Throttler(throttle_mbps)
                # encode with throttled chunk pacing: emulate the
                # server path's -compactionMBps on shard generation
                from seaweedfs_tpu.ec import encoder as enc_mod
                orig = enc_mod._read_padded

                def slow_read(f, offset, length):
                    throttler.maybe_slowdown(length)
                    return orig(f, offset, length)
                enc_mod._read_padded = slow_read
                try:
                    v.read_only = True
                    store_ec.generate_ec_shards(store, 1, backend="native")
                finally:
                    enc_mod._read_padded = orig
            time.sleep(0.3)
            stop.set()
            th.join(timeout=5)
            store.close()
            lat.sort()
            return lat[int(len(lat) * 0.99)] * 1000 if lat else 0.0

    idle = run_case(None)
    unthrottled = run_case(0)       # 0 = throttler disabled
    throttled = run_case(200)       # 200 MB/s cap
    return {"config": 5, "metric": "read_p99_during_ec_encode_ms",
            "idle_p99_ms": round(idle, 2),
            "encode_unthrottled_p99_ms": round(unthrottled, 2),
            "encode_throttled_200mbps_p99_ms": round(throttled, 2)}


def _phase_stats(st, seconds: float) -> dict:
    ms = sorted(st.latencies_ms)
    return {
        "req_per_s": round(st.completed / seconds, 1) if seconds else 0.0,
        "p50_ms": round(st.percentile(ms, 50), 2),
        "p99_ms": round(st.percentile(ms, 99), 2),
        "failed": st.failed,
    }


def config6() -> dict:
    """Write-path A/B: round-1-style synchronous per-write commits vs
    the round-2 group-commit worker (storage/volume.py
    _GroupCommitWriter), measured with the in-binary load generator at
    the reference's shape (c=16, 1KB; reference weed benchmark
    README.md:493-503 = 15,708 req/s on 2012 hardware). Proves the
    worker earns its complexity (round-2 verdict item 7)."""
    import os as _os
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import io
    import tempfile

    from seaweedfs_tpu.command.benchmark import run_benchmark_programmatic
    from seaweedfs_tpu.storage import volume as volume_mod
    from tests.cluster_util import Cluster

    n = int(_os.environ.get("BENCH6_N", 100_000))
    results = {}
    for mode, async_write in (("sync_per_write", False),
                              ("group_commit", True)):
        orig = volume_mod.Volume.__init__

        def patched(self, *a, **kw):
            kw["async_write"] = async_write
            orig(self, *a, **kw)

        volume_mod.Volume.__init__ = patched
        c = None
        try:
            import pathlib
            tmp = pathlib.Path(tempfile.mkdtemp(prefix=f"bench6-{mode}-"))
            c = Cluster(tmp, n_volume_servers=1)
            r = run_benchmark_programmatic(
                c.master.url, n=n, concurrency=16, size=1024,
                do_read=False, out=io.StringIO())
            results[mode] = _phase_stats(r["write"], r["write_seconds"])
        finally:
            volume_mod.Volume.__init__ = orig
            if c is not None:
                c.stop()
            import shutil
            shutil.rmtree(tmp, ignore_errors=True)
    results["config"] = 6
    results["n"] = n
    results["speedup"] = round(
        results["group_commit"]["req_per_s"] /
        max(results["sync_per_write"]["req_per_s"], 0.001), 2)
    return results


def config7() -> dict:
    """Small-file data plane, round-4 shape (BASELINE.md config 6b):
    write + random-read through the public path (HTTP /dir/assign +
    pooled volume-server HTTP), c=16, 1KB, in-process cluster."""
    import io
    import pathlib

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from seaweedfs_tpu.command.benchmark import run_benchmark_programmatic
    from tests.cluster_util import Cluster

    n = int(os.environ.get("BENCH7_N", 30_000))
    tmp = pathlib.Path(tempfile.mkdtemp(prefix="bench7-"))
    c = Cluster(tmp, n_volume_servers=1)
    try:
        r = run_benchmark_programmatic(
            c.master.url, n=n, concurrency=16, size=1024,
            do_read=True, out=io.StringIO())
    finally:
        c.stop()
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)
    out = {"config": 7, "n": n}
    for phase in ("write", "read"):
        out[phase] = _phase_stats(r[phase], r[f"{phase}_seconds"])
    return out


def _drive(n: int, concurrency: int, op) -> dict:
    """Run op(i) from `concurrency` threads, n times total; returns
    req/s + latency percentiles (the config-7 stats shape)."""
    import threading
    import time as _t
    lat = []
    lock = threading.Lock()
    counter = iter(range(n))
    failed = [0]

    def worker():
        while True:
            with lock:
                i = next(counter, None)
            if i is None:
                return
            t0 = _t.monotonic()
            try:
                op(i)
                dt = (_t.monotonic() - t0) * 1e3
                with lock:
                    lat.append(dt)
            except Exception:
                with lock:
                    failed[0] += 1

    t0 = _t.monotonic()
    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    secs = _t.monotonic() - t0
    lat.sort()
    pct = lambda p: round(lat[min(len(lat) - 1, int(p * len(lat)))], 2) \
        if lat else 0.0
    return {"req_per_s": round(len(lat) / secs, 1), "p50_ms": pct(0.5),
            "p99_ms": pct(0.99), "failed": failed[0]}


class _SigV4:
    """Pooled-transport S3 bench client: signature math rides the
    repo's own util.aws_auth.sigv4_headers (the same canonical-request
    chain the gateway verifies); only the send path is the pooled
    keep-alive client."""

    def __init__(self, endpoint, access, secret, region="us-east-1"):
        self.endpoint, self.access = endpoint, access
        self.secret, self.region = secret, region

    def request(self, method: str, path: str, payload: bytes = b""):
        from seaweedfs_tpu.util import http_client
        from seaweedfs_tpu.util.aws_auth import sigv4_headers
        headers = sigv4_headers(method, self.endpoint, path, [], {},
                                payload, self.access, self.secret,
                                self.region, "s3")
        headers.pop("host", None)  # the pooled client sets Host itself
        r = http_client.request(
            method, f"{self.endpoint}{path}", body=payload or None,
            headers=headers)
        if r.status >= 300:
            raise RuntimeError(f"s3 {method} {path}: {r.status}")
        return r


def config8() -> dict:
    """Filer + S3 data planes (VERDICT r4 #2): same 1KB/c=16 shape as
    config 7 but through filer POST/GET /path (auto-chunking,
    filer_server_handlers_write_autochunk.go) and s3 PUT/GET (SigV4,
    s3api/auth_signature_v4.go)."""
    import pathlib

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from seaweedfs_tpu.s3api.auth import (ACTION_ADMIN, Credential, Iam,
                                          Identity)
    from seaweedfs_tpu.s3api.server import S3ApiServer
    from seaweedfs_tpu.util import http_client
    from tests.cluster_util import Cluster, free_port_pair

    n = int(os.environ.get("BENCH8_N", 15_000))  # BASELINE.md runs use 15k
    c16 = 16
    payload = bytes(i * 31 % 256 for i in range(1024))
    tmp = pathlib.Path(tempfile.mkdtemp(prefix="bench8-"))
    cluster = Cluster(tmp, n_volume_servers=1, with_filer=True)
    s3srv = S3ApiServer(
        filer_url=cluster.filer.url, port=free_port_pair(),
        iam=Iam([Identity(name="bench",
                          credentials=[Credential("benchak", "benchsk")],
                          actions=[ACTION_ADMIN])]))
    s3srv.start()
    out = {"config": 8, "n": n}
    try:
        filer = cluster.filer.url
        out["filer_write"] = _drive(
            n, c16, lambda i: http_client.request(
                "POST", f"{filer}/bench/f{i}", body=payload))
        out["filer_read"] = _drive(
            n, c16, lambda i: http_client.request(
                "GET", f"{filer}/bench/f{i}"))
        s3c = _SigV4(s3srv.url, "benchak", "benchsk")
        s3c.request("PUT", "/benchbkt")
        out["s3_write"] = _drive(
            n, c16, lambda i: s3c.request("PUT", f"/benchbkt/o{i}",
                                          payload))
        out["s3_read"] = _drive(
            n, c16, lambda i: s3c.request("GET", f"/benchbkt/o{i}"))
    finally:
        s3srv.stop()
        cluster.stop()
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    configs = {"1": config1, "2": config2, "3": config3, "4": config4,
               "5": config5, "6": config6, "7": config7, "8": config8}
    if which == "all":
        # each config in its own subprocess: config2 initializes the
        # TPU backend in-process, which would make config4's
        # force_cpu_platform impossible in the same interpreter
        import subprocess
        for n in configs:
            try:
                r = subprocess.run([sys.executable, __file__, n],
                                   capture_output=True, text=True,
                                   timeout=1800)
            except subprocess.TimeoutExpired:
                print(json.dumps({"config": int(n),
                                  "error": "timed out after 1800s"}),
                      flush=True)
                continue
            out = r.stdout.strip()
            if r.returncode != 0 or not out:
                print(json.dumps({"config": int(n), "error":
                                  r.stderr.strip()[-300:]}), flush=True)
            else:
                print(out.splitlines()[-1], flush=True)
        return
    print(json.dumps(configs[which]()), flush=True)


if __name__ == "__main__":
    main()
