"""gRPC plumbing without codegen'd service stubs.

The image has the grpc runtime and protoc, but not the grpc_tools /
grpc_python_plugin codegen. Instead of checking in hand-written *_pb2_grpc
boilerplate, stubs and server handlers are built at import time from the
service descriptors embedded in the generated *_pb2 modules.

Conventions follow the reference:
  - gRPC port = HTTP port + 10000 (weed/command/master.go:136)
  - one cached channel per target address (weed/pb/grpc_client_server.go)
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

import grpc
from google.protobuf import message_factory

from seaweedfs_tpu.resilience import deadline as _deadline
from seaweedfs_tpu.resilience import failpoint as _failpoint

GRPC_PORT_OFFSET = 10000

# QoS tenant propagation seam: seaweedfs_tpu.qos.configure() installs
# the tenant ContextVar here (reset() clears it) so outbound stubs
# forward the ambient tenant as x-seaweed-tenant metadata. None — the
# default — keeps invoke() one identity check away from the plain path.
_qos_tenant = None
_QOS_TENANT_KEY = "x-seaweed-tenant"

_channel_lock = threading.Lock()
_channels: Dict[str, grpc.Channel] = {}
# bumped on close_channels; invalidates the stub cache. make_stub's
# lock-free read only keys the cache: a stale generation rebuilds a
# stub against a closing channel, which the resilient-call retry absorbs
_channel_generation = 0  # guarded_by(_channel_lock, writes)
_stub_cache: Dict[tuple, object] = {}

# process-wide TLS (security/tls.py configure_process_tls). None =
# plaintext, matching the reference's default when security.toml has no
# [grpc.*] sections.
_server_credentials: Optional[grpc.ServerCredentials] = None
_channel_credentials: Optional[grpc.ChannelCredentials] = None


def set_server_credentials(creds) -> None:
    global _server_credentials
    _server_credentials = creds


def set_channel_credentials(creds) -> None:
    """Future channels dial with mTLS; existing cached plaintext
    channels are dropped so they re-dial secured."""
    global _channel_credentials
    _channel_credentials = creds
    close_channels()


def grpc_address(url: str) -> str:
    """Map an HTTP "host:port" to its gRPC sibling "host:port+10000"."""
    if "//" in url:
        url = url.split("//", 1)[1]
    host, sep, port = url.rpartition(":")
    if not sep or not port.isdigit():
        raise ValueError(f"expected host:port, got {url!r}")
    return f"{host}:{int(port) + GRPC_PORT_OFFSET}"


def cached_channel(address: str) -> grpc.Channel:
    with _channel_lock:
        ch = _channels.get(address)
        if ch is None:
            options = [("grpc.max_send_message_length", 64 << 20),
                       ("grpc.max_receive_message_length", 64 << 20)]
            if _channel_credentials is not None:
                ch = grpc.secure_channel(address, _channel_credentials,
                                         options=options)
            else:
                ch = grpc.insecure_channel(address, options=options)
            _channels[address] = ch
        return ch


def close_channels() -> None:
    global _channel_generation
    with _channel_lock:
        for ch in _channels.values():
            ch.close()
        _channels.clear()
        _channel_generation += 1
        _stub_cache.clear()


class _MethodSpec:
    __slots__ = ("name", "path", "req_cls", "resp_cls",
                 "client_streaming", "server_streaming")

    def __init__(self, service_desc, method_desc):
        self.name = method_desc.name
        self.path = f"/{service_desc.full_name}/{method_desc.name}"
        self.req_cls = message_factory.GetMessageClass(method_desc.input_type)
        self.resp_cls = message_factory.GetMessageClass(method_desc.output_type)
        self.client_streaming = method_desc.client_streaming
        self.server_streaming = method_desc.server_streaming


def _service_specs(pb2_module, service_name: str):
    svc = pb2_module.DESCRIPTOR.services_by_name[service_name]
    return svc, [_MethodSpec(svc, m) for m in svc.methods]


def _resilient_call(multicallable, path: str):
    """Wrap one multicallable with the outbound resilience +
    observability edge: the rpc.call failpoint, the ambient deadline
    (capping any caller timeout to the remaining budget; gRPC itself
    propagates the deadline to the server as context.time_remaining())
    and — when cluster tracing is on — the x-seaweed-trace metadata
    carrying the ambient trace context to the peer. Every branch is
    one flag/contextvar check when disarmed/unbudgeted/untraced."""
    from seaweedfs_tpu.stats import cluster_trace as _ctrace

    def invoke(request_or_iterator, timeout=None, **kwargs):
        if _failpoint._armed:
            _failpoint.hit("rpc.call", method=path)
        if _deadline.get() is not None:
            rem = _deadline.remaining()
            if rem <= 0:
                from seaweedfs_tpu.stats.metrics import \
                    DeadlineRefusedCounter
                DeadlineRefusedCounter.labels("rpc").inc()
                raise _deadline.DeadlineExceeded(f"rpc {path}")
            timeout = rem if timeout is None else min(timeout, rem)
        if _ctrace._enabled:
            hdr = _ctrace.outbound_header()
            if hdr is not None:
                md = list(kwargs.get("metadata") or ())
                md.append((_ctrace.GRPC_KEY, hdr))
                kwargs["metadata"] = md
        if _qos_tenant is not None:
            _t = _qos_tenant.get()
            if _t is not None:
                md = list(kwargs.get("metadata") or ())
                md.append((_QOS_TENANT_KEY, _t))
                kwargs["metadata"] = md
        return multicallable(request_or_iterator, timeout=timeout,
                             **kwargs)
    invoke.__name__ = path.rsplit("/", 1)[-1]
    return invoke


def make_stub(pb2_module, service_name: str, target: str):
    """A stub object with one callable per RPC, like codegen'd stubs.

    Stubs are cached per (service, target): building one walks the
    service descriptor and allocates a multicallable per RPC, which is
    far too expensive to repeat on every data-plane request."""
    key = (id(pb2_module), service_name, target, _channel_generation)
    stub = _stub_cache.get(key)
    if stub is not None:
        return stub
    _, specs = _service_specs(pb2_module, service_name)
    channel = cached_channel(target)
    stub = type(f"{service_name}Stub", (), {})()
    for spec in specs:
        if spec.client_streaming and spec.server_streaming:
            factory = channel.stream_stream
        elif spec.client_streaming:
            factory = channel.stream_unary
        elif spec.server_streaming:
            factory = channel.unary_stream
        else:
            factory = channel.unary_unary
        setattr(stub, spec.name, _resilient_call(factory(
            spec.path,
            request_serializer=spec.req_cls.SerializeToString,
            response_deserializer=spec.resp_cls.FromString), spec.path))
    with _channel_lock:
        return _stub_cache.setdefault(key, stub)


def generic_handler(pb2_module, service_name: str, servicer,
                    stats_role: Optional[str] = None) -> grpc.GenericRpcHandler:
    """Route RPCs of one service to same-named methods on `servicer`.

    Unimplemented methods raise UNIMPLEMENTED instead of failing at
    registration, so servers can grow their surface incrementally.

    Every implemented method is wrapped with the shared request
    counter/latency instrumentation (stats.metrics.instrument_grpc_method)
    under the `stats_role` type label — lowerCamel of the service name
    when the caller doesn't pass one — so all roles' gRPC planes report
    uniformly instead of each hand-rolling stats.
    """
    from seaweedfs_tpu.stats.metrics import instrument_grpc_method
    if stats_role is None:
        stats_role = service_name[:1].lower() + service_name[1:]
    # the cluster tracer labels request spans with the serving node's
    # address so the stitcher groups gRPC and HTTP ingress of one
    # server into the same process lane (servicers expose .url;
    # address-less ones like RaftNode just label empty)
    server_url = getattr(servicer, "url", "") or ""
    svc, specs = _service_specs(pb2_module, service_name)
    handlers = {}
    for spec in specs:
        fn = getattr(servicer, spec.name, None)
        if fn is None:
            def fn(request, context, _name=spec.name):  # noqa: ARG001
                context.abort(grpc.StatusCode.UNIMPLEMENTED,
                              f"method {_name} not implemented")
        else:
            fn = instrument_grpc_method(
                fn, stats_role, spec.name,
                server_streaming=spec.server_streaming,
                server=server_url)
        if spec.client_streaming and spec.server_streaming:
            make = grpc.stream_stream_rpc_method_handler
        elif spec.client_streaming:
            make = grpc.stream_unary_rpc_method_handler
        elif spec.server_streaming:
            make = grpc.unary_stream_rpc_method_handler
        else:
            make = grpc.unary_unary_rpc_method_handler
        handlers[spec.name] = make(fn, request_deserializer=spec.req_cls.FromString,
                                   response_serializer=spec.resp_cls.SerializeToString)
    return grpc.method_handlers_generic_handler(svc.full_name, handlers)


def make_server(address: str, handlers, max_workers: int = 16) -> grpc.Server:
    """Build + start a grpc.Server bound to `address` with the given
    generic handlers (from generic_handler())."""
    from concurrent import futures
    server = grpc.server(
        # lint: thread-ok(gRPC server pool; instrument_grpc_method mints request context per call)
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=[("grpc.max_send_message_length", 64 << 20),
                 ("grpc.max_receive_message_length", 64 << 20),
                 ("grpc.so_reuseport", 0)])
    for h in handlers:
        server.add_generic_rpc_handlers((h,))
    if _server_credentials is not None:
        bound = server.add_secure_port(address, _server_credentials)
    else:
        bound = server.add_insecure_port(address)
    if bound == 0:
        raise OSError(f"cannot bind grpc server to {address}")
    server.bound_port = bound  # OS-assigned when address ends in :0
    server.start()
    return server


def peer_ip(context, default: str = "127.0.0.1") -> str:
    """Client IP from a gRPC ServicerContext ("ipv4:1.2.3.4:567",
    "ipv6:[::1]:567", "unix:..." -> default)."""
    peer = context.peer() or ""
    if peer.startswith(("ipv4:", "ipv6:")):
        host = peer.split(":", 1)[1].rsplit(":", 1)[0]
        return host.strip("[]") or default
    return default
