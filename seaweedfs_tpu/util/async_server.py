"""Selector-based async serving core (-serve.async).

Every server role's data plane used to ride thread-per-connection
(util/http_server.TrackingHTTPServer): at millions of keep-alive
connections that model is the wall — 10k idle sockets cost 10k parked
threads. The reference gets an event-driven data plane for free from
Go's netpoller (SURVEY §1, server layer); this module is the
Python-side equivalent:

- ONE event loop (the role's existing listener thread calling
  serve_forever) owns every socket through a ``selectors`` poll: it
  accepts, reads, frames requests with a state-machine HTTP/1.1
  parser (partial headers across recvs, keep-alive, pipelining,
  chunked bodies), and writes responses — connections cost a few KB
  of buffer, not a thread.
- Parsed requests dispatch to a bounded FanOutPool of workers (zero
  threads until the first request) that run the SAME instrumented
  handler classes the threaded model runs: the do_* methods, the
  instrument_http_handler spans/metrics, X-Seaweed-Deadline
  re-anchoring, X-Seaweed-Trace adoption, and failpoints all flow
  through unchanged, so both models answer byte-identically and land
  on the same dashboards.
- GET bodies that resolve to a FileSpan (the volume read path's
  zero-copy seam) leave the process through os.sendfile — volume fd
  straight to socket, payload bytes never enter Python.
- Accept backpressure: past -serve.maxConns the listener is
  unregistered from the poll (the accept queue, then SYN backlog,
  absorbs the burst) and re-registered as connections close.
- Keep-alive budget: past -serve.keepAliveBudget idle keep-alive
  connections, the least-recently-active idle connection is closed —
  responses already promised keep-alive are never truncated; the
  close lands between requests, exactly where HTTP allows it.

Parse-level behavior is byte-identical to the threaded model by
construction, not by re-implementation: once a head block is framed,
the request is parsed by the handler class's OWN parse_request over
the buffered bytes, so 400/414/431/505 error bytes, close_connection
rules, and Expect: 100-continue handling come from the one shared
code path.

Concurrency contract (proved by schedule-explorer interleavings in
tests/test_serve_async.py): the loop thread owns all connection
state except the completion handoff — workers publish finished
responses through _complete(), which appends under _lock and wakes
the loop through a self-pipe; the loop is the only closer of
connections, and a completion racing a close is dropped with its
file spans released.
"""

from __future__ import annotations

import errno
import io
import os
import selectors
import socket
import threading
from collections import OrderedDict, deque
from typing import Deque, Dict, List, Optional, Tuple

from seaweedfs_tpu.util import wlog
from seaweedfs_tpu.util.fanout import FanOutPool
from seaweedfs_tpu.util.http_server import (
    _MAX_CHUNK_LINE, _MAX_LINE, BodyReader, FileSpan, is_chunked,
    parse_content_length)

log = wlog.logger("async_server")

DEFAULT_MAX_CONNS = 4096
DEFAULT_KEEPALIVE_BUDGET = 1024
DEFAULT_WORKERS = 16
# QoS seam: seaweedfs_tpu.qos.configure() installs its manager here
# (reset() clears it). With it armed, -serve.maxConns / keep-alive
# budgets become WEIGHTED per-tenant budgets: an over-share tenant is
# refused at frame time — before a worker thread is burned — and its
# idle keep-alives are the first reclaimed. None (default) keeps every
# loop path one identity check away from unchanged.
_qos = None
# fraction of max_conns past which frame-time conn policing kicks in
# (below it there is no contention worth refusing anyone over)
_QOS_CONN_HIGH_WATER = 0.875
# most bytes buffered ahead of the current request before the loop
# stops reading a connection (aggressive pipeliners can't balloon RAM)
_PIPELINE_CAP = 262144
_RECV_SIZE = 65536
# Linux sendfile caps count near 2^31; stay page-aligned under it
_SENDFILE_MAX = 0x7FFFF000
_ACCEPT_BATCH = 64


class _ResponseWriter:
    """wfile stand-in for async-driven handlers: collects response
    bytes (and FileSpans) in order; the loop thread drains them to the
    socket. flush() is a no-op — everything is already 'sent' as far
    as the handler can observe, matching the threaded model's
    end-of-request flush."""

    __slots__ = ("chunks",)

    def __init__(self):
        self.chunks: List = []

    def write(self, data) -> int:
        if data:
            self.chunks.append(bytes(data))
        return len(data)

    def add_span(self, span: FileSpan) -> None:
        self.chunks.append(span)

    def take(self) -> List:
        out, self.chunks = self.chunks, []
        return out

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


class _ChunkedScanner:
    """Framing-only scanner: finds where a chunked message body ENDS
    in the inbound stream. The raw (still-encoded) bytes are buffered
    and later decoded by BodyReader in the worker — the same decoder
    the threaded model runs, so the two models cannot disagree about
    a body's content."""

    __slots__ = ("_phase", "_remaining", "error")

    def __init__(self):
        self._phase = "size"   # size | data | trailer
        self._remaining = 0
        self.error = False

    def feed(self, buf: bytearray, start: int) -> Tuple[int, bool]:
        """Consume from buf[start:]; returns (new_start, done)."""
        i, n = start, len(buf)
        while i < n:
            if self._phase == "data":
                take = min(self._remaining, n - i)
                i += take
                self._remaining -= take
                if self._remaining:
                    break
                self._phase = "size"
                continue
            j = buf.find(b"\n", i)
            if j < 0:
                if n - i > _MAX_CHUNK_LINE:
                    self.error = True
                    return i, True
                break
            line = bytes(buf[i:j]).strip()
            i = j + 1
            if self._phase == "trailer":
                if not line:
                    return i, True
                continue
            if not line:      # CRLF between chunks
                continue
            try:
                size = int(line.split(b";", 1)[0].strip(), 16)
            except ValueError:
                self.error = True
                return i, True
            if size == 0:
                self._phase = "trailer"
            else:
                self._phase = "data"
                self._remaining = size + 2  # payload + trailing CRLF
        return i, False


# connection states (loop-thread-owned)
_ST_HEAD = 0    # accumulating/expecting a request head
_ST_BODY = 1    # head parsed, accumulating the body
_ST_BUSY = 2    # request dispatched to a worker
_ST_WRITE = 3   # response draining to the socket


class _Connection:
    """One accepted socket. All fields are owned by the loop thread
    except `pending`/`dead`, the worker->loop completion handoff,
    which the server's _lock guards."""

    __slots__ = ("sock", "fd", "addr", "inbuf", "body", "body_scan",
                 "body_remaining", "chunker", "shim", "out", "state",
                 "close_after", "eof", "read_on", "write_on",
                 "pending", "dead", "last_active", "expect_sent",
                 "tenant")

    def __init__(self, sock, addr):
        self.sock = sock
        self.fd = sock.fileno()
        self.addr = addr
        self.inbuf = bytearray()
        self.body = b""
        self.body_scan = 0            # scanner cursor into inbuf
        self.body_remaining = 0       # content-length mode
        self.chunker: Optional[_ChunkedScanner] = None
        self.shim = None
        self.out: Deque = deque()
        self.state = _ST_HEAD
        self.close_after = False
        self.eof = False
        self.read_on = False
        self.write_on = False
        self.pending: Optional[Tuple[List, bool]] = None  # guarded_by(server._lock)
        self.dead = False                                 # guarded_by(server._lock)
        self.last_active = 0.0
        self.expect_sent = False
        self.tenant = None   # QoS identity (set at first framed request)

    def drop_buffers(self) -> None:
        """Release FileSpans queued on a connection that will never
        drain (loop-side close)."""
        for item in self.out:
            if isinstance(item, FileSpan):
                item.close()
        self.out.clear()


class AsyncHTTPServer:
    """Drop-in for TrackingHTTPServer behind -serve.async: same
    construction shape, serve_forever()/shutdown()/server_close()
    contract, and handler classes — different machine underneath."""

    def __init__(self, server_address, RequestHandlerClass, role: str = "",
                 max_conns: int = 0, keepalive_budget: int = 0,
                 workers: int = 0):
        import time as _time
        self._time = _time
        self.handler_cls = RequestHandlerClass
        self.role = role or "server"
        self.max_conns = max_conns or DEFAULT_MAX_CONNS
        self.keepalive_budget = keepalive_budget or \
            DEFAULT_KEEPALIVE_BUDGET
        self._listener = socket.socket(socket.AF_INET,
                                       socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind(server_address)
        self._listener.listen(1024)
        self._listener.setblocking(False)
        self.server_address = self._listener.getsockname()
        self._selector = selectors.DefaultSelector()
        self._selector.register(self._listener, selectors.EVENT_READ,
                                ("accept", None))
        self._wake_r, self._wake_w = os.pipe()
        os.set_blocking(self._wake_r, False)
        os.set_blocking(self._wake_w, False)
        self._selector.register(self._wake_r, selectors.EVENT_READ,
                                ("wake", None))
        # zero threads until the first request (FanOutPool contract)
        self._pool = FanOutPool(workers or DEFAULT_WORKERS,
                                f"serve-{self.server_address[1]}")
        self._conns: Dict[int, _Connection] = {}
        self._idle: "OrderedDict[int, _Connection]" = OrderedDict()
        self._accepting = True
        self._lock = threading.Lock()
        self._completed: Deque[_Connection] = deque()  # guarded_by(self._lock)
        self._shutdown = False   # latch; loop polls it each pass
        self._done = threading.Event()
        self._done.set()   # not running yet
        self._closed = False
        from seaweedfs_tpu.stats.metrics import (
            ServeConnectionsGauge, ServeSendfileBytesCounter,
            ServeShedCounter)
        self._conns_gauge = ServeConnectionsGauge.labels(self.role)
        self._sendfile_counter = ServeSendfileBytesCounter.labels(
            self.role)
        self._shed_accept = ServeShedCounter.labels(self.role, "accept")
        self._shed_idle = ServeShedCounter.labels(self.role,
                                                  "keepalive")
        self._shed_qos = ServeShedCounter.labels(self.role, "qos")

    # -- lifecycle -----------------------------------------------------------

    def serve_forever(self, poll_interval: float = 0.5) -> None:
        self._done.clear()
        try:
            while not self._shutdown:
                self._service_once(poll_interval)
        except OSError:
            # selector/listener torn down under us mid-shutdown
            if not self._shutdown and not self._closed:
                raise
        finally:
            self._done.set()

    def _service_once(self, timeout: Optional[float]) -> None:
        events = self._selector.select(timeout)
        for key, mask in events:
            kind, conn = key.data
            if kind == "accept":
                self._on_accept()
            elif kind == "wake":
                try:
                    os.read(self._wake_r, 4096)
                except OSError:
                    pass
            elif self._conns.get(conn.fd) is conn:
                # IDENTITY check, not membership: an fd freed by a
                # close earlier in this batch can be reused by an
                # accept in the same batch — a stale event must not
                # touch the new tenant
                if mask & selectors.EVENT_WRITE:
                    self._on_writable(conn)
                if mask & selectors.EVENT_READ and \
                        self._conns.get(conn.fd) is conn:
                    self._on_readable(conn)
        self._handle_completions()

    def shutdown(self) -> None:
        self._shutdown = True
        self._wake()
        self._done.wait(timeout=5.0)

    def server_close(self) -> None:
        self._shutdown = True
        self._closed = True
        self._wake()
        self._done.wait(timeout=5.0)
        for conn in list(self._conns.values()):
            self._close_conn(conn)
        try:
            self._selector.close()
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        for fd in (self._wake_r, self._wake_w):
            try:
                os.close(fd)
            except OSError:
                pass
        self._pool.stop()

    def _wake(self) -> None:
        try:
            os.write(self._wake_w, b"\x00")
        except OSError:
            pass  # pipe full (wake already pending) or closed

    # -- accept / close ------------------------------------------------------

    def _on_accept(self) -> None:
        for _ in range(_ACCEPT_BATCH):
            try:
                sock, addr = self._listener.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            sock.setblocking(False)
            try:
                sock.setsockopt(socket.IPPROTO_TCP,
                                socket.TCP_NODELAY, 1)
            except OSError:
                pass
            conn = _Connection(sock, addr)
            conn.last_active = self._time.monotonic()
            self._conns[conn.fd] = conn
            self._conns_gauge.inc()
            self._selector.register(sock, selectors.EVENT_READ,
                                    ("conn", conn))
            conn.read_on = True
            self._mark_idle(conn)
            if len(self._conns) >= self.max_conns and self._accepting:
                # backpressure: stop accepting; the kernel backlog
                # holds the burst until connections drain
                self._selector.unregister(self._listener)
                self._accepting = False
                self._shed_accept.inc()
                return

    def _close_conn(self, conn: _Connection) -> None:
        if self._conns.get(conn.fd) is not conn:
            return   # already closed (fd possibly reused — leave it)
        del self._conns[conn.fd]
        if conn.tenant is not None:
            mgr = _qos
            if mgr is not None:
                mgr.conn_closed(conn.tenant)
            conn.tenant = None
        with self._lock:
            conn.dead = True
            pending = conn.pending
            conn.pending = None
        if pending is not None:
            for item in pending[0]:
                if isinstance(item, FileSpan):
                    item.close()
        if self._idle.get(conn.fd) is conn:
            del self._idle[conn.fd]
        conn.drop_buffers()
        try:
            self._selector.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass
        self._conns_gauge.dec()
        if not self._accepting and not self._closed and \
                len(self._conns) < self.max_conns:
            self._selector.register(self._listener,
                                    selectors.EVENT_READ,
                                    ("accept", None))
            self._accepting = True

    # -- idle / keep-alive budget --------------------------------------------

    def _mark_idle(self, conn: _Connection) -> None:
        self._idle[conn.fd] = conn
        self._idle.move_to_end(conn.fd)
        while len(self._idle) > self.keepalive_budget:
            victim = None
            if _qos is not None:
                # weighted keep-alive budget: reclaim from the tenant
                # furthest past its share first, LRU within the tenant
                victim = self._pick_idle_victim(_qos)
            if victim is None:
                _fd, victim = self._idle.popitem(last=False)
            else:
                del self._idle[victim.fd]
            self._shed_idle.inc()
            self._close_conn(victim)

    def _pick_idle_victim(self, mgr) -> Optional[_Connection]:
        """The LRU idle connection of the tenant most over its weighted
        share of the keep-alive budget; None = nobody is over (plain
        LRU applies). Only runs while the budget is exceeded, so the
        scan is bounded by the budget itself."""
        counts: Dict[str, int] = {}
        for c in self._idle.values():
            if c.tenant is not None:
                counts[c.tenant] = counts.get(c.tenant, 0) + 1
        worst = mgr.most_over_share(counts, self.keepalive_budget)
        if worst is None:
            return None
        for c in self._idle.values():   # insertion order = LRU first
            if c.tenant == worst:
                return c
        return None

    def _mark_active(self, conn: _Connection) -> None:
        self._idle.pop(conn.fd, None)
        conn.last_active = self._time.monotonic()

    # -- read side -----------------------------------------------------------

    def _set_read(self, conn: _Connection, on: bool) -> None:
        if conn.read_on == on or conn.eof and on:
            return
        conn.read_on = on
        self._update_interest(conn)

    def _set_write(self, conn: _Connection, on: bool) -> None:
        if conn.write_on == on:
            return
        conn.write_on = on
        self._update_interest(conn)

    def _update_interest(self, conn: _Connection) -> None:
        mask = (selectors.EVENT_READ if conn.read_on else 0) | \
               (selectors.EVENT_WRITE if conn.write_on else 0)
        try:
            if mask:
                self._selector.modify(conn.sock, mask, ("conn", conn))
            else:
                self._selector.unregister(conn.sock)
                # re-register on next interest change
                conn.read_on = conn.write_on = False
        except (KeyError, ValueError):
            if mask:
                self._selector.register(conn.sock, mask,
                                        ("conn", conn))
        except OSError:
            self._close_conn(conn)

    def _on_readable(self, conn: _Connection) -> None:
        try:
            data = conn.sock.recv(_RECV_SIZE)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._close_conn(conn)
            return
        if not data:
            # peer finished sending; it may still be reading our
            # response (half-close), so a BUSY/WRITE connection lives
            # until its response drains. A head or body that hasn't
            # completed never will (no more bytes can arrive) — close
            # NOW, or a connect/partial-send/FIN loop would leak
            # connections that no budget can reclaim (they left the
            # idle LRU on their first byte) and eventually wedge
            # accept at max_conns.
            conn.eof = True
            self._set_read(conn, False)
            if conn.state in (_ST_HEAD, _ST_BODY):
                self._close_conn(conn)
            return
        conn.inbuf += data
        self._mark_active(conn)
        self._advance(conn)

    def _advance(self, conn: _Connection) -> None:
        """Run the per-connection state machine as far as the buffered
        bytes allow (requests execute strictly one at a time per
        connection; pipelined followers wait in inbuf)."""
        while True:
            if conn.state == _ST_HEAD:
                if not self._try_head(conn):
                    break
            elif conn.state == _ST_BODY:
                if not self._try_body(conn):
                    break
            else:
                # busy/writing: just watch the pipeline cap
                if len(conn.inbuf) > _PIPELINE_CAP:
                    self._set_read(conn, False)
                break

    # -- head parse ----------------------------------------------------------

    def _make_shim(self, conn: _Connection):
        shim = self.handler_cls.__new__(self.handler_cls)
        shim.server = self
        shim.client_address = conn.addr
        shim.connection = conn.sock
        shim.close_connection = True
        shim.requestline = ""
        shim.request_version = ""
        shim.command = ""
        shim.wfile = _ResponseWriter()
        shim.async_conn = conn
        return shim

    def _try_head(self, conn: _Connection) -> bool:
        """Parse one request head out of inbuf; False = need bytes."""
        buf = conn.inbuf
        nl = buf.find(b"\n")
        if nl < 0:
            if len(buf) > _MAX_LINE:
                self._head_error(conn, 414)
            return False
        if nl + 1 > _MAX_LINE:
            self._head_error(conn, 414)
            return False
        # a bare (CR)LF where a request line should be: the threaded
        # model's parse_request returns False silently and closes
        if not bytes(buf[:nl]).strip():
            self._close_conn(conn)
            return False
        # find end of head: a line boundary followed by a blank line
        end = -1
        for pat in (b"\n\r\n", b"\n\n"):
            idx = buf.find(pat, nl)
            if idx >= 0 and (end < 0 or idx + len(pat) < end):
                end = idx + len(pat)
        if end < 0:
            # incomplete: bound the damage a never-ending header block
            # can do (any complete line is already ≤ _MAX_LINE or the
            # parse below would reject it; this caps the total block)
            if len(buf) > _MAX_LINE * 4:
                self._head_error(conn, 431)
            return False
        head = bytes(buf[:end])
        del buf[:end]
        self._mark_active(conn)
        line_end = head.find(b"\n") + 1
        shim = self._make_shim(conn)
        shim.raw_requestline = head[:line_end]
        shim.rfile = io.BufferedReader(io.BytesIO(head[line_end:]))
        ok = False
        try:
            # the handler class's OWN parser: status codes, error
            # bodies and close_connection rules come from the single
            # shared implementation
            ok = shim.parse_request()
        except Exception:
            log.exception("request parse failed (%s)", self.role)
            ok = False
        early = shim.wfile.take()   # parse errors, 100-continue
        if early:
            conn.out.extend(self._as_wire(early))
        if not ok:
            conn.close_after = True
            conn.state = _ST_WRITE
            self._start_write(conn)
            return False
        if _qos is not None and self._frame_shed(conn, shim):
            return False
        conn.shim = shim
        conn.expect_sent = bool(early)
        shim._expect_sent = conn.expect_sent
        if early:
            # the interim 100 Continue must reach a waiting client
            # BEFORE we sit in _ST_BODY expecting its payload — a
            # compliant Expect client would otherwise deadlock with us
            if self._send_items(conn.sock, conn.out):
                self._close_conn(conn)
                return False
            if conn.out:
                self._set_write(conn, True)
        if is_chunked(shim.headers):
            conn.chunker = _ChunkedScanner()
            conn.body_scan = 0
            conn.state = _ST_BODY
        else:
            conn.body_remaining = parse_content_length(shim.headers)
            conn.state = _ST_BODY
        return True

    def _frame_shed(self, conn: _Connection, shim) -> bool:
        """LOOP-thread QoS connection policing, run per framed request
        before worker handoff: account the connection to its tenant,
        and — once the process is near the conn cap — refuse a tenant
        past its weighted share of -serve.maxConns with the same
        429/503 + Retry-After reply the admission seam writes. True =
        shed (reply queued, connection closing)."""
        mgr = _qos
        name = mgr.state_of(mgr.resolve(shim.headers, shim.path)).name
        if name != conn.tenant:
            if conn.tenant is not None:
                mgr.conn_closed(conn.tenant)
            conn.tenant = name
            mgr.conn_opened(name)
        if len(self._conns) < self.max_conns * _QOS_CONN_HIGH_WATER:
            return False
        if not mgr.conn_over_share(name, self.max_conns):
            return False
        mgr.shed_reply(shim, self.role, name, 1.0, "conns")
        self._shed_qos.inc()
        conn.inbuf.clear()
        conn.out.extend(self._as_wire(shim.wfile.take()))
        conn.close_after = True
        conn.state = _ST_WRITE
        self._start_write(conn)
        return True

    def _head_error(self, conn: _Connection, code: int) -> None:
        """Pre-parse protocol error: same bytes the threaded model's
        handle_one_request would produce (requestline cleared)."""
        shim = self._make_shim(conn)
        try:
            if code == 414:
                shim.send_error(414)
            else:
                shim.send_error(code, "Header line too long")
        except Exception:
            log.exception("error reply failed (%s)", self.role)
        conn.inbuf.clear()
        conn.out.extend(self._as_wire(shim.wfile.take()))
        conn.close_after = True
        conn.state = _ST_WRITE
        self._start_write(conn)

    # -- body ----------------------------------------------------------------

    def _try_body(self, conn: _Connection) -> bool:
        buf = conn.inbuf
        if conn.chunker is not None:
            new_scan, done = conn.chunker.feed(buf, conn.body_scan)
            conn.body_scan = new_scan
            if conn.chunker.error:
                # malformed chunking: the threaded model's BodyReader
                # raises mid-handler and the connection dies without a
                # response; die the same way
                self._close_conn(conn)
                return False
            if not done:
                if len(buf) > _PIPELINE_CAP and conn.body_scan == 0:
                    pass  # still consuming; cap applies to follower bytes
                return False
            raw = bytes(buf[:conn.body_scan])
            del buf[:conn.body_scan]
            conn.body_scan = 0
            conn.chunker = None
            self._dispatch(conn, raw)
            return False
        need = conn.body_remaining
        if len(buf) < need:
            return False
        raw = bytes(buf[:need])
        del buf[:need]
        conn.body_remaining = 0
        self._dispatch(conn, raw)
        return False

    # -- worker dispatch -----------------------------------------------------

    def _dispatch(self, conn: _Connection, body: bytes) -> None:
        shim, conn.shim = conn.shim, None
        conn.state = _ST_BUSY
        if len(conn.inbuf) > _PIPELINE_CAP:
            self._set_read(conn, False)
        self._pool.submit(self._run_request, conn, shim, body)

    def _run_request(self, conn: _Connection, shim, body: bytes) -> None:
        """WORKER thread: run the instrumented handler exactly as the
        threaded model's handle_one_request would."""
        raw = io.BufferedReader(io.BytesIO(body))
        if body:
            shim.rfile = BodyReader(raw, shim.headers)
        else:
            shim.rfile = raw
        ok = True
        try:
            mname = "do_" + shim.command
            if not hasattr(shim, mname):
                shim.send_error(
                    501, "Unsupported method (%r)" % shim.command)
            else:
                getattr(shim, mname)()
        except Exception:
            # mirror of socketserver handle_error + finish(): the
            # partially-buffered response still flushes, then the
            # connection closes
            ok = False
            log.exception("handler failed: %s %s (%s)", shim.command,
                          getattr(shim, "path", "?"), self.role)
        self._complete(conn, shim.wfile.take(),
                       close=shim.close_connection or not ok)

    def _complete(self, conn: _Connection, chunks: List,
                  close: bool) -> None:
        """WORKER -> loop handoff; the only cross-thread entry.

        Deliberately hand-off-only: a worker-side direct send was
        measured SLOWER on the 2-core VM (2.0k vs 2.9k rps at 8
        conns, 2.3k vs 3.9k at 256) — pushing the send back onto the
        loop lets it batch completions per poll pass and frees the
        worker for the next request instead of serializing both
        threads through the socket."""
        dropped = None
        with self._lock:
            if conn.dead:
                dropped = chunks
            else:
                conn.pending = (self._as_wire(chunks), close)
                self._completed.append(conn)
        if dropped is not None:
            for item in dropped:
                if isinstance(item, FileSpan):
                    item.close()
            return
        self._wake()

    def _handle_completions(self) -> None:
        while True:
            with self._lock:
                if not self._completed:
                    return
                conn = self._completed.popleft()
                pending, conn.pending = conn.pending, None
            if pending is None or self._conns.get(conn.fd) is not conn:
                continue
            chunks, close = pending
            conn.out.extend(chunks)   # _complete stored wire form
            conn.close_after = conn.close_after or close
            conn.state = _ST_WRITE
            self._start_write(conn)

    @staticmethod
    def _as_wire(chunks: List) -> List:
        """memoryview discipline: byte chunks become sliceable views
        so partial sends never re-copy the tail."""
        return [c if isinstance(c, FileSpan) else memoryview(c)
                for c in chunks]

    # -- write side ----------------------------------------------------------

    def _start_write(self, conn: _Connection) -> None:
        if self._write_some(conn):
            self._set_write(conn, True)

    def _on_writable(self, conn: _Connection) -> None:
        if not self._write_some(conn):
            self._set_write(conn, False)

    def _send_items(self, sock, items: Deque) -> bool:
        """Push items (memoryviews / FileSpans) non-blocking, popping
        the deque in place as they complete; returns True on a socket
        error."""
        error = False
        try:
            while items:
                item = items[0]
                if isinstance(item, FileSpan):
                    sent = os.sendfile(sock.fileno(), item.fd,
                                       item.offset,
                                       min(item.length, _SENDFILE_MAX))
                    if sent == 0:
                        raise OSError(errno.EIO,
                                      "file span truncated mid-send")
                    self._sendfile_counter.inc(sent)
                    item.offset += sent
                    item.length -= sent
                    if item.length == 0:
                        item.close()
                        items.popleft()
                    continue
                sent = sock.send(item)
                if sent < len(item):
                    items[0] = item[sent:]
                else:
                    items.popleft()
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            error = True
        return error

    def _write_some(self, conn: _Connection) -> bool:
        """Drain conn.out; True = more to write (want EVENT_WRITE)."""
        if self._send_items(conn.sock, conn.out):
            self._close_conn(conn)
            return False
        if conn.out:
            return True
        if conn.state == _ST_WRITE:
            self._finish_response(conn)
        return False

    def _finish_response(self, conn: _Connection) -> None:
        if conn.close_after or (conn.eof and not conn.inbuf):
            self._close_conn(conn)
            return
        conn.state = _ST_HEAD
        if not conn.read_on and not conn.eof and \
                len(conn.inbuf) <= _PIPELINE_CAP:
            self._set_read(conn, True)
        if conn.inbuf:
            self._advance(conn)        # pipelined follower
        if self._conns.get(conn.fd) is not conn:
            return
        if conn.eof and conn.state in (_ST_HEAD, _ST_BODY):
            # the peer already FIN'd: an unfinished follower can
            # never complete, an idle conn is simply done
            self._close_conn(conn)
        elif conn.state == _ST_HEAD and not conn.inbuf:
            self._mark_idle(conn)
