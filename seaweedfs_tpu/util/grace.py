"""Graceful stop + profiling hooks.

Reference: weed/util/grace/signal_handling.go:16-50 (OnInterrupt signal
hooks) and weed/util/grace/pprof.go:11-34 (-cpuprofile/-memprofile).
The Python analogs: signal handlers that run registered cleanups once on
SIGINT/SIGTERM/SIGHUP, and cProfile for the CPU profile flag.
"""

from __future__ import annotations

import cProfile
import signal
import threading
from typing import Callable, List, Optional

_hooks: List[Callable[[], None]] = []
_installed = False
_fired = False
_lock = threading.Lock()
_profiler: Optional[cProfile.Profile] = None
_profile_path: Optional[str] = None


def on_interrupt(fn: Callable[[], None]) -> None:
    """Register a cleanup to run when the process receives
    SIGINT/SIGTERM (each runs once, LIFO, like the reference)."""
    global _installed
    with _lock:
        _hooks.append(fn)
        if not _installed:
            _installed = True
            for sig in (signal.SIGINT, signal.SIGTERM, signal.SIGHUP):
                try:
                    signal.signal(sig, _handle)
                except (ValueError, OSError):
                    pass  # not the main thread / unsupported signal


def _handle(signum, frame) -> None:
    run_hooks()
    raise SystemExit(128 + signum)


def run_hooks() -> None:
    """Run all registered cleanups exactly once (also called on normal
    shutdown so ctrl-C and clean exit share one path)."""
    global _fired
    with _lock:
        if _fired:
            return
        _fired = True
        hooks, _hooks[:] = list(_hooks), []
    stop_profiling()
    for fn in reversed(hooks):
        try:
            fn()
        # lint: swallow-ok(shutdown hooks are best-effort by contract)
        except Exception:
            pass


def reset() -> None:
    """Forget hooks + fired state (tests)."""
    global _fired
    with _lock:
        _hooks.clear()
        _fired = False


def setup_profiling(cpu_profile: Optional[str]) -> None:
    """Start a CPU profile that stop_profiling()/run_hooks() dumps to
    `cpu_profile` (pstats format, readable with `python -m pstats`)."""
    global _profiler, _profile_path
    if not cpu_profile:
        return
    _profile_path = cpu_profile
    _profiler = cProfile.Profile()
    _profiler.enable()


def stop_profiling() -> None:
    global _profiler
    if _profiler is not None:
        _profiler.disable()
        _profiler.dump_stats(_profile_path)
        _profiler = None
