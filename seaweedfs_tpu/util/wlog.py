"""Leveled logging for every server and tool.

The reference vendors a glog clone (weed/glog: leveled V(n) verbosity,
severity prefixes, log-dir flags).  Here the same surface is built on the
standard-library ``logging`` package: one package-root logger, a glog-style
line format, a process-wide verbosity knob for ``v(n)`` guards, and an
optional log file.

Usage::

    from seaweedfs_tpu.util import wlog
    log = wlog.logger("volume")
    log.info("volume server started on %s:%d", ip, port)
    if wlog.v(2):
        log.debug("heartbeat delta: %s", delta)

Configuration comes from ``wlog.configure()`` (the CLI wires ``-v`` and
``-logFile`` to it) or the ``WEED_V`` environment variable.
"""

from __future__ import annotations

import logging
import os
import sys
import threading
from typing import Optional

_ROOT_NAME = "seaweedfs_tpu"
_FORMAT = "%(levelname).1s%(asctime)s.%(msecs)03d %(name)s] %(message)s"
_DATEFMT = "%m%d %H:%M:%S"

_lock = threading.Lock()
_configured = False
try:
    _verbosity = int(os.environ.get("WEED_V", "0") or 0)
except ValueError:
    _verbosity = 0


def configure(verbosity: Optional[int] = None,
              log_file: Optional[str] = None,
              stderr: bool = True) -> None:
    """Install handlers on the package root logger.  Idempotent; later
    calls replace the handler set (so tests can reconfigure)."""
    global _configured, _verbosity
    with _lock:
        root = logging.getLogger(_ROOT_NAME)
        for h in list(root.handlers):
            root.removeHandler(h)
            h.close()
        fmt = logging.Formatter(_FORMAT, datefmt=_DATEFMT)
        if stderr:
            h = logging.StreamHandler(sys.stderr)
            h.setFormatter(fmt)
            root.addHandler(h)
        if log_file:
            fh = logging.FileHandler(log_file)
            fh.setFormatter(fmt)
            root.addHandler(fh)
        if verbosity is not None:
            _verbosity = verbosity
        root.setLevel(logging.DEBUG if _verbosity > 0 else logging.INFO)
        root.propagate = False
        _configured = True


def _ensure_configured() -> None:
    # Auto-configure only when nobody else set up logging: a host app
    # that installed its own handlers (on our logger or the root) keeps
    # control — we never clobber it from an import side effect.
    # lint: guard-ok(double-checked fast path; a stale False only repeats the idempotent configure)
    if _configured:
        return
    if logging.getLogger(_ROOT_NAME).handlers or logging.getLogger().handlers:
        return
    configure()


def logger(name: str) -> logging.Logger:
    """A child logger, e.g. ``wlog.logger("master")`` →
    ``seaweedfs_tpu.master``."""
    _ensure_configured()
    return logging.getLogger(f"{_ROOT_NAME}.{name}")


def v(level: int) -> bool:
    """glog-style verbosity guard: true when ``-v`` >= level."""
    return _verbosity >= level


def set_verbosity(level: int) -> None:
    global _verbosity
    _verbosity = level
    logging.getLogger(_ROOT_NAME).setLevel(
        logging.DEBUG if level > 0 else logging.INFO)


def verbosity() -> int:
    return _verbosity
