"""RS256 (RSASSA-PKCS1-v1_5 + SHA-256) signing on the standard library.

Enough to mint Google service-account JWTs without the `cryptography`
package: parse the PEM private key from a service-account JSON file
(PKCS#8 "PRIVATE KEY" or PKCS#1 "RSA PRIVATE KEY"), then sign with the
textbook m^d mod n. Used by notification/google_pub_sub.py — the
reference gets this from google-cloud-go's oauth2 stack.
"""

from __future__ import annotations

import base64
import hashlib
import json
import re
from typing import Dict, List, Tuple


class RsaKeyError(Exception):
    pass


# -- minimal DER ---------------------------------------------------------------


def _der_read(buf: bytes, pos: int) -> Tuple[int, bytes, int]:
    """One TLV: returns (tag, value, next_pos)."""
    tag = buf[pos]
    length = buf[pos + 1]
    pos += 2
    if length & 0x80:
        n = length & 0x7F
        length = int.from_bytes(buf[pos:pos + n], "big")
        pos += n
    return tag, buf[pos:pos + length], pos + length


def _der_ints(seq: bytes, count: int) -> List[int]:
    out, pos = [], 0
    while len(out) < count and pos < len(seq):
        tag, val, pos = _der_read(seq, pos)
        if tag != 0x02:
            raise RsaKeyError(f"expected INTEGER, got tag {tag:#x}")
        out.append(int.from_bytes(val, "big"))
    if len(out) < count:
        raise RsaKeyError("truncated RSA key")
    return out


def parse_private_key_pem(pem: str) -> Dict[str, int]:
    """-> {n, e, d} from a PKCS#8 or PKCS#1 RSA private key PEM."""
    m = re.search(
        r"-----BEGIN (RSA )?PRIVATE KEY-----(.*?)-----END (RSA )?"
        r"PRIVATE KEY-----", pem, re.S)
    if not m:
        raise RsaKeyError("no PRIVATE KEY block in PEM")
    der = base64.b64decode("".join(m.group(2).split()))
    tag, body, _ = _der_read(der, 0)
    if tag != 0x30:
        raise RsaKeyError("PEM body is not a DER SEQUENCE")
    if not m.group(1):
        # PKCS#8: version, AlgorithmIdentifier, OCTET STRING(PKCS#1)
        pos = 0
        _, _version, pos = _der_read(body, pos)
        _, _alg, pos = _der_read(body, pos)
        tag, inner, _ = _der_read(body, pos)
        if tag != 0x04:
            raise RsaKeyError("PKCS#8 without private-key octets")
        tag, body, _ = _der_read(inner, 0)
        if tag != 0x30:
            raise RsaKeyError("bad inner PKCS#1 structure")
    # PKCS#1 RSAPrivateKey: version, n, e, d, p, q, ...
    version, n, e, d = _der_ints(body, 4)
    return {"n": n, "e": e, "d": d}


# -- RSASSA-PKCS1-v1_5 / SHA-256 ----------------------------------------------

# DigestInfo prefix for SHA-256 (RFC 8017 §9.2 note 1)
_SHA256_PREFIX = bytes.fromhex(
    "3031300d060960864801650304020105000420")


def rs256_sign(key: Dict[str, int], data: bytes) -> bytes:
    n, d = key["n"], key["d"]
    k = (n.bit_length() + 7) // 8
    t = _SHA256_PREFIX + hashlib.sha256(data).digest()
    if k < len(t) + 11:
        raise RsaKeyError("RSA key too small for SHA-256 signature")
    em = b"\x00\x01" + b"\xff" * (k - len(t) - 3) + b"\x00" + t
    sig = pow(int.from_bytes(em, "big"), d, n)
    return sig.to_bytes(k, "big")


def rs256_verify(n: int, e: int, data: bytes, sig: bytes) -> bool:
    """Verifier counterpart (used by tests and any local consumer)."""
    k = (n.bit_length() + 7) // 8
    if len(sig) != k:
        return False
    em = pow(int.from_bytes(sig, "big"), e, n).to_bytes(k, "big")
    t = _SHA256_PREFIX + hashlib.sha256(data).digest()
    return em == b"\x00\x01" + b"\xff" * (k - len(t) - 3) + b"\x00" + t


# -- JWT ----------------------------------------------------------------------


def b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def make_jwt(key: Dict[str, int], claims: dict,
             headers: dict = None) -> str:
    header = {"alg": "RS256", "typ": "JWT", **(headers or {})}
    signing_input = (b64url(json.dumps(header).encode()) + "." +
                     b64url(json.dumps(claims).encode()))
    sig = rs256_sign(key, signing_input.encode())
    return signing_input + "." + b64url(sig)
