"""Waterfall retry with full jitter, deadline cap, and typed outcomes
(reference: weed/util/retry.go, grown per "The Tail at Scale": naked
exponential backoff synchronizes retry storms; full jitter — U(0, wait)
— decorrelates them, and a total deadline stops retrying work the
caller has already abandoned).

Every attempt lands in SeaweedFS_retry_attempts_total{name,outcome}:
  ok            the attempt succeeded
  retried       the attempt failed and another follows
  exhausted     the attempt failed and the attempt budget is spent
  nonretryable  the error class must not be replayed
  deadline      the time budget ran out before another attempt fit

The default `retryable=` is no longer a catch-all: it classifies via
util/http_client.classify — connection-class errors (the request never
reached the peer) retry; timeouts and post-send response errors do NOT
(the peer may have executed the request); open breakers and spent
deadlines never burn attempts. Non-HTTP exceptions stay retryable,
preserving the old behavior for generic callers.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, TypeVar

T = TypeVar("T")


class NonRetryableError(Exception):
    pass


def default_retryable(e: Exception) -> bool:
    from seaweedfs_tpu.util import http_client
    # "busy" = the peer answered 429/503 WITHOUT executing (QoS
    # admission shed): always safe to replay, and the server told us
    # exactly when — the loop honors e.retry_after as the pause
    return http_client.classify(e) in ("connect", "busy", "other")


def _count(name: str, outcome: str) -> None:
    from seaweedfs_tpu.stats.metrics import RetryAttemptsCounter
    RetryAttemptsCounter.labels(name, outcome).inc()


def retry(name: str, fn: Callable[[], T], *, times: int = 6,
          wait_seconds: float = 0.05, backoff: float = 2.0,
          retryable: Optional[Callable[[Exception], bool]] = None,
          deadline: Optional[float] = None, jitter: bool = True,
          _sleep=time.sleep, _rand=random.random) -> T:
    """Run fn() up to `times` times with full-jitter exponential
    backoff (sleep_k ~ U(0, wait_seconds * backoff**k) when jitter).

    `deadline` caps the WHOLE call in seconds; it combines (min) with
    any ambient resilience deadline, sleeps truncate to the remaining
    budget, and a spent budget stops retrying immediately. A budget
    that is already spent at entry raises DeadlineExceeded without
    running fn at all — the caller is gone, the work is garbage.
    """
    from seaweedfs_tpu.resilience import deadline as dl
    if retryable is None:
        retryable = default_retryable
    budget_end = None
    if deadline is not None:
        budget_end = time.monotonic() + deadline
    ambient = dl.get()
    if ambient is not None:
        budget_end = ambient if budget_end is None \
            else min(budget_end, ambient)
    if budget_end is not None and time.monotonic() >= budget_end:
        _count(name, "deadline")
        raise dl.DeadlineExceeded(f"retry {name}")

    wait = wait_seconds
    last: Exception = RuntimeError(f"{name}: retry never ran")
    for attempt in range(times):
        try:
            result = fn()
            _count(name, "ok")
            return result
        except NonRetryableError:
            _count(name, "nonretryable")
            raise
        except Exception as e:  # noqa: BLE001 - classified below
            last = e
            if not retryable(e):
                _count(name, "nonretryable")
                break
            if attempt == times - 1:
                _count(name, "exhausted")
                break
            pause = _rand() * wait if jitter else wait
            # a server-sent Retry-After (qos shed, ServerBusy) beats
            # the jittered guess: the server computed the exact bucket
            # refill time, retrying sooner just sheds again. Still
            # capped by the deadline budget below — backpressure never
            # extends a caller's time budget.
            ra = getattr(e, "retry_after", 0.0)
            if ra and ra > 0:
                pause = float(ra)
            if budget_end is not None:
                remaining = budget_end - time.monotonic()
                if remaining <= 0:
                    _count(name, "deadline")
                    break
                pause = min(pause, remaining)
            _count(name, "retried")
            _sleep(pause)
            wait *= backoff
    raise last
