"""Waterfall retry with backoff (reference: weed/util/retry.go)."""

from __future__ import annotations

import time
from typing import Callable, TypeVar

T = TypeVar("T")


class NonRetryableError(Exception):
    pass


def retry(name: str, fn: Callable[[], T], *, times: int = 6,
          wait_seconds: float = 0.05, backoff: float = 2.0,
          retryable: Callable[[Exception], bool] = lambda e: True) -> T:
    wait = wait_seconds
    last: Exception = RuntimeError(f"{name}: retry never ran")
    for attempt in range(times):
        try:
            return fn()
        except NonRetryableError:
            raise
        except Exception as e:  # noqa: BLE001 - deliberate catch-all retry
            last = e
            if not retryable(e) or attempt == times - 1:
                break
            time.sleep(wait)
            wait *= backoff
    raise last
