"""Pooled keep-alive HTTP/1.1 client for the data plane.

The reference's data path rides Go's http.Client, which pools
persistent connections per host (net/http Transport) and parses
responses with a tight byte-loop (net/textproto). The stdlib pair
(urllib / http.client) costs a fresh TCP connection per request in
urllib's case and an email-module header parse per response in both —
at small-file request rates that parsing is a measurable share of the
whole data plane. This module is the Go-client idea in plain sockets:

  - process-wide pool of persistent connections keyed by netloc
    (moral equivalent of weed/util/http_util.go:17-29's shared client)
  - TCP_NODELAY (small requests must not wait on delayed ACKs)
  - one sendall per request (headers + body in one buffer)
  - hand-rolled response parse into a lowercase-keyed dict
  - Content-Length, chunked, and read-to-close bodies
  - one retry when a pooled connection turns out stale

Only plain http is spoken here — this is the cluster-internal data
plane; TLS-bearing paths (cloud tiers, notification backends) keep
their own clients.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Dict, List, Optional, Tuple

from seaweedfs_tpu.resilience import breaker, deadline, failpoint
from seaweedfs_tpu.stats import cluster_trace as _ctrace
from seaweedfs_tpu.util.http_server import HeaderDict, parse_header_block

_pool_lock = threading.Lock()
_pool: Dict[str, List["_Conn"]] = {}  # guarded_by(_pool_lock)
_MAX_IDLE_PER_HOST = 32
# Idle-age cap: a pooled socket untouched this long is closed instead
# of reused. Long-idle sockets are the ones the server side reaps
# first, so under bursty load they surface as stale-retry churn (a
# replayed request per reused-dead socket); reaping happens
# opportunistically on pool get/put — no reaper thread, per the
# zero-threads-until-used house rule.
_IDLE_MAX_S = 60.0
_MAX_LINE = 65536


def _idle_count() -> int:
    with _pool_lock:
        return sum(len(c) for c in _pool.values())


def _export_pool_gauge() -> None:
    # collection-time callable: the gauge keeps moving without a write
    # per pool mutation
    from seaweedfs_tpu.stats.metrics import HttpPoolIdleGauge
    HttpPoolIdleGauge.set_function(_idle_count)


_export_pool_gauge()


# QoS seam: seaweedfs_tpu.qos.configure() installs the ambient-tenant
# contextvar here (reset() clears it). When armed, every outbound
# request forwards the caller's tenant in X-Seaweed-Tenant, so a
# filer's chunk uploads (or a background engine's repair traffic) are
# charged to the ORIGINAL tenant at the next hop. None (default) keeps
# the request path one identity check away from unchanged.
_qos_tenant = None
_TENANT_HEADER = "X-Seaweed-Tenant"


class ConnectError(OSError):
    """Could not establish (or reuse) a connection — the request never
    reached the peer, so replaying it is always safe. The class the
    retry default classifier treats as retryable."""


class ServerBusy(OSError):
    """Explicit backpressure from the peer (HTTP 429/503 with the QoS
    plane's Retry-After): the request was REFUSED, not executed, so
    replaying it is always safe — and the peer demonstrably answered,
    so this never burns breaker evidence (request() records the
    response as peer-alive before raising). Raised only when the
    caller opted in via request(busy_raises=True); `retry_after`
    carries the server's refill estimate in seconds (0.0 when the
    header was absent or unparseable), which util/retry honors as the
    backoff pause, capped by the ambient deadline budget."""

    def __init__(self, msg: str, status: int = 503,
                 retry_after: float = 0.0):
        super().__init__(msg)
        self.status = status
        self.retry_after = retry_after


class ResponseError(OSError):
    """Wire failure AFTER the request was sent: the peer may have
    executed it, so blind replay is not safe."""


class RequestTimeout(ResponseError):
    """Timed out awaiting the peer (connect timeouts surface as
    ConnectError via create_connection instead)."""


class _Conn:
    __slots__ = ("netloc", "sock", "rfile", "last_used")

    def __init__(self, netloc: str, timeout: float):
        self.netloc = netloc
        if failpoint._armed:
            failpoint.hit("http.connect", peer=netloc)
        if netloc.startswith("["):  # [v6-literal]:port or bare [v6-literal]
            bracket = netloc.find("]")
            host = netloc[1:bracket]
            rest = netloc[bracket + 1:]
            port = int(rest[1:]) if rest.startswith(":") else 80
        elif ":" in netloc:
            host, _, port_s = netloc.rpartition(":")
            port = int(port_s)
        else:
            host, port = netloc, 80
        try:
            self.sock = socket.create_connection((host, port),
                                                 timeout=timeout)
        except OSError as e:
            raise ConnectError(f"connect {netloc}: {e}") from e
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.rfile = self.sock.makefile("rb", buffering=65536)
        self.last_used = time.monotonic()

    def close(self) -> None:
        try:
            self.rfile.close()
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


def _get_conn(netloc: str, timeout: float) -> Tuple["_Conn", bool]:
    """Returns (conn, reused). Conns past the idle-age cap are closed,
    never handed out — they are the stale-retry churn under bursty
    load."""
    expired = []
    conn = None
    cutoff = time.monotonic() - _IDLE_MAX_S
    with _pool_lock:
        conns = _pool.get(netloc)
        while conns:
            cand = conns.pop()
            if cand.last_used >= cutoff:
                conn = cand
                break
            expired.append(cand)
    _reap(expired)
    if conn is not None:
        conn.sock.settimeout(timeout)
        return conn, True
    return _Conn(netloc, timeout), False


def _put_conn(conn: "_Conn") -> None:
    conn.last_used = time.monotonic()
    cutoff = conn.last_used - _IDLE_MAX_S
    expired = []
    with _pool_lock:
        conns = _pool.setdefault(conn.netloc, [])
        # oldest sit at the front (append order); shed them first
        while conns and conns[0].last_used < cutoff:
            expired.append(conns.pop(0))
        if len(conns) < _MAX_IDLE_PER_HOST:
            conns.append(conn)
            conn = None
    _reap(expired)
    if conn is not None:
        conn.close()


def _reap(expired) -> None:
    if not expired:
        return
    from seaweedfs_tpu.stats.metrics import HttpPoolReapedCounter
    HttpPoolReapedCounter.inc(len(expired))
    for c in expired:
        c.close()


def close_all() -> None:
    """Drop every pooled connection (tests / topology changes).
    Sockets are closed OUTSIDE the pool lock — close() can block on a
    lingering send, and the pool lock sits on the request hot path."""
    with _pool_lock:
        doomed = [c for conns in _pool.values() for c in conns]
        _pool.clear()
    for c in doomed:
        c.close()


class Response:
    __slots__ = ("status", "headers", "body")

    def __init__(self, status: int, headers: "HeaderDict", body: bytes):
        self.status = status
        self.headers = headers
        self.body = body

    def header(self, name: str, default: str = "") -> str:
        return self.headers.get(name, default)


def request(method: str, url: str, body: Optional[bytes] = None,
            headers: Optional[dict] = None, timeout: float = 60.0,
            pooled: bool = True, busy_raises: bool = False) -> Response:
    """One HTTP request over a pooled persistent connection.

    `url` is "http://host:port/path?q" or bare "host:port/path?q".
    Returns the full body bytes.

    Resilience edge (each branch is one flag check when disabled):
      - an ambient deadline refuses exhausted budgets up front, sizes
        the socket timeout to min(timeout, remaining), and forwards
        the remaining budget in X-Seaweed-Deadline
      - an enabled circuit breaker fails fast on an open peer and is
        fed by this call's final outcome (any HTTP response counts as
        peer-alive; only connection-level OSError counts as failure)
      - an ambient QoS tenant is forwarded in X-Seaweed-Tenant
      - `busy_raises=True` turns a 429/503 response into ServerBusy
        carrying the server's Retry-After — AFTER the breaker has
        recorded the response as peer-alive, so explicit backpressure
        never opens a breaker (the opt-in default keeps existing
        callers' status-code handling byte-identical)
      - the http.connect / http.response failpoints inject here
    """
    netloc, path = _split(url)
    budget_shrunk = False
    if deadline.get() is not None:
        rem = deadline.remaining()
        if rem <= 0:
            from seaweedfs_tpu.stats.metrics import DeadlineRefusedCounter
            DeadlineRefusedCounter.labels("http_client").inc()
            raise deadline.DeadlineExceeded(f"{method} {netloc}{path}")
        if rem < timeout:
            timeout = rem
            budget_shrunk = True
        merged = dict(headers) if headers else {}
        merged[deadline.HEADER] = f"{rem:.4f}"
        headers = merged
    if _qos_tenant is not None:
        _t = _qos_tenant.get()
        if _t is not None and not (headers and
                                   _TENANT_HEADER in headers):
            merged = dict(headers) if headers else {}
            merged[_TENANT_HEADER] = _t
            headers = merged
    tsp = None
    if _ctrace._enabled:
        from seaweedfs_tpu.stats import trace as _trace
        if _trace.request_ctx() is not None:
            # client-side hop span opened FIRST so the remote request
            # span (minted by the peer's ingress wrapper from this
            # header) nests under it in the stitched view
            tsp = _trace.Span("http.client",  None,
                              {"peer": netloc, "method": method})
            tsp.__enter__()
            merged = dict(headers) if headers else {}
            merged[_ctrace.HEADER] = _ctrace.outbound_header()
            headers = merged
    try:
        if breaker.enabled:
            breaker.check(netloc)   # raises BreakerOpen while open
        try:
            resp = _request_once_retried(netloc, path, method, body,
                                         headers, timeout, pooled)
        except deadline.DeadlineExceeded:
            # a spent budget says nothing about the PEER's health
            raise
        except OSError as e:
            # ...and neither does a timeout the budget SHRANK below the
            # caller's own: a healthy-but-slower-than-the-budget peer
            # must not have its breaker opened by impatient clients
            if breaker.enabled and not (budget_shrunk and
                                        isinstance(e, RequestTimeout)):
                breaker.record(netloc, False)
            raise
        if breaker.enabled:
            breaker.record(netloc, True)
        if busy_raises and resp.status in (429, 503):
            raise ServerBusy(
                f"{method} {netloc}{path}: {resp.status} busy",
                status=resp.status,
                retry_after=retry_after_seconds(resp))
        if failpoint._armed:
            resp.body = failpoint.mangle("http.response", resp.body,
                                         peer=netloc,
                                         status=str(resp.status))
        return resp
    finally:
        if tsp is not None:
            tsp.__exit__(None, None, None)


def _request_once_retried(netloc: str, path: str, method: str,
                          body: Optional[bytes], headers: Optional[dict],
                          timeout: float, pooled: bool) -> Response:
    reuse_ok = pooled
    for attempt in (0, 1):
        if reuse_ok:
            conn, reused = _get_conn(netloc, timeout)
        else:
            conn, reused = _Conn(netloc, timeout), False
        try:
            resp, keep = _roundtrip(conn, netloc, method, path, body,
                                    headers)
        except _StaleConnection as e:
            # retry ONLY when the pooled connection died before the
            # server can have processed the request (clean close before
            # the first response byte, or the send itself failing) —
            # never on timeouts or mid-response failures, which would
            # re-execute a request the server already ran (Go's
            # net/http draws the same line)
            conn.close()
            if not (reused and e.retryable) or attempt == 1:
                raise
            from seaweedfs_tpu.stats.metrics import \
                HttpPoolStaleRetryCounter
            HttpPoolStaleRetryCounter.inc()
            reuse_ok = False
            continue
        except TimeoutError as e:
            # typed for retry classification: the peer may have run the
            # request, so this is never blind-replayed
            conn.close()
            raise RequestTimeout(
                f"{method} {netloc}{path}: {e or 'timed out'}") from e
        except OSError:
            conn.close()
            raise
        if keep and pooled:
            _put_conn(conn)
        else:
            conn.close()
        return resp
    raise RuntimeError("unreachable")


def retry_after_seconds(resp: "Response") -> float:
    """The Retry-After header as seconds (delta-seconds grammar; the
    HTTP-date form is not spoken on the cluster-internal plane). 0.0
    when absent or unparseable."""
    v = resp.header("retry-after")
    if not v:
        return 0.0
    try:
        return max(0.0, float(v))
    except ValueError:
        return 0.0


def classify(exc: BaseException) -> str:
    """Bucket a data-plane client error for retry decisions and
    metrics: 'deadline' | 'breaker' | 'busy' | 'timeout' | 'connect'
    | 'response' | 'other'."""
    if isinstance(exc, deadline.DeadlineExceeded):
        return "deadline"
    if isinstance(exc, breaker.BreakerOpen):
        return "breaker"
    if isinstance(exc, ServerBusy):
        # the peer answered (alive) and refused (not executed): safe
        # to replay once its Retry-After elapses
        return "busy"
    if isinstance(exc, (RequestTimeout, TimeoutError)):
        return "timeout"
    if isinstance(exc, ConnectError):
        return "connect"
    if isinstance(exc, _StaleConnection) and exc.retryable:
        # retryable=True is the class's own contract that no byte
        # reached the peer — connect-class, safe to replay
        return "connect"
    if isinstance(exc, ResponseError):
        return "response"
    if isinstance(exc, OSError):
        # raw socket errors surface at connect/reuse time; post-send
        # failures are wrapped in _StaleConnection/RequestTimeout above
        return "connect"
    return "other"


class _StaleConnection(ResponseError):
    """Connection-level failure. retryable=True means no response byte
    arrived AND the request cannot have been durably received (safe to
    replay on a fresh connection). Subclasses OSError so callers'
    pre-pooled-client `except OSError` error handling keeps catching
    connection-level failures."""

    def __init__(self, msg, retryable: bool = False):
        super().__init__(msg)
        self.retryable = retryable


def _roundtrip(conn: "_Conn", netloc: str, method: str, path: str,
               body: Optional[bytes],
               headers: Optional[dict]) -> Tuple[Response, bool]:
    buf = [f"{method} {path} HTTP/1.1\r\nHost: {netloc}\r\n"]
    has_len = False
    has_enc = False
    if headers:
        for k, v in headers.items():
            buf.append(f"{k}: {v}\r\n")
            kl = k.lower()
            if kl == "content-length":
                has_len = True
            elif kl == "accept-encoding":
                has_enc = True
    if not has_enc:
        # default to identity (this client never decompresses), but a
        # caller-supplied Accept-Encoding must win — the server parses
        # first-value-wins
        buf.append("Accept-Encoding: identity\r\n")
    if body is not None and not has_len:
        buf.append(f"Content-Length: {len(body)}\r\n")
    elif body is None and method in ("POST", "PUT"):
        buf.append("Content-Length: 0\r\n")
    buf.append("\r\n")
    msg = "".join(buf).encode("latin-1")
    if body:
        msg += body
    try:
        conn.sock.sendall(msg)
    except (BrokenPipeError, ConnectionResetError) as e:
        # the peer closed the idle pooled connection; nothing reached it
        raise _StaleConnection(str(e), retryable=True)

    rfile = conn.rfile
    try:
        line = rfile.readline(_MAX_LINE)
    except ConnectionResetError as e:
        # RST before any response byte on a reused connection is the
        # idle-close race (server dropped the conn as our bytes were in
        # flight); data-plane requests are idempotent by fid, so replay
        raise _StaleConnection(str(e), retryable=True)
    if not line:
        # clean close before any response byte: the server dropped the
        # idle keep-alive connection before our request landed
        raise _StaleConnection(netloc, retryable=True)
    try:
        proto, rest = line.split(None, 1)
        status = int(rest.split(None, 1)[0])
    except (ValueError, IndexError):
        raise _StaleConnection(f"bad status line {line!r}")
    if not proto.startswith(b"HTTP/"):
        raise _StaleConnection(f"bad proto {line!r}")

    hdrs = HeaderDict()
    # same parser as FastHandler.parse_request (first value wins);
    # shared so client and server header handling stay in lockstep
    err = parse_header_block(rfile, hdrs)
    if err is not None:
        raise _StaleConnection(f"bad header block ({err})")

    keep = proto != b"HTTP/1.0"
    conn_hdr = hdrs.get("connection", "").lower()
    if "close" in conn_hdr:
        keep = False
    elif proto == b"HTTP/1.0" and "keep-alive" in conn_hdr:
        keep = True

    # body framing: HEAD and 1xx/204/304 have none regardless of headers
    if method == "HEAD" or status < 200 or status in (204, 304):
        return Response(status, hdrs, b""), keep
    if hdrs.get("transfer-encoding", "").lower().endswith("chunked"):
        data = _read_chunked(rfile)
        return Response(status, hdrs, data), keep
    length = hdrs.get("content-length")
    if length is not None:
        try:
            n = int(length)
        except ValueError:
            raise _StaleConnection(f"bad Content-Length {length!r}")
        data = rfile.read(n) if n else b""
        if len(data) != n:
            raise _StaleConnection("short body")
        return Response(status, hdrs, data), keep
    # no framing: read to close (HTTP/1.0 style)
    data = rfile.read()
    return Response(status, hdrs, data), False


def _read_chunked(rfile) -> bytes:
    parts = []
    while True:
        line = rfile.readline(_MAX_LINE)
        if not line:
            raise _StaleConnection("truncated chunked body")
        try:
            size = int(line.split(b";", 1)[0].strip(), 16)
        except ValueError:
            raise _StaleConnection(f"bad chunk size {line!r}")
        if size == 0:
            # trailers until blank line
            while True:
                t = rfile.readline(_MAX_LINE)
                if t in (b"\r\n", b"\n", b""):
                    break
            return b"".join(parts)
        chunk = rfile.read(size)
        if len(chunk) != size:
            raise _StaleConnection("truncated chunk")
        parts.append(chunk)
        rfile.readline(_MAX_LINE)  # trailing CRLF


def _split(url: str) -> Tuple[str, str]:
    if url.startswith("http://"):
        url = url[7:]
    elif url.startswith("https://"):
        raise ValueError("https data path not supported by the pool")
    slash = url.find("/")
    if slash < 0:
        return url, "/"
    return url[:slash], url[slash:]
