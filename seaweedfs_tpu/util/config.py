"""TOML config loading with the reference's search path
(reference: weed/util/config.go:20-60; viper → tomllib).

`load_configuration("security")` looks for security.toml in ".",
"$HOME/.seaweedfs/", "/usr/local/etc/seaweedfs/", "/etc/seaweedfs/".
Values are addressed viper-style with dotted keys:
`cfg.get("jwt.signing.key")`.
"""

from __future__ import annotations

import os
from typing import Any, List, Optional

try:
    import tomllib
except ImportError:  # py<3.11 without the tomli backport
    try:
        import tomli as tomllib  # type: ignore[no-redef]
    except ImportError:
        tomllib = None  # type: ignore[assignment]

SEARCH_PATH = [
    ".",
    os.path.join(os.path.expanduser("~"), ".seaweedfs"),
    "/usr/local/etc/seaweedfs",
    "/etc/seaweedfs",
]


class Configuration:
    def __init__(self, data: Optional[dict] = None):
        self.data = data or {}

    def get(self, dotted_key: str, default: Any = None) -> Any:
        node: Any = self.data
        for part in dotted_key.split("."):
            if not isinstance(node, dict) or part not in node:
                return default
            node = node[part]
        return node

    def get_string(self, key: str, default: str = "") -> str:
        v = self.get(key, default)
        return str(v) if v is not None else default

    def get_bool(self, key: str, default: bool = False) -> bool:
        return bool(self.get(key, default))

    def sub(self, dotted_key: str) -> "Configuration":
        v = self.get(dotted_key)
        return Configuration(v if isinstance(v, dict) else {})

    def __bool__(self) -> bool:
        return bool(self.data)


def load_configuration(name: str, required: bool = False,
                       search_path: Optional[List[str]] = None) -> Configuration:
    skipped = None
    for d in (search_path or SEARCH_PATH):
        p = os.path.join(d, name + ".toml")
        if os.path.isfile(p):
            if tomllib is None:
                # no TOML parser in this interpreter (py<3.11 without
                # tomli): don't crash every server at startup, but a
                # SKIPPED config can mean security silently off — warn
                # loudly, never silently
                from seaweedfs_tpu.util import wlog
                wlog.logger("config").warning(
                    "%s exists but this interpreter has no TOML parser "
                    "(py<3.11 without tomli); IGNORING it — settings in "
                    "it (including any [jwt]/[grpc] security sections) "
                    "are NOT applied", p)
                skipped = p
                break
            with open(p, "rb") as f:
                return Configuration(tomllib.load(f))
    if required:
        if skipped:
            # the file EXISTS — a "missing file" error would send the
            # operator chasing search paths instead of the parser
            raise RuntimeError(
                f"{skipped} exists but cannot be parsed: this "
                "interpreter has no TOML parser (python <3.11 without "
                "the tomli backport)")
        raise FileNotFoundError(
            f"missing {name}.toml in {search_path or SEARCH_PATH}")
    return Configuration({})
