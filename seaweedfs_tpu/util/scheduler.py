"""Deterministic schedule explorer — systematic interleaving search.

The static `guard` check (seaweedfs_tpu/analysis/guards.py) proves
which lock protects which state; the sanitizer (util/sanitizer.py)
catches lock-order cycles at runtime. Neither can demonstrate an
*atomicity* violation — a check-then-act split across two locked
regions that only corrupts state on one interleaving in a thousand.
This module makes those interleavings enumerable, in the style of
PCT/Coyote: a cooperative scheduler that serializes a small
multi-threaded test onto ONE runnable-at-a-time schedule chosen by a
seeded policy, so

    explore(fn, schedules=50, seed=0)

runs `fn` under 50 distinct deterministic interleavings and
`replay(fn, seed=<failing>)` reproduces a failure exactly — a
one-in-a-thousand CI flake becomes a unit test.

How it interposes (armed only — see the cost contract below): the
`threading.Lock`/`RLock`/`Event`/`Thread` and
`queue.Queue`/`queue.SimpleQueue` factories are swapped for
cooperative wrappers, and `time.sleep` becomes a scheduling point
(virtual time: a sleep never actually sleeps; timeouts fire only when
no other thread can run, which is the deterministic reading of "the
timeout elapsed first"). Every wrapper delegates to the real
primitive unless the calling thread is REGISTERED with the active
run, so background machinery (metrics pushers, pools spawned outside
the test) keeps working untouched. Threads started by a registered
thread during a run are themselves registered — the test's whole
thread tree runs cooperatively, one thread at a time, switching only
at interposition points.

Schedule policies:
  random  at every scheduling point, pick uniformly among runnable
          threads (seeded `random.Random`) — good breadth.
  pct     PCT (probabilistic concurrency testing): threads get random
          priorities; the highest-priority runnable thread runs;
          at d-1 pre-sampled change points the current top thread is
          demoted below everyone. Finds depth-d bugs that need one
          long uninterrupted run plus one precisely-placed preempt —
          the shape uniform-random almost never produces.

Deadlocks don't hang: when every registered thread is blocked and no
timed waiter remains, the run raises DeadlockError naming each
thread's blocked-on resource. Runaway schedules (spin loops) hit
max_steps and raise ScheduleLimitError.

Out of scope, by contract: `Condition.wait` from a registered thread
(raises — restructure the test or leave that seam to the sanitizer)
and synchronizers shared between registered and unregistered threads
(the cooperative and real views of such a primitive can diverge;
keep explored tests self-contained).

Cost contract (house rule, gated by
test_perf_gates.test_scheduler_disabled_overhead): unarmed, importing
this module is one env read — `threading.Lock` stays the stock C
factory and no thread is ever spawned at import. `explore()` arms on
entry and restores the previous factories on exit, so the tree never
pays for exploration it didn't ask for. `SEAWEED_SCHED=1` arms at
import (wrappers in delegate mode until a run starts);
`SEAWEED_SCHED_SCHEDULES` / `SEAWEED_SCHED_SEED` /
`SEAWEED_SCHED_MAX_STEPS` override explore()'s defaults.
"""

from __future__ import annotations

import os
import queue as _queue_mod
import random
import threading
import time as _time_mod
from collections import deque
from typing import Callable, List, Optional

import _thread

__all__ = ["explore", "replay", "arm", "disarm", "armed",
           "ExploreResult", "ScheduleFailure", "DeadlockError",
           "ScheduleLimitError"]


class DeadlockError(RuntimeError):
    """Every registered thread is blocked and no timeout can fire."""


class ScheduleLimitError(RuntimeError):
    """The schedule exceeded max_steps (spin loop in the test?)."""


class _Aborted(BaseException):
    """Internal: unwind a registered thread after its run died."""


class ScheduleFailure(AssertionError):
    """One schedule failed; carries everything that reproduces it
    (seed, policy, AND depth — a pct failure found at depth=2 samples
    different change points under the default, so the printed repro
    must pin it)."""

    def __init__(self, seed: int, policy: str, cause: BaseException,
                 depth: int = 3):
        self.seed = seed
        self.policy = policy
        self.depth = depth
        self.cause = cause
        repro = f"replay(fn, seed={seed}, policy={policy!r}"
        if policy == "pct":
            repro += f", depth={depth}"
        repro += ")"
        super().__init__(
            f"schedule seed={seed} policy={policy} failed: "
            f"{type(cause).__name__}: {cause} — reproduce with "
            f"{repro}")


# -- run/thread state ---------------------------------------------------------

_RUNNABLE, _RUNNING, _BLOCKED, _FINISHED = range(4)


class _TState:
    __slots__ = ("seq", "thread", "gate", "status", "blocked_on",
                 "timed", "wake_reason", "joiners", "priority",
                 "name")

    def __init__(self, seq: int, thread):
        self.seq = seq
        self.thread = thread
        self.name = getattr(thread, "name", f"t{seq}")
        # handed to the thread when it is scheduled; starts held
        self.gate = _thread.allocate_lock()
        self.gate.acquire()
        self.status = _RUNNABLE
        self.blocked_on = ""
        self.timed = False
        self.wake_reason = ""
        self.joiners: List["_TState"] = []
        self.priority = 0.0


class _RandomPolicy:
    name = "random"

    def on_register(self, run: "_Run", ts: _TState) -> None:
        pass

    def pick(self, run: "_Run", cands: List[_TState]) -> _TState:
        return run.rng.choice(cands)


class _PCTPolicy:
    name = "pct"

    def __init__(self, depth: int = 3, horizon: int = 128):
        self.depth = max(1, depth)
        self.horizon = max(2, horizon)
        self.change_points: set = set()
        self._demote = -1.0

    def bind(self, run: "_Run") -> None:
        k = min(self.depth - 1, self.horizon - 1)
        if k > 0:
            self.change_points = set(
                run.rng.sample(range(1, self.horizon), k))

    def on_register(self, run: "_Run", ts: _TState) -> None:
        ts.priority = run.rng.random()

    def pick(self, run: "_Run", cands: List[_TState]) -> _TState:
        if run.step in self.change_points:
            top = max(cands, key=lambda s: (s.priority, -s.seq))
            top.priority = self._demote
            self._demote -= 1.0
        return max(cands, key=lambda s: (s.priority, -s.seq))


class _Run:
    def __init__(self, seed: int, policy, max_steps: int):
        self.mutex = _thread.allocate_lock()
        self.rng = random.Random(seed)
        self.seed = seed
        self.policy = policy
        self.max_steps = max_steps
        self.step = 0
        self.seq = 0
        self.states: List[_TState] = []
        self.failures: List[BaseException] = []
        self.abort: Optional[type] = None   # DeadlockError et al
        self.abort_msg = ""
        # the main thread parks here while late worker threads drain
        self.drain_waiters: List[_TState] = []
        if hasattr(policy, "bind"):
            policy.bind(self)

    # -- registration (run.mutex held or single-threaded) --

    def register(self, thread) -> _TState:
        ts = _TState(self.seq, thread)
        self.seq += 1
        self.states.append(ts)
        self.policy.on_register(self, ts)
        return ts

    # -- core switch machinery --

    def _runnable(self, extra: Optional[_TState] = None
                  ) -> List[_TState]:
        out = [s for s in self.states if s.status == _RUNNABLE]
        if extra is not None:
            out.append(extra)
        return sorted(out, key=lambda s: s.seq)

    def _dispatch(self, ts: _TState) -> None:
        ts.status = _RUNNING
        ts.wake_reason = "go"
        ts.gate.release()

    def _check_abort(self) -> None:
        if self.abort is not None:
            raise self.abort(self.abort_msg)

    def _bump_step(self) -> None:
        self.step += 1
        if self.step > self.max_steps:
            self._trigger_abort(
                ScheduleLimitError,
                f"schedule exceeded {self.max_steps} steps — "
                "spin loop under exploration?")
            raise self.abort(self.abort_msg)

    def _trigger_abort(self, exc_type, msg: str) -> None:
        """mutex held: poison the run and wake every blocked thread so
        each unwinds with the abort instead of hanging."""
        if self.abort is None:
            self.abort = exc_type
            self.abort_msg = msg
        # wake BLOCKED and RUNNABLE threads alike: both are parked on
        # their gate (a never-yet-scheduled thread included) and would
        # otherwise leak as zombies when the run unwinds
        for s in self.states:
            if s.status in (_BLOCKED, _RUNNABLE):
                s.status = _RUNNING
                s.wake_reason = "abort"
                s.gate.release()

    def yield_point(self, ts: _TState) -> None:
        """Non-blocking scheduling point: the policy may preempt."""
        nxt = None
        with self.mutex:
            self._check_abort()
            self._bump_step()
            cands = self._runnable(extra=ts)
            chosen = self.policy.pick(self, cands)
            if chosen is not ts:
                ts.status = _RUNNABLE
                self._dispatch(chosen)
                nxt = chosen
        if nxt is not None:
            ts.gate.acquire()
            if self.abort is not None and ts.wake_reason == "abort":
                raise self.abort(self.abort_msg)

    def block(self, ts: _TState, waiters: Optional[List[_TState]],
              what: str, timed: bool) -> str:
        """Blocking scheduling point; returns the wake reason:
        'go' (resource event) or 'timeout' (virtual time fired)."""
        with self.mutex:
            self._check_abort()
            self._bump_step()
            ts.status = _BLOCKED
            ts.blocked_on = what
            ts.timed = timed
            if waiters is not None:
                waiters.append(ts)
            self._schedule_next()
        ts.gate.acquire()
        if self.abort is not None and ts.wake_reason == "abort":
            raise self.abort(self.abort_msg)
        return ts.wake_reason

    def _schedule_next(self) -> None:
        """mutex held: hand the token onward after the current thread
        blocked or finished."""
        cands = self._runnable()
        if cands:
            self._dispatch(self.policy.pick(self, cands))
            return
        timed = sorted((s for s in self.states
                        if s.status == _BLOCKED and s.timed),
                       key=lambda s: s.seq)
        if timed:
            # virtual time advances only when nothing else can run:
            # the policy-chosen timed waiter sees its timeout fire
            chosen = self.policy.pick(self, timed)
            chosen.timed = False
            chosen.status = _RUNNING
            chosen.wake_reason = "timeout"
            chosen.gate.release()
            return
        blocked = [s for s in self.states if s.status == _BLOCKED]
        if blocked:
            self._trigger_abort(DeadlockError,
                                "all threads blocked: " + "; ".join(
                                    f"{s.name} on {s.blocked_on}"
                                    for s in blocked))
        # else: every thread finished — nothing to do

    def wake(self, waiters: List[_TState]) -> None:
        """mutex held: a resource event makes its waiters runnable
        (they still wait to be SCHEDULED — this is not a dispatch)."""
        for s in waiters:
            if s.status == _BLOCKED:
                s.status = _RUNNABLE
                s.timed = False
        del waiters[:]

    def finish_thread(self, ts: _TState) -> None:
        with self.mutex:
            ts.status = _FINISHED
            self.wake(ts.joiners)
            self.wake(self.drain_waiters)
            self._schedule_next()


# -- arming: factory interposition -------------------------------------------

_armed = False
_RUN: Optional[_Run] = None
_tls = threading.local()

_PREV: dict = {}


def armed() -> bool:
    return _armed


def _state() -> Optional[_TState]:
    return getattr(_tls, "state", None)


def _ctx():
    """(run, tstate) when the CALLING thread is registered with the
    active run; (None, None) otherwise — the delegate-mode check every
    wrapper makes first."""
    run = _RUN
    if run is None:
        return None, None
    st = _state()
    if st is None:
        return None, None
    return run, st


class _SchedLock:
    """Cooperative Lock: logical ownership for registered threads, a
    real lock (built from the pre-arm factory) for everyone else."""

    _reentrant = False

    def __init__(self, real_factory):
        self._real = real_factory()
        self._owner: Optional[_TState] = None
        self._depth = 0
        self._waiters: List[_TState] = []

    def acquire(self, blocking: bool = True, timeout: float = -1):
        run, st = _ctx()
        if st is None:
            if timeout is None or timeout < 0:
                return self._real.acquire(blocking)
            return self._real.acquire(blocking, timeout)
        run.yield_point(st)          # preemption before the CS
        while True:
            with run.mutex:
                if self._owner is None:
                    self._owner = st
                    self._depth = 1
                    return True
                if self._owner is st and self._reentrant:
                    self._depth += 1
                    return True
            if not blocking:
                return False
            r = run.block(st, self._waiters, f"lock {id(self):#x}",
                          timed=timeout is not None and timeout >= 0)
            if r == "timeout":
                return False

    def release(self) -> None:
        run, st = _ctx()
        if st is None:
            self._real.release()
            return
        with run.mutex:
            if self._owner is not st:
                raise RuntimeError("release of unacquired sched lock")
            self._depth -= 1
            if self._depth > 0:
                return
            self._owner = None
            run.wake(self._waiters)
        run.yield_point(st)          # preemption after the CS

    __enter__ = acquire

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        run, st = _ctx()
        if st is None:
            return self._real.locked()
        # lint: guard-ok(introspection peek; cooperative threads serialize on the run token)
        return self._owner is not None

    def _at_fork_reinit(self) -> None:
        if hasattr(self._real, "_at_fork_reinit"):
            self._real._at_fork_reinit()
        # lint: guard-ok(fork re-init runs single-threaded in the child)
        self._owner = None
        # lint: guard-ok(fork re-init runs single-threaded in the child)
        self._depth = 0
        del self._waiters[:]

    # Condition's private protocol, on BOTH lock flavors (a Condition
    # built over a plain Lock reaches these too — leaving them off the
    # base class made cv.wait() park a registered thread on a raw
    # waiter lock while it still held the scheduling token, hanging
    # the whole run with no DeadlockError; review finding). Supported
    # in delegate mode only: a registered thread raises instead.
    def _release_save(self):
        run, st = _ctx()
        if st is not None:
            raise RuntimeError(
                "Condition.wait on a scheduler-wrapped lock inside an "
                "explored run is not supported — restructure the test "
                "around Event/Queue, or leave this seam to the "
                "sanitizer")
        if hasattr(self._real, "_release_save"):
            return self._real._release_save()
        self._real.release()       # plain-Lock default protocol
        return None

    def _acquire_restore(self, state) -> None:
        if hasattr(self._real, "_acquire_restore"):
            self._real._acquire_restore(state)
        else:
            self._real.acquire()

    def _is_owned(self) -> bool:
        run, st = _ctx()
        if st is not None:
            # lint: guard-ok(cooperative ownership peek; only the token-holding thread reaches here)
            return self._owner is st
        if hasattr(self._real, "_is_owned"):
            return self._real._is_owned()
        # plain-Lock default: owned iff a non-blocking acquire fails
        if self._real.acquire(False):
            self._real.release()
            return False
        return True


class _SchedRLock(_SchedLock):
    _reentrant = True


class _SchedEvent:
    """Cooperative Event; delegate mode is a textbook flag+condition
    over pre-arm primitives."""

    def __init__(self):
        self._flag = False
        self._real_cv = _PREV["Condition"](_PREV["Lock"]())
        self._waiters: List[_TState] = []

    def is_set(self) -> bool:
        return self._flag

    isSet = is_set

    def set(self) -> None:
        run, st = _ctx()
        with self._real_cv:
            self._flag = True
            self._real_cv.notify_all()
        if st is not None:
            with run.mutex:
                run.wake(self._waiters)
            run.yield_point(st)

    def clear(self) -> None:
        with self._real_cv:
            self._flag = False

    def wait(self, timeout: Optional[float] = None) -> bool:
        run, st = _ctx()
        if st is None:
            with self._real_cv:
                if not self._flag:
                    self._real_cv.wait(timeout)
                return self._flag
        run.yield_point(st)
        while not self._flag:
            r = run.block(st, self._waiters, "event.wait",
                          timed=timeout is not None)
            if r == "timeout":
                return self._flag
        return True


class _SchedQueue:
    """Cooperative queue.Queue/SimpleQueue stand-in: one deque is the
    single source of truth for both modes."""

    def __init__(self, maxsize: int = 0):
        self.maxsize = maxsize
        self._items: deque = deque()
        self._real_cv = _PREV["Condition"](_PREV["Lock"]())
        self._getters: List[_TState] = []
        self._putters: List[_TState] = []
        self._joiners: List[_TState] = []
        self._unfinished = 0

    def _full(self) -> bool:
        # lint: guard-ok(len peek is GIL-atomic; put/get re-check under their mode's lock)
        return 0 < self.maxsize <= len(self._items)

    def qsize(self) -> int:
        # lint: guard-ok(introspection; len peek is GIL-atomic and may be stale)
        return len(self._items)

    def empty(self) -> bool:
        # lint: guard-ok(introspection; truthiness peek is GIL-atomic and may be stale)
        return not self._items

    def full(self) -> bool:
        return self._full()

    def _wait_real(self, endtime: Optional[float]) -> bool:
        """One delegate-mode condition wait against a DEADLINE, not a
        restarted timeout — a wakeup that loses the race to a sibling
        must not reset the clock (queue.Queue semantics)."""
        if endtime is None:
            self._real_cv.wait()
            return True
        remaining = endtime - _time_mod.monotonic()
        if remaining <= 0:
            return False
        return self._real_cv.wait(remaining)

    def put(self, item, block: bool = True,
            timeout: Optional[float] = None) -> None:
        run, st = _ctx()
        if st is None:
            endtime = None if timeout is None \
                else _time_mod.monotonic() + timeout
            with self._real_cv:
                while self._full():
                    if not block or not self._wait_real(endtime):
                        raise _queue_mod.Full
                self._items.append(item)
                self._unfinished += 1
                self._real_cv.notify_all()
            return
        run.yield_point(st)
        while True:
            with run.mutex:
                if not self._full():
                    self._items.append(item)
                    self._unfinished += 1
                    run.wake(self._getters)
                    break
            if not block:
                raise _queue_mod.Full
            r = run.block(st, self._putters, "queue.put",
                          timed=timeout is not None)
            if r == "timeout":
                raise _queue_mod.Full
        with self._real_cv:
            self._real_cv.notify_all()

    def put_nowait(self, item) -> None:
        self.put(item, block=False)

    def get(self, block: bool = True,
            timeout: Optional[float] = None):
        run, st = _ctx()
        if st is None:
            endtime = None if timeout is None \
                else _time_mod.monotonic() + timeout
            with self._real_cv:
                while not self._items:
                    if not block or not self._wait_real(endtime):
                        raise _queue_mod.Empty
                item = self._items.popleft()
                self._real_cv.notify_all()
                return item
        run.yield_point(st)
        while True:
            with run.mutex:
                if self._items:
                    item = self._items.popleft()
                    run.wake(self._putters)
                    return item
            if not block:
                raise _queue_mod.Empty
            r = run.block(st, self._getters, "queue.get",
                          timed=timeout is not None)
            if r == "timeout":
                raise _queue_mod.Empty

    def get_nowait(self):
        return self.get(block=False)

    def task_done(self) -> None:
        run, st = _ctx()
        with self._real_cv:
            # lint: guard-ok(count mutates under _real_cv in delegate mode and under the run token cooperatively)
            self._unfinished = max(0, self._unfinished - 1)
            # lint: guard-ok(read under _real_cv; cooperative mutators hold the run token besides)
            done = self._unfinished == 0
            if done:
                self._real_cv.notify_all()
        if st is not None and done:
            with run.mutex:
                run.wake(self._joiners)
            run.yield_point(st)

    def join(self) -> None:
        """Block until every put() has a matching task_done() —
        queue.Queue semantics in both modes (cooperative block under a
        run, condition wait in delegate mode)."""
        run, st = _ctx()
        if st is None:
            with self._real_cv:
                # lint: guard-ok(read under _real_cv, the delegate-mode count lock)
                while self._unfinished:
                    self._real_cv.wait()
            return
        run.yield_point(st)
        # lint: guard-ok(cooperative re-check; task_done wakes _joiners when the count hits zero)
        while self._unfinished:
            run.block(st, self._joiners, "queue.join", timed=False)


def _make_sched_thread(orig_thread_cls):
    class _SchedThread(orig_thread_cls):
        _sched_ts: Optional[_TState] = None

        def start(self) -> None:
            run, st = _ctx()
            if st is None:
                super().start()
                return
            with run.mutex:
                self._sched_ts = run.register(self)
            # the _started handshake inside Thread.start() is an Event
            # set by the NEW OS thread before it reaches our gate —
            # run it in delegate mode (real event) or the cooperative
            # wait would park this thread where only real signaling
            # exists. No user code runs in that window, so schedule
            # determinism is unaffected.
            _tls.state = None
            try:
                super().start()
            finally:
                _tls.state = st
            run.yield_point(st)   # the new thread is now schedulable

        def run(self) -> None:
            ts = self._sched_ts
            if ts is None:
                super().run()
                return
            run = _RUN
            _tls.state = ts
            ts.gate.acquire()     # wait to be scheduled the first time
            try:
                if ts.wake_reason == "abort" and run is not None \
                        and run.abort is not None:
                    raise _Aborted
                super().run()
            except _Aborted:
                pass
            except BaseException as e:  # noqa: BLE001 - recorded, surfaces as the schedule's failure
                if run is not None and not isinstance(
                        e, (DeadlockError, ScheduleLimitError)):
                    run.failures.append(e)
            finally:
                _tls.state = None
                if run is not None:
                    run.finish_thread(ts)

        def join(self, timeout: Optional[float] = None) -> None:
            run, st = _ctx()
            ts = self._sched_ts
            if st is None or ts is None:
                super().join(timeout)
                return
            run.yield_point(st)
            while ts.status != _FINISHED:
                r = run.block(st, ts.joiners, f"join {self.name}",
                              timed=timeout is not None)
                if r == "timeout":
                    return
            super().join()        # the OS thread is already exiting

    return _SchedThread


def _sched_sleep(seconds: float) -> None:
    run, st = _ctx()
    if st is None:
        _PREV["sleep"](seconds)
        return
    # virtual time: a sleep is a scheduling point, never a real wait
    run.yield_point(st)


def arm() -> None:
    """Swap the factories for cooperative wrappers (delegate mode
    until a run starts). explore() calls this on entry; SEAWEED_SCHED=1
    does it at import."""
    global _armed
    if _armed:
        return
    _PREV.update(
        Lock=threading.Lock, RLock=threading.RLock,
        Event=threading.Event, Thread=threading.Thread,
        Condition=threading.Condition,
        Queue=_queue_mod.Queue, SimpleQueue=_queue_mod.SimpleQueue,
        sleep=_time_mod.sleep)
    _armed = True
    prev_lock, prev_rlock = _PREV["Lock"], _PREV["RLock"]
    threading.Lock = lambda: _SchedLock(prev_lock)
    threading.RLock = lambda: _SchedRLock(prev_rlock)
    threading.Event = _SchedEvent
    threading.Thread = _make_sched_thread(_PREV["Thread"])
    _queue_mod.Queue = _SchedQueue
    _queue_mod.SimpleQueue = _SchedQueue
    _time_mod.sleep = _sched_sleep


def disarm() -> None:
    """Restore the pre-arm factories. Wrapper objects created while
    armed keep working (they delegate once no run is active)."""
    global _armed
    if not _armed:
        return
    _armed = False
    threading.Lock = _PREV["Lock"]
    threading.RLock = _PREV["RLock"]
    threading.Event = _PREV["Event"]
    threading.Thread = _PREV["Thread"]
    _queue_mod.Queue = _PREV["Queue"]
    _queue_mod.SimpleQueue = _PREV["SimpleQueue"]
    _time_mod.sleep = _PREV["sleep"]


# -- the public exploration API ----------------------------------------------


class ExploreResult:
    def __init__(self, schedules: int, policy: str):
        self.schedules = schedules
        self.policy = policy
        self.failures: List[ScheduleFailure] = []

    @property
    def ok(self) -> bool:
        return not self.failures

    def __repr__(self) -> str:
        return (f"<ExploreResult {self.policy} "
                f"{self.schedules - len(self.failures)}/"
                f"{self.schedules} ok>")


def _policy_for(policy: str, depth: int):
    if policy == "random":
        return _RandomPolicy()
    if policy == "pct":
        return _PCTPolicy(depth=depth)
    raise ValueError(f"unknown schedule policy {policy!r}")


def _run_one(fn: Callable[[], None], seed: int, policy: str,
             depth: int, max_steps: int) -> Optional[BaseException]:
    """One schedule: returns the failure (or None). Must be called
    armed; arms the run for the duration of fn()."""
    global _RUN
    if _RUN is not None:
        raise RuntimeError("explore() does not nest")
    run = _Run(seed, _policy_for(policy, depth), max_steps)
    st = run.register(threading.current_thread())
    st.status = _RUNNING
    _tls.state = st
    _RUN = run
    failure: Optional[BaseException] = None
    try:
        fn()
        # drain: let every spawned thread run to completion so the
        # next schedule starts clean
        while True:
            with run.mutex:
                alive = [s for s in run.states
                         if s is not st and s.status != _FINISHED]
                if not alive:
                    break
            run.block(st, run.drain_waiters, "drain", timed=False)
    except (DeadlockError, ScheduleLimitError, _Aborted) as e:
        failure = e if not isinstance(e, _Aborted) else None
        _drain_abort(run, st)
    except BaseException as e:  # noqa: BLE001 - the schedule's verdict, re-raised by the caller
        failure = e
        _drain_abort(run, st)
    finally:
        _tls.state = None
        _RUN = None
    if failure is None and run.failures:
        failure = run.failures[0]
    if failure is None and run.abort is not None:
        failure = run.abort(run.abort_msg)
    return failure


def _drain_abort(run: _Run, st: _TState) -> None:
    """The main thread is unwinding: poison the run so blocked workers
    raise instead of hanging, then wait for the OS threads to exit."""
    with run.mutex:
        run._trigger_abort(
            run.abort or _Aborted,
            run.abort_msg or "schedule unwound by a main-thread "
            "failure")
    for s in run.states:
        if s is not st:
            try:
                # real join (bypassing the cooperative override):
                # every worker either finished or is unwinding on the
                # abort it was just woken with
                _PREV["Thread"].join.__get__(s.thread)(5.0)
            except RuntimeError:
                pass   # never started


def explore(fn: Callable[[], None], schedules: Optional[int] = None,
            seed: Optional[int] = None, policy: str = "random",
            depth: int = 3, max_steps: Optional[int] = None,
            check: bool = True) -> ExploreResult:
    """Run `fn` under `schedules` deterministic interleavings (seeds
    seed, seed+1, ...). With check=True (default) the first failing
    schedule raises ScheduleFailure carrying its seed; check=False
    returns the full ExploreResult instead."""
    schedules = int(os.environ.get("SEAWEED_SCHED_SCHEDULES", "20")) \
        if schedules is None else schedules
    seed = int(os.environ.get("SEAWEED_SCHED_SEED", "0")) \
        if seed is None else seed
    max_steps = int(os.environ.get("SEAWEED_SCHED_MAX_STEPS", "20000")) \
        if max_steps is None else max_steps
    result = ExploreResult(schedules, policy)
    was_armed = _armed
    arm()
    try:
        for i in range(schedules):
            failure = _run_one(fn, seed + i, policy, depth, max_steps)
            if failure is not None:
                sf = ScheduleFailure(seed + i, policy, failure,
                                     depth=depth)
                result.failures.append(sf)
                if check:
                    raise sf from failure
    finally:
        if not was_armed:
            disarm()
    return result


def replay(fn: Callable[[], None], seed: int, policy: str = "random",
           depth: int = 3, max_steps: Optional[int] = None) -> None:
    """Deterministically re-run the single schedule `seed` — the
    repro command ScheduleFailure prints. Raises the failure."""
    explore(fn, schedules=1, seed=seed, policy=policy, depth=depth,
            max_steps=max_steps, check=True)


if os.environ.get("SEAWEED_SCHED"):
    arm()
