"""Minimal S3-compatible REST client (SigV4) on the standard library.

The reference pulls in aws-sdk-go for its S3 cloud-tier backend and
replication sink (weed/storage/backend/s3_backend/s3_backend.go,
weed/replication/sink/s3sink); this image has no boto3, so the same
wire protocol is implemented directly: AWS Signature Version 4 over
plain HTTP requests. It is enough for object CRUD + ranged reads +
prefix listing against any S3-compatible endpoint — including this
package's own s3api gateway, which the tests use as the server side.
"""

from __future__ import annotations

import hashlib
import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET
from typing import Dict, Iterator, List, Optional, Tuple

from seaweedfs_tpu.util.aws_auth import canonical_query, sigv4_headers


class S3Error(Exception):
    def __init__(self, status: int, body: str = ""):
        super().__init__(f"S3 request failed: HTTP {status} {body[:200]}")
        self.status = status
        self.body = body


class S3Client:
    """One endpoint + credential pair; methods map 1:1 to S3 REST ops."""

    def __init__(self, endpoint: str, access_key: str, secret_key: str,
                 region: str = "us-east-1", timeout: float = 60.0):
        # endpoint is "host:port" (path-style addressing, like the
        # reference's ForcePathStyle for non-AWS endpoints)
        self.endpoint = endpoint.replace("http://", "").rstrip("/")
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.timeout = timeout

    # -- SigV4 ---------------------------------------------------------------

    def _sign(self, method: str, path: str, query: List[Tuple[str, str]],
              headers: Dict[str, str], payload: bytes,
              payload_hash: Optional[str] = None) -> Dict[str, str]:
        return sigv4_headers(method, self.endpoint, path, query, headers,
                             payload, self.access_key, self.secret_key,
                             self.region, "s3", payload_hash=payload_hash)

    def _request(self, method: str, path: str,
                 query: Optional[List[Tuple[str, str]]] = None,
                 headers: Optional[Dict[str, str]] = None,
                 payload: bytes = b"") -> Tuple[int, Dict[str, str], bytes]:
        query = query or []
        headers = dict(headers or {})
        signed = self._sign(method, path, query, headers, payload)
        # the SAME encoder (and order) as the canonical query string:
        # urlencode's quote_plus turns spaces into '+', which strict
        # SigV4 servers reject as SignatureDoesNotMatch
        qs = canonical_query(query)
        url = f"http://{self.endpoint}{urllib.parse.quote(path)}" + \
            (f"?{qs}" if qs else "")
        req = urllib.request.Request(url, data=payload or None,
                                     method=method, headers=signed)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return r.status, dict(r.headers), r.read()
        except urllib.error.HTTPError as e:
            body = e.read().decode("utf-8", "replace")
            raise S3Error(e.code, body) from None

    # -- object ops ----------------------------------------------------------

    def put_object(self, bucket: str, key: str, data: bytes,
                   content_type: str = "application/octet-stream") -> str:
        status, headers, _ = self._request(
            "PUT", f"/{bucket}/{key}", payload=data,
            headers={"content-type": content_type})
        return headers.get("ETag", "").strip('"')

    def get_object(self, bucket: str, key: str,
                   byte_range: Optional[Tuple[int, int]] = None) -> bytes:
        headers = {}
        if byte_range is not None:
            headers["range"] = f"bytes={byte_range[0]}-{byte_range[1]}"
        _, _, body = self._request("GET", f"/{bucket}/{key}",
                                   headers=headers)
        return body

    def head_object(self, bucket: str, key: str) -> Optional[Dict[str, str]]:
        try:
            _, headers, _ = self._request("HEAD", f"/{bucket}/{key}")
            return headers
        except S3Error as e:
            if e.status == 404:
                return None
            raise

    def delete_object(self, bucket: str, key: str) -> None:
        try:
            self._request("DELETE", f"/{bucket}/{key}")
        except S3Error as e:
            if e.status != 404:
                raise

    def create_bucket(self, bucket: str) -> None:
        try:
            self._request("PUT", f"/{bucket}")
        except S3Error as e:
            if e.status not in (409,):  # BucketAlreadyExists is fine
                raise

    def list_objects(self, bucket: str, prefix: str = "",
                     max_keys: int = 1000) -> Iterator[Dict[str, str]]:
        token = ""
        while True:
            query = [("list-type", "2"), ("prefix", prefix),
                     ("max-keys", str(max_keys))]
            if token:
                query.append(("continuation-token", token))
            _, _, body = self._request("GET", f"/{bucket}", query=query)
            root = ET.fromstring(body)
            ns = ""
            if root.tag.startswith("{"):
                ns = root.tag[:root.tag.index("}") + 1]
            for item in root.findall(f"{ns}Contents"):
                yield {
                    "key": item.findtext(f"{ns}Key", ""),
                    "size": int(item.findtext(f"{ns}Size", "0")),
                    "etag": item.findtext(f"{ns}ETag", "").strip('"'),
                }
            if root.findtext(f"{ns}IsTruncated", "false") != "true":
                return
            token = root.findtext(f"{ns}NextContinuationToken", "")
            if not token:
                return

    def upload_file(self, local_path: str, bucket: str, key: str,
                    chunk: int = 8 << 20, progress=None) -> int:
        """Streaming whole-object PUT: one hashing pass (SigV4 needs
        the payload sha256 up front), then the body streams from the
        file — a multi-GB sealed .dat never sits in memory."""
        import os as _os
        size = _os.path.getsize(local_path)
        h = hashlib.sha256()
        with open(local_path, "rb") as f:
            for blk in iter(lambda: f.read(chunk), b""):
                h.update(blk)
        path = f"/{bucket}/{key}"
        headers = {"content-type": "application/octet-stream",
                   "content-length": str(size)}
        signed = self._sign("PUT", path, [], headers, b"",
                            payload_hash=h.hexdigest())
        url = f"http://{self.endpoint}{urllib.parse.quote(path)}"
        body = open(local_path, "rb")
        try:
            req = urllib.request.Request(url, data=body, method="PUT",
                                         headers=signed)
            try:
                with urllib.request.urlopen(req, timeout=self.timeout):
                    pass
            except urllib.error.HTTPError as e:
                raise S3Error(e.code,
                              e.read().decode("utf-8", "replace")) from None
        finally:
            body.close()
        if progress:
            progress(size)
        return size

    def download_file(self, bucket: str, key: str, local_path: str,
                      chunk: int = 8 << 20, progress=None) -> int:
        """Streaming GET straight to disk."""
        path = f"/{bucket}/{key}"
        signed = self._sign("GET", path, [], {}, b"")
        url = f"http://{self.endpoint}{urllib.parse.quote(path)}"
        req = urllib.request.Request(url, method="GET", headers=signed)
        total = 0
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r, \
                    open(local_path, "wb") as out:
                for blk in iter(lambda: r.read(chunk), b""):
                    out.write(blk)
                    total += len(blk)
                    if progress:
                        progress(len(blk))
        except urllib.error.HTTPError as e:
            raise S3Error(e.code,
                          e.read().decode("utf-8", "replace")) from None
        return total
