"""AWS Signature Version 4 on the standard library — shared by every
client that speaks an AWS wire protocol without an SDK: the S3 client
(util/s3_client.py), the SQS notification queue (notification/aws_sqs),
and the cloud replication sinks.

Reference counterpart: the aws-sdk-go signer the Go code relies on
(weed/replication/sink/s3sink, weed/notification/aws_sqs).
"""

from __future__ import annotations

import hashlib
import hmac
import time
import urllib.parse
from typing import Dict, List, Optional, Tuple


def uri_encode(s: str, encode_slash: bool = True) -> str:
    safe = "-_.~" if encode_slash else "-_.~/"
    return urllib.parse.quote(s, safe=safe)


def canonical_query(query: List[Tuple[str, str]]) -> str:
    return "&".join(f"{uri_encode(k)}={uri_encode(v)}"
                    for k, v in sorted(query))


def sigv4_headers(method: str, host: str, path: str,
                  query: List[Tuple[str, str]],
                  headers: Dict[str, str], payload: bytes,
                  access_key: str, secret_key: str,
                  region: str, service: str,
                  payload_hash: Optional[str] = None) -> Dict[str, str]:
    """Lower-cased headers dict including host/x-amz-date/
    x-amz-content-sha256/authorization, ready to send."""
    t = time.gmtime()
    amz_date = time.strftime("%Y%m%dT%H%M%SZ", t)
    date = time.strftime("%Y%m%d", t)
    if payload_hash is None:
        payload_hash = hashlib.sha256(payload).hexdigest()
    h = {k.lower(): str(v) for k, v in headers.items()}
    h["host"] = host
    h["x-amz-date"] = amz_date
    h["x-amz-content-sha256"] = payload_hash
    signed = sorted(h)
    canonical = "\n".join([
        method,
        uri_encode(path, encode_slash=False),
        canonical_query(query),
        "".join(f"{k}:{' '.join(h[k].split())}\n" for k in signed),
        ";".join(signed),
        payload_hash,
    ])
    scope = f"{date}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        hashlib.sha256(canonical.encode()).hexdigest()])

    def hm(key: bytes, msg: str) -> bytes:
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    k = hm(("AWS4" + secret_key).encode(), date)
    k = hm(k, region)
    k = hm(k, service)
    k = hm(k, "aws4_request")
    signature = hmac.new(k, string_to_sign.encode(),
                         hashlib.sha256).hexdigest()
    h["authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
        f"SignedHeaders={';'.join(signed)}, Signature={signature}")
    return h
