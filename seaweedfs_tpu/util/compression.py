"""Compression-aware data path (reference: weed/util/compression.go).

Stored blobs may be gzipped (or zstd'd) at upload time; the read path
serves compressed bytes directly when the client accepts the encoding,
else decompresses on the fly.
"""

from __future__ import annotations

import gzip
from typing import Tuple

try:
    import zstandard as _zstd
except ImportError:  # pragma: no cover - zstandard is in the image
    _zstd = None

GZIP_MAGIC = b"\x1f\x8b"
ZSTD_MAGIC = b"\x28\xb5\x2f\xfd"

_UNCOMPRESSABLE_EXT = {
    ".zip", ".rar", ".gz", ".bz2", ".xz", ".zst", ".br",
    ".jpg", ".jpeg", ".png", ".gif", ".webp", ".heic",
    ".mp3", ".mp4", ".m4a", ".mkv", ".avi", ".mov", ".ogg",
    ".7z", ".woff", ".woff2",
}

_COMPRESSABLE_EXT = {
    ".txt", ".htm", ".html", ".css", ".js", ".json", ".xml", ".csv",
    ".svg", ".md", ".log", ".conf", ".toml", ".yaml", ".yml", ".pdf",
    ".go", ".py", ".java", ".c", ".cc", ".cpp", ".h", ".ts",
}


def is_gzipped(data: bytes) -> bool:
    return data[:2] == GZIP_MAGIC


def is_zstd(data: bytes) -> bool:
    return data[:4] == ZSTD_MAGIC


def is_compressed(data: bytes) -> bool:
    return is_gzipped(data) or is_zstd(data)


def can_be_compressed(ext: str, mime: str) -> bool:
    """Should this payload be gzip'd before storing?
    Mirrors util.IsCompressableFileType (compression.go)."""
    ext = ext.lower()
    if ext in _UNCOMPRESSABLE_EXT:
        return False
    if ext in _COMPRESSABLE_EXT:
        return True
    if mime.startswith("text/") or mime in (
            "application/json", "application/xml", "application/javascript",
            "application/x-javascript", "image/svg+xml"):
        return True
    if mime.startswith(("image/", "video/", "audio/")):
        return False
    return False


def compress(data: bytes, method: str = "gzip", level: int = 3) -> bytes:
    if method == "zstd" and _zstd is not None:
        return _zstd.ZstdCompressor(level=level).compress(data)
    return gzip.compress(data, compresslevel=level)


def maybe_compress(data: bytes, ext: str = "", mime: str = "") -> Tuple[bytes, bool]:
    """Compress if worthwhile; returns (stored_bytes, is_compressed)."""
    if len(data) < 128 or is_compressed(data):
        return data, False
    if not can_be_compressed(ext, mime):
        return data, False
    out = compress(data)
    if len(out) >= len(data):
        return data, False
    return out, True


def decompress(data: bytes) -> bytes:
    if is_gzipped(data):
        return gzip.decompress(data)
    if is_zstd(data):
        if _zstd is None:  # pragma: no cover
            raise ValueError("zstd data but zstandard module unavailable")
        return _zstd.ZstdDecompressor().decompress(data)
    return data
