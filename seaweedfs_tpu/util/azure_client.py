"""Minimal Azure Blob REST client with SharedKey auth (stdlib only).

The reference pulls in azure-storage-blob-go for its Azure replication
sink (weed/replication/sink/azuresink/azure_sink.go); SharedKey is
just HMAC-SHA256 over a canonicalized request (the same class of
client as util/s3_client's SigV4), so the sink needs no SDK.

Covers Put/Get/Delete Blob and container listing — the operations the
replication sink uses. `endpoint` may point at a local emulator for
tests; production default is https://<account>.blob.core.windows.net.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import time
import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET
from email.utils import formatdate
from typing import Dict, Iterator, List, Optional, Tuple

API_VERSION = "2019-12-12"


class AzureError(Exception):
    def __init__(self, status: int, body: str = ""):
        super().__init__(f"Azure request failed: HTTP {status} "
                         f"{body[:200]}")
        self.status = status
        self.body = body


def string_to_sign(method: str, account: str, path: str,
                   query: List[Tuple[str, str]],
                   headers: Dict[str, str],
                   content_length: int) -> str:
    """The SharedKey canonical string (2015-02-21+ rules: empty
    Content-Length when zero). Shared with tests so the server side
    can verify signatures independently of the signing call."""
    h = {k.lower(): str(v) for k, v in headers.items()}
    ms_headers = "".join(
        f"{k}:{h[k]}\n" for k in sorted(h) if k.startswith("x-ms-"))
    canonical_resource = f"/{account}{path}"
    for k, v in sorted(query):
        canonical_resource += f"\n{k.lower()}:{v}"
    return "\n".join([
        method,
        h.get("content-encoding", ""),
        h.get("content-language", ""),
        str(content_length) if content_length else "",
        h.get("content-md5", ""),
        h.get("content-type", ""),
        "",  # Date: empty because x-ms-date is set
        h.get("if-modified-since", ""),
        h.get("if-match", ""),
        h.get("if-none-match", ""),
        h.get("if-unmodified-since", ""),
        h.get("range", ""),
    ]) + "\n" + ms_headers + canonical_resource


def sign(account: str, key_b64: str, sts: str) -> str:
    mac = hmac.new(base64.b64decode(key_b64), sts.encode("utf-8"),
                   hashlib.sha256)
    return base64.b64encode(mac.digest()).decode()


class AzureBlobClient:
    def __init__(self, account_name: str, account_key: str,
                 endpoint: Optional[str] = None, timeout: float = 60.0):
        self.account = account_name
        self.key = account_key
        self.base = (endpoint.rstrip("/") if endpoint else
                     f"https://{account_name}.blob.core.windows.net")
        self.timeout = timeout

    def _request(self, method: str, path: str,
                 query: Optional[List[Tuple[str, str]]] = None,
                 headers: Optional[Dict[str, str]] = None,
                 payload: bytes = b"") -> Tuple[int, Dict[str, str], bytes]:
        query = query or []
        headers = dict(headers or {})
        headers["x-ms-date"] = formatdate(time.time(), usegmt=True)
        headers["x-ms-version"] = API_VERSION
        sts = string_to_sign(method, self.account, path, query, headers,
                             len(payload))
        headers["Authorization"] = \
            f"SharedKey {self.account}:{sign(self.account, self.key, sts)}"
        qs = urllib.parse.urlencode(query)
        url = self.base + urllib.parse.quote(path) + \
            (f"?{qs}" if qs else "")
        req = urllib.request.Request(url, data=payload or None,
                                     method=method, headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return r.status, dict(r.headers), r.read()
        except urllib.error.HTTPError as e:
            body = e.read().decode("utf-8", "replace")
            raise AzureError(e.code, body) from None

    # -- blob ops ------------------------------------------------------------

    def put_blob(self, container: str, key: str, data: bytes,
                 content_type: str = "application/octet-stream") -> None:
        self._request("PUT", f"/{container}/{key}", payload=data,
                      headers={"x-ms-blob-type": "BlockBlob",
                               "Content-Type": content_type})

    def get_blob(self, container: str, key: str) -> bytes:
        _, _, body = self._request("GET", f"/{container}/{key}")
        return body

    def delete_blob(self, container: str, key: str) -> None:
        try:
            self._request("DELETE", f"/{container}/{key}",
                          headers={"x-ms-delete-snapshots": "include"})
        except AzureError as e:
            if e.status != 404:  # absent blob: already converged
                raise

    def list_blobs(self, container: str,
                   prefix: str = "") -> Iterator[str]:
        marker = ""
        while True:
            query = [("restype", "container"), ("comp", "list")]
            if prefix:
                query.append(("prefix", prefix))
            if marker:
                query.append(("marker", marker))
            _, _, body = self._request("GET", f"/{container}",
                                       query=query)
            root = ET.fromstring(body)
            for blob in root.iter("Blob"):
                name = blob.findtext("Name")
                if name:
                    yield name
            marker = root.findtext("NextMarker") or ""
            if not marker:
                return
