"""Bandwidth throttler for compaction / EC copy
(reference: weed/util/throttler.go — -compactionMBps)."""

from __future__ import annotations

import time


class Throttler:
    """Call maybe_slowdown(n) after processing n bytes; sleeps so the
    average rate stays at or below limit_mbps. 0 disables."""

    def __init__(self, limit_mbps: float = 0.0):
        self.limit_bps = limit_mbps * 1024 * 1024
        self._window_start = time.monotonic()
        self._window_bytes = 0

    def maybe_slowdown(self, n: int) -> None:
        if self.limit_bps <= 0:
            return
        self._window_bytes += n
        elapsed = time.monotonic() - self._window_start
        expected = self._window_bytes / self.limit_bps
        if expected > elapsed:
            time.sleep(expected - elapsed)
        if elapsed > 1.0:
            self._window_start = time.monotonic()
            self._window_bytes = 0
