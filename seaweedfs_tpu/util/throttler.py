"""Bandwidth throttler for compaction / EC copy / scrub
(reference: weed/util/throttler.go — -compactionMBps)."""

from __future__ import annotations

import time


class Throttler:
    """Call maybe_slowdown(n) after processing n bytes; sleeps so the
    average rate stays at or below limit_mbps. 0 disables.

    Token bucket: credit accrues at the limit rate and is CAPPED at
    burst_s seconds worth, so a long idle period cannot bank unlimited
    budget — without the cap, a scrub that slept through a quiet hour
    would then read at full disk speed for an hour straight, exactly
    the IO spike the throttle exists to prevent. A call that overdraws
    the bucket sleeps until the deficit is repaid.

    limit_mbps=0 (any burst_s) is a guaranteed no-op: `disabled` is
    computed once at construction and maybe_slowdown pays exactly one
    attribute comparison — no clock read, no credit math — so the
    hot copy loops that call this per block can keep the call
    unconditionally. (The QoS plane's AdmissionBucket generalizes this
    class to non-blocking admission; seaweedfs_tpu/qos/admission.py.)
    """

    def __init__(self, limit_mbps: float = 0.0, burst_s: float = 1.0):
        self.limit_bps = limit_mbps * 1024 * 1024
        self.burst_s = max(burst_s, 0.0)
        self.disabled = self.limit_bps <= 0
        self._credit = 0.0  # empty bucket: the first bytes pay full price
        self._last = time.monotonic()

    def maybe_slowdown(self, n: int) -> None:
        if self.disabled:
            return
        now = time.monotonic()
        self._credit = min(self.limit_bps * self.burst_s,
                           self._credit + (now - self._last) * self.limit_bps)
        self._credit -= n
        if self._credit < 0:
            time.sleep(-self._credit / self.limit_bps)
            self._credit = 0.0
        # stamp AFTER any sleep: the sleep itself repaid the deficit and
        # must not accrue as fresh credit on the next call
        self._last = time.monotonic()

    def tokens(self) -> float:
        """Current credit in bytes, refreshed to now (introspection for
        the QoS gauges and /status blocks); +inf when disabled. May be
        negative right after an overdraw that has not slept yet."""
        if self.disabled:
            return float("inf")
        now = time.monotonic()
        self._credit = min(self.limit_bps * self.burst_s,
                           self._credit + (now - self._last) * self.limit_bps)
        self._last = now
        return self._credit
