"""Runtime concurrency sanitizer — the `go test -race` stand-in.

The static analyzer (`seaweedfs_tpu/analysis/`) catches what syntax
can prove; this module catches what only execution reveals. Armed, it
replaces the `threading.Lock` / `threading.RLock` factories with
wrappers that feed two detectors:

  lock-order graph   every time a thread acquires lock B while
                     holding lock A, the edge A->B is recorded (with
                     the full acquisition stack the first time the
                     edge appears). If adding an edge closes a cycle
                     — some other thread acquired them in the
                     opposite order — a `cycle` finding is emitted
                     carrying BOTH acquisition stacks: a potential
                     deadlock caught without having to lose the race.

  hold-time watchdog a lock held longer than SEAWEED_SANITIZE_HOLD_MS
                     (default 200) produces a `hold` finding with the
                     release-side stack — the runtime complement of
                     the analyzer's blocking-under-lock check, and
                     the one that sees through helper-function
                     indirection.

Zero-cost-disabled contract (the house rule): unarmed, this module is
an env read at import — `threading.Lock` stays the untouched C
factory, no wrapper, no graph, nothing (gated by
test_perf_gates.test_sanitizer_disabled_overhead). Armed via
`SEAWEED_SANITIZE=1` in the environment (before the process imports
`seaweedfs_tpu`, so module-level locks are wrapped too) or by calling
`arm()` at runtime (tests; locks created before that stay plain).

Findings surface three ways: the `findings()` API, an optional
`SEAWEED_SANITIZE_OUT` file findings append to as JSON lines
(subprocess harvest for the bench/chaos drivers), and the
`SeaweedFS_sanitizer_findings_total{kind}` counter. The chaos and
cluster E2E suites run armed (tests/conftest.py) and assert no cycle
was ever observed — every 32-way scenario doubles as a race hunt.

Instance-keyed on purpose: two locks created at the same source line
are distinct graph nodes, so a per-object lock correctly nested under
another instance of its own class never false-positives; the report
names each lock by its creation site.
"""

from __future__ import annotations

import json
import os
import threading
import time
import traceback
from typing import Dict, List, Optional, Set, Tuple

_ORIG_LOCK = threading.Lock
_ORIG_RLOCK = threading.RLock

_armed = False
_hold_threshold_s = float(os.environ.get("SEAWEED_SANITIZE_HOLD_MS",
                                         "200") or 200) / 1000.0
_out_path = os.environ.get("SEAWEED_SANITIZE_OUT", "")

# all sanitizer bookkeeping hides behind this one plain RLock; user
# code never holds it, so it cannot participate in user deadlocks.
# Reentrant because a GC pass triggered while we hold it can run a
# lock's __del__, which needs it too
_graph_lock = _ORIG_RLOCK()
_edges: Dict[Tuple[int, int], str] = {}     # (a,b) -> acquisition stack
_adj: Dict[int, Set[int]] = {}              # a -> {b}
_radj: Dict[int, Set[int]] = {}             # b -> {a} (for O(degree) GC)
_names: Dict[int, str] = {}                 # lock id -> creation site
_findings: List[dict] = []
_reported_cycles: Set[Tuple[int, int]] = set()

_tls = threading.local()


def armed() -> bool:
    return _armed


def findings() -> List[dict]:
    with _graph_lock:
        return list(_findings)


def cycles() -> List[dict]:
    return [f for f in findings() if f["kind"] == "cycle"]


def reset() -> None:
    """Drop graph + findings (tests); wrappers stay armed."""
    with _graph_lock:
        _edges.clear()
        _adj.clear()
        _radj.clear()
        _findings.clear()
        _reported_cycles.clear()


def _publish(finding: dict) -> None:
    """File append + metrics bump for one finding. MUST be called
    WITHOUT _graph_lock held: the metric family lock is taken inside
    labels(), and a concurrent labels() call creating a child lock
    takes _graph_lock — holding _graph_lock here would be the exact
    lock-order inversion this module exists to catch (and would
    deadlock the sanitizer against its own ledger; review finding)."""
    if _out_path:
        try:
            with open(_out_path, "a") as f:
                f.write(json.dumps(finding) + "\n")
        except OSError:
            pass
    # metrics import deferred: the sanitizer must be importable before
    # (and without) the stats stack
    try:
        from seaweedfs_tpu.stats.metrics import SanitizerFindingsCounter
        SanitizerFindingsCounter.labels(finding["kind"]).inc()
    except Exception:  # lint: swallow-ok(sanitizer must never take a process down)
        pass


def _held() -> List["_SanLockBase"]:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def _site(skip: int = 2) -> str:
    """filename:lineno of the code that created the lock — skipping
    threading.py internals so a bare Condition()'s default RLock is
    named after the Condition's creator, not threading.py:238."""
    f = traceback.extract_stack(limit=skip + 8)
    for fr in reversed(f[:-skip] or f):
        if fr.filename != _THIS_FILE and \
                not fr.filename.endswith(("threading.py", "queue.py")):
            return f"{fr.filename}:{fr.lineno}"
    return "<unknown>"


_THIS_FILE = __file__


def _stack() -> str:
    frames = traceback.extract_stack()
    keep = [fr for fr in frames if fr.filename != _THIS_FILE]
    return "".join(traceback.format_list(keep[-12:]))


class _SanLockBase:
    """Shared acquire/release bookkeeping around an inner lock."""

    __slots__ = ("_inner", "_site", "_acquired_at", "_depth")

    def __init__(self, inner, site: str):
        self._inner = inner
        self._site = site
        self._acquired_at = 0.0
        self._depth = 0
        with _graph_lock:
            _names[id(self)] = site

    # -- the two detectors ---------------------------------------------------

    def _on_acquired(self) -> None:
        held = _held()
        # the held list is maintained UNCONDITIONALLY — a release that
        # lands in a disarm window must still unlist the lock, or
        # re-arming would record edges from locks the thread no longer
        # holds and fabricate phantom cycles (review finding)
        if any(h is self for h in held):      # reentrant RLock acquire
            self._depth += 1
            return
        self._depth = 1
        self._acquired_at = time.monotonic()
        held.append(self)
        if not _armed or len(held) == 1:
            return
        me = id(self)
        stack = None
        new_findings = []
        with _graph_lock:
            for h in held[:-1]:
                edge = (id(h), me)
                if edge not in _edges:
                    if stack is None:
                        stack = _stack()
                    _edges[edge] = stack
                    _adj.setdefault(id(h), set()).add(me)
                    _radj.setdefault(me, set()).add(id(h))
                    f = self._cycle_check(id(h), me)
                    if f is not None:
                        _findings.append(f)
                        new_findings.append(f)
        for f in new_findings:   # file I/O + metrics OUTSIDE the lock
            _publish(f)

    def _cycle_check(self, frm: int, to: int) -> Optional[dict]:  # requires(_graph_lock)
        # caller holds _graph_lock: is there now a path to -> ... -> frm?
        # (we just added frm -> to; a path back closes the cycle).
        # Returns the finding — the caller records it under the lock
        # and publishes it after release
        pair = (min(frm, to), max(frm, to))
        if pair in _reported_cycles:
            return None
        path = self._find_path(to, frm)   # [to, ..., frm]
        if path is None:
            return None
        _reported_cycles.add(pair)
        nodes = [frm] + path[:-1]         # the cycle, each node once
        return {
            "kind": "cycle",
            "locks": [_names.get(x, "?") for x in nodes],
            # one entry per edge of the cycle, each carrying the full
            # stack of the acquisition that first created that edge —
            # for the classic AB/BA case: both sides' stacks
            "stacks": [
                {"edge": f"{_names.get(a, '?')} -> {_names.get(b, '?')}",
                 "stack": _edges.get((a, b), "?")}
                for a, b in zip(nodes, nodes[1:] + nodes[:1])
                if (a, b) in _edges
            ],
        }

    @staticmethod
    def _find_path(frm: int, to: int) -> Optional[List[int]]:  # requires(_graph_lock)
        # iterative DFS over _adj; returns the node list frm..to
        seen = {frm}
        stack = [(frm, [frm])]
        while stack:
            node, path = stack.pop()
            if node == to:
                return path
            for nxt in _adj.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def _on_release(self) -> None:
        held = _held()
        if not any(h is self for h in held):
            return
        if self._depth > 1:                    # reentrant release
            self._depth -= 1
            return
        self._depth = 0
        try:
            held.remove(self)   # unconditional — see _on_acquired
        except ValueError:
            pass
        if not _armed:
            return
        dur = time.monotonic() - self._acquired_at
        if dur >= _hold_threshold_s:
            finding = {"kind": "hold", "lock": self._site,
                       "held_s": round(dur, 4),
                       "stack": _stack()}
            with _graph_lock:
                _findings.append(finding)
            _publish(finding)   # file I/O + metrics outside the lock

    # -- lock protocol -------------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._on_acquired()
        return got

    def release(self) -> None:
        self._on_release()
        self._inner.release()

    __enter__ = acquire

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def _at_fork_reinit(self) -> None:
        # stdlib (threading, concurrent.futures) re-initializes module
        # locks in the child after fork
        self._inner._at_fork_reinit()
        self._depth = 0
        self._acquired_at = 0.0

    def __del__(self) -> None:
        # drop this lock's graph node so a recycled id() can never
        # alias onto stale edges (which could fabricate a cycle).
        # O(degree of this node), NOT O(graph) — a server churns locks
        # by the hundred-thousand (every Event/Queue/Future), and a
        # whole-graph scan per GC'd lock goes quadratic
        try:
            me = id(self)
            with _graph_lock:
                _names.pop(me, None)
                for b in _adj.pop(me, ()):
                    _edges.pop((me, b), None)
                    peers = _radj.get(b)
                    if peers is not None:
                        peers.discard(me)
                for a in _radj.pop(me, ()):
                    _edges.pop((a, me), None)
                    peers = _adj.get(a)
                    if peers is not None:
                        peers.discard(me)
        except Exception:  # lint: swallow-ok(interpreter-shutdown teardown must never raise)
            pass

    def __repr__(self) -> str:
        return f"<sanitized {self._inner!r} @ {self._site}>"


class _SanLock(_SanLockBase):
    __slots__ = ()


class _SanRLock(_SanLockBase):
    """RLock wrapper: also speaks Condition's private protocol so a
    Condition built over a sanitized RLock keeps full-depth
    release/reacquire semantics."""

    __slots__ = ()

    def _release_save(self):
        # carry the wrapper's recursion depth through Condition.wait's
        # opaque state: restoring to depth 1 regardless would make the
        # first post-wait release look final while the inner RLock is
        # still held, silently dropping edge tracking (review finding)
        saved_depth = self._depth
        self._depth = 0
        self._on_release()
        return (self._inner._release_save(), saved_depth)

    def _acquire_restore(self, state) -> None:
        inner_state, saved_depth = state
        self._inner._acquire_restore(inner_state)
        self._on_acquired()
        self._depth = max(saved_depth, 1)

    def _is_owned(self) -> bool:
        return self._inner._is_owned()


def _make_lock():
    return _SanLock(_ORIG_LOCK(), _site())


def _make_rlock():
    return _SanRLock(_ORIG_RLOCK(), _site())


def arm() -> None:
    """Patch the threading factories. Locks created BEFORE arming stay
    plain (arm before importing the package — e.g. via the env var —
    to cover module-level locks)."""
    global _armed
    if _armed:
        return
    _armed = True
    threading.Lock = _make_lock
    threading.RLock = _make_rlock


def disarm() -> None:
    """Restore the stock factories. Wrapper locks created while armed
    keep working (their recording is gated on the module flag)."""
    global _armed
    if not _armed:
        return
    _armed = False
    threading.Lock = _ORIG_LOCK
    threading.RLock = _ORIG_RLOCK


def configure(hold_ms: Optional[float] = None,
              out_path: Optional[str] = None) -> None:
    global _hold_threshold_s, _out_path
    if hold_ms is not None:
        _hold_threshold_s = float(hold_ms) / 1000.0
    if out_path is not None:
        _out_path = out_path


if os.environ.get("SEAWEED_SANITIZE"):
    arm()
