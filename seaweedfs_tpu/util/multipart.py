"""One multipart/form-data parser for every HTTP surface — the volume
server's upload path (reference needle_parse_upload.go) and the S3
gateway's POST-policy forms share it so framing/boundary fixes happen
once.
"""

from __future__ import annotations

from typing import Dict, Iterator, Tuple


def iter_parts(content_type: str, body: bytes
               ) -> Iterator[Tuple[str, str, Dict[str, str], bytes]]:
    """Yield (field name, filename, part headers lower-cased, data) for
    each part. Quoted boundaries (RFC 2046) are handled; framing CRLFs
    are stripped but content bytes survive untouched. Raises ValueError
    when the content type carries no boundary."""
    boundary = None
    for piece in (content_type or "").split(";"):
        piece = piece.strip()
        if piece.startswith("boundary="):
            boundary = piece[len("boundary="):].strip('"')
    if not boundary:
        raise ValueError("multipart without boundary")
    # RFC 2046: the delimiter is CRLF + "--" + boundary; binary content
    # containing "--boundary" mid-line must NOT split. The first
    # delimiter has no preceding CRLF in the wire form, so prepend one.
    delim = b"\r\n--" + boundary.encode()
    for part in (b"\r\n" + body).split(delim)[1:]:
        if part.startswith(b"--"):
            break  # closing delimiter
        # consume the CRLF that terminates the delimiter line; content
        # bytes survive untouched (the CRLF before the next delimiter
        # was part of the delimiter itself)
        if part.startswith(b"\r\n"):
            part = part[2:]
        header_blob, sep, data = part.partition(b"\r\n\r\n")
        if not sep:
            continue
        headers: Dict[str, str] = {}
        for line in header_blob.split(b"\r\n"):
            k, _, v = line.decode("utf-8", "replace").partition(":")
            headers[k.strip().lower()] = v.strip()
        name = filename = ""
        for item in headers.get("content-disposition", "").split(";")[1:]:
            item = item.strip()
            if item.startswith("name="):
                name = item[len("name="):].strip('"')
            elif item.startswith("filename="):
                filename = item[len("filename="):].strip('"')
        yield name, filename, headers, data
