"""Shared lazy fan-out pool for the ingest and delete planes.

The write path has three places that used to walk peers one blocking
round trip at a time — the filer's per-chunk uploads, the volume
server's replica POSTs, and delete_files' per-server BatchDelete — and
the reference fans each of them out with goroutines
(topology/store_replicate.go, operation/delete_content.go). Python has
no free goroutines, so this module is the shared substitute: a bounded
worker pool that costs NOTHING until the first task.

Cost discipline (the fleet/cache/scrub house rule, gated by
tests/test_perf_gates.py::test_ingest_pipeline_disabled_overhead):
constructing a FanOutPool allocates a queue and a lock — no threads.
Workers spawn one-per-submit up to the cap on the first tasks and then
persist (daemon), so a server that never sees a multi-chunk body or a
replicated write never grows an ingest thread.
"""

from __future__ import annotations

import contextvars
import queue
import threading
from typing import Any, Callable, List, Optional, Sequence, Tuple

# Weighted-fair scheduling seam: seaweedfs_tpu.qos.configure() installs
# its manager here (reset() clears it). None — the default — keeps
# submit() one identity check away from the plain FIFO path, which is
# what tests/test_perf_gates.py::test_qos_disabled_overhead gates.
_qos_sched = None

# queue token standing in for one task parked in the pool's weighted-
# fair queue: the SimpleQueue stays the worker WAKEUP channel (stop()
# sentinel semantics untouched), the WFQ decides the ORDER
_WFQ_TOKEN = object()


class Future:
    """Result slot for one submitted task: wait() -> (result, exc)."""

    __slots__ = ("_ev", "result", "exc")

    def __init__(self):
        self._ev = threading.Event()
        self.result: Any = None
        self.exc: Optional[BaseException] = None

    def wait(self, timeout: Optional[float] = None
             ) -> Tuple[Any, Optional[BaseException]]:
        if not self._ev.wait(timeout):
            raise TimeoutError("fan-out task still running")
        return self.result, self.exc

    def done(self) -> bool:
        return self._ev.is_set()


class FanOutPool:
    """Bounded daemon-worker pool; zero threads until first submit().

    Tasks must never block on THIS pool's own futures (a task that
    submits to its own saturated pool and waits can deadlock) — the
    ingest callers all bottom out in plain socket/gRPC calls, which is
    the contract.
    """

    def __init__(self, size: int = 8, name: str = "fanout",
                 inflight_gauge=None):
        self.size = max(1, int(size))
        self.name = name
        # tasks submitted but not finished; optional gauge mirrors it
        # (SeaweedFS_ingest_pipeline_occupancy on the filer's pool)
        self._inflight_gauge = inflight_gauge
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._lock = threading.Lock()
        # thread_count() reads lock-free (introspection may be stale)
        self._threads: List[threading.Thread] = []  # guarded_by(self._lock, writes)
        self._stopping = False  # guarded_by(self._lock)
        # weighted-fair backlog, built lazily on the first submit made
        # while QoS is on (None forever otherwise)
        self._wfq = None  # guarded_by(self._lock, writes)

    def thread_count(self) -> int:
        return len(self._threads)

    def _worker(self) -> None:
        while True:
            item = self._q.get()
            if item is None:   # stop() sentinel
                return
            if item is _WFQ_TOKEN:
                wfq = self._wfq
                item = wfq.pop() if wfq is not None else None
                if item is None:
                    continue
            fut, ctx, fn, args = item
            self._run_task(fut, ctx, fn, args)

    def _run_task(self, fut: Future, ctx, fn: Callable, args) -> None:
        try:
            fut.result = ctx.run(fn, *args)
        except BaseException as e:  # noqa: BLE001 - latched, not lost
            fut.exc = e
        finally:
            if self._inflight_gauge is not None:
                self._inflight_gauge.dec()
            fut._ev.set()

    def submit(self, fn: Callable, *args) -> Future:
        # tasks run in a COPY of the submitter's context, so ambient
        # request state — the resilience deadline above all — follows
        # the work across the thread hop instead of silently resetting
        ctx = contextvars.copy_context()
        fut = Future()
        if self._inflight_gauge is not None:
            self._inflight_gauge.inc()
        # enqueue + stopping-check + spawn-bookkeeping are one atomic
        # step against stop(): a task enqueued under the lock is
        # guaranteed to sit AHEAD of stop()'s sentinels (stop takes the
        # same lock first), so it always gets a worker; a submit that
        # sees _stopping runs inline instead — no window where a task
        # lands behind the sentinels and hangs its Future forever
        qos = _qos_sched
        with self._lock:
            stopping = self._stopping
            if not stopping:
                if qos is not None:
                    # weighted-fair path: the task parks in the WFQ
                    # (ordered by tenant weight), a token wakes one
                    # worker; transport and stop semantics unchanged
                    wfq = self._wfq
                    if wfq is None:
                        wfq = self._wfq = qos.make_wfq(self.name)
                    wfq.put((fut, ctx, fn, args))
                    # lint: block-ok(SimpleQueue.put never blocks; the lock orders enqueue against stop's sentinels)
                    self._q.put(_WFQ_TOKEN)
                else:
                    # lint: block-ok(SimpleQueue.put never blocks; the lock orders enqueue against stop's sentinels)
                    self._q.put((fut, ctx, fn, args))
                if len(self._threads) < self.size:
                    t = threading.Thread(
                        target=self._worker, daemon=True,
                        name=f"{self.name}-{len(self._threads)}")
                    # started INSIDE the lock: stop() joins whatever
                    # sits in _threads, and joining a never-started
                    # thread raises RuntimeError mid-shutdown
                    t.start()
                    self._threads.append(t)
        if stopping:
            # drain semantics after stop(): late tasks run inline on
            # the caller instead of being lost or growing new threads
            self._run_task(fut, ctx, fn, args)
        return fut

    def stop(self, join_timeout: float = 2.0) -> None:
        """Drain + stop every worker (util/grace shutdown path: server
        stop() calls this). Queued tasks still run — workers only exit
        on the sentinel, which sits BEHIND everything already queued —
        and tasks submitted afterwards run inline on the caller."""
        with self._lock:
            if self._stopping:
                return
            self._stopping = True
            threads = list(self._threads)
        for _ in threads:
            self._q.put(None)
        for t in threads:
            t.join(timeout=join_timeout)

    def run(self, fns: Sequence[Callable]
            ) -> List[Tuple[Any, Optional[BaseException]]]:
        """Run all thunks concurrently; ordered (result, exc) pairs.

        Always drains every task — an early failure never leaves a
        sibling's socket dangling half-read in a shared pool.
        """
        if len(fns) == 1:  # no thread hop for the degenerate fan-out
            try:
                return [(fns[0](), None)]
            except BaseException as e:  # noqa: BLE001
                return [(None, e)]
        futs = [self.submit(fn) for fn in fns]
        return [f.wait() for f in futs]
