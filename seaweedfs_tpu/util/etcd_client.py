"""Minimal etcd v3 client over the JSON gRPC-gateway (stdlib only).

The reference links the etcd clientv3 gRPC SDK
(weed/filer/etcd/etcd_store.go, weed/sequence/etcd_sequencer.go); this
image has no etcd SDK, so the same capability rides etcd's built-in
HTTP/JSON gateway (`/v3/kv/*`, base64-encoded keys/values) — enough
for KV CRUD, prefix ranges, and the compare-and-swap transactions the
sequencer needs.
"""

from __future__ import annotations

import base64
import json
import urllib.error
import urllib.request
from typing import List, Optional, Tuple


class EtcdError(Exception):
    pass


def _b64(b: bytes) -> str:
    return base64.b64encode(b).decode()


def _unb64(s: str) -> bytes:
    return base64.b64decode(s)


def prefix_range_end(prefix: bytes) -> bytes:
    """etcd convention: the key range [prefix, prefix+1) covers every
    key with that prefix."""
    end = bytearray(prefix)
    for i in range(len(end) - 1, -1, -1):
        if end[i] < 0xFF:
            end[i] += 1
            return bytes(end[: i + 1])
    return b"\x00"  # all-0xff prefix: range to the end of keyspace


class EtcdClient:
    def __init__(self, endpoint: str = "127.0.0.1:2379",
                 timeout: float = 10.0):
        self.base = "http://" + endpoint.replace("http://", "").rstrip("/")
        self.timeout = timeout

    def _post(self, path: str, body: dict) -> dict:
        req = urllib.request.Request(
            self.base + path, data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return json.load(r)
        except urllib.error.HTTPError as e:
            raise EtcdError(
                f"etcd {path}: HTTP {e.code} "
                f"{e.read().decode('utf-8', 'replace')[:200]}") from None
        except urllib.error.URLError as e:
            raise EtcdError(f"etcd {path}: {e.reason}") from None

    # -- KV ------------------------------------------------------------------

    def put(self, key: bytes, value: bytes) -> None:
        self._post("/v3/kv/put",
                   {"key": _b64(key), "value": _b64(value)})

    def get(self, key: bytes) -> Optional[bytes]:
        kvs = self.range(key)
        return kvs[0][1] if kvs else None

    def range(self, key: bytes, range_end: Optional[bytes] = None,
              limit: int = 0) -> List[Tuple[bytes, bytes]]:
        body = {"key": _b64(key)}
        if range_end is not None:
            body["range_end"] = _b64(range_end)
        if limit:
            body["limit"] = str(limit)
        body["sort_order"] = "ASCEND"
        body["sort_target"] = "KEY"
        resp = self._post("/v3/kv/range", body)
        return [(_unb64(kv["key"]), _unb64(kv.get("value", "")))
                for kv in resp.get("kvs", [])]

    def delete_range(self, key: bytes,
                     range_end: Optional[bytes] = None) -> int:
        body = {"key": _b64(key)}
        if range_end is not None:
            body["range_end"] = _b64(range_end)
        resp = self._post("/v3/kv/deleterange", body)
        return int(resp.get("deleted", 0))

    # -- transactions --------------------------------------------------------

    def cas(self, key: bytes, expect: Optional[bytes],
            new_value: bytes) -> bool:
        """Compare-and-swap: expect=None means 'key must not exist'.
        Returns True when the swap applied."""
        if expect is None:
            compare = [{"key": _b64(key), "target": "CREATE",
                        "result": "EQUAL", "create_revision": "0"}]
        else:
            compare = [{"key": _b64(key), "target": "VALUE",
                        "result": "EQUAL", "value": _b64(expect)}]
        body = {
            "compare": compare,
            "success": [{"request_put": {"key": _b64(key),
                                         "value": _b64(new_value)}}],
            "failure": [],
        }
        resp = self._post("/v3/kv/txn", body)
        return bool(resp.get("succeeded", False))
