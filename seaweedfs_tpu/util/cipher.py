"""Chunk encryption: AES-256-GCM (reference: weed/util/cipher.go).

Each chunk gets a fresh random key; the key lives in filer metadata
(FileChunk.cipher_key), never on the volume server. The nonce is
prepended to the ciphertext exactly like the reference's Seal with a
random nonce prefix.
"""

from __future__ import annotations

import os

from cryptography.hazmat.primitives.ciphers.aead import AESGCM

KEY_SIZE = 32
NONCE_SIZE = 12


class CipherError(Exception):
    pass


def encrypt(data: bytes) -> tuple[bytes, bytes]:
    """Returns (nonce||ciphertext||tag, key)."""
    key = os.urandom(KEY_SIZE)
    nonce = os.urandom(NONCE_SIZE)
    sealed = AESGCM(key).encrypt(nonce, data, None)
    return nonce + sealed, key


def decrypt(data: bytes, key: bytes) -> bytes:
    if len(data) < NONCE_SIZE:
        raise CipherError("ciphertext shorter than nonce")
    try:
        return AESGCM(key).decrypt(data[:NONCE_SIZE], data[NONCE_SIZE:], None)
    except Exception as e:
        raise CipherError(f"decrypt: {e}") from e
