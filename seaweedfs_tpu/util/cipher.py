"""Chunk encryption: AES-256-GCM (reference: weed/util/cipher.go).

Each chunk gets a fresh random key; the key lives in filer metadata
(FileChunk.cipher_key), never on the volume server. The nonce is
prepended to the ciphertext exactly like the reference's Seal with a
random nonce prefix.
"""

from __future__ import annotations

import os

KEY_SIZE = 32
NONCE_SIZE = 12


class CipherError(RuntimeError):
    # RuntimeError subclass so the data-plane handlers' generic
    # `except RuntimeError` (filer do_POST, chunk readers) map a
    # cipher failure to a JSON 500, never an escaped exception
    pass


def _aesgcm():
    """Deferred dependency: images without the cryptography package
    must still import the filer stack (cipher=False is the default and
    never reaches this) — an actual encrypted read/write on such an
    image raises CipherError at call time instead of breaking every
    filer import."""
    try:
        from cryptography.hazmat.primitives.ciphers.aead import AESGCM
    except ImportError as e:
        raise CipherError(f"cryptography package not available: {e}") \
            from e
    return AESGCM


def encrypt(data: bytes) -> tuple[bytes, bytes]:
    """Returns (nonce||ciphertext||tag, key)."""
    key = os.urandom(KEY_SIZE)
    nonce = os.urandom(NONCE_SIZE)
    sealed = _aesgcm()(key).encrypt(nonce, data, None)
    return nonce + sealed, key


def decrypt(data: bytes, key: bytes) -> bytes:
    if len(data) < NONCE_SIZE:
        raise CipherError("ciphertext shorter than nonce")
    try:
        return _aesgcm()(key).decrypt(data[:NONCE_SIZE],
                                      data[NONCE_SIZE:], None)
    except CipherError:
        raise
    except Exception as e:
        raise CipherError(f"decrypt: {e}") from e
