"""Force JAX onto a virtual N-device CPU platform (shared helper).

Used by tests/conftest.py and __graft_entry__.dryrun_multichip: multi-chip
hardware is unavailable in this container, so sharding programs are
validated on virtual CPU devices via
``--xla_force_host_platform_device_count``.

Why this is fiddly enough to deserve one shared owner: the image's
sitecustomize imports jax at interpreter start (registering the remote
'axon' TPU platform), so setting ``JAX_PLATFORMS`` in the environment is
captured too late — ``jax.config.update("jax_platforms", "cpu")`` is the
supported post-import override, and it must run before the first backend
initialization (the first ``jax.devices()``/dispatch).

This module must NOT import jax at top level: callers need to mutate
``XLA_FLAGS`` before jax's backend reads it.
"""

from __future__ import annotations

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def force_cpu_platform(n_devices: int = 8) -> None:
    """Point JAX at a virtual ``n_devices``-CPU platform.

    Safe to call multiple times; replaces (not just appends) any existing
    device-count flag so a stale smaller count from the environment cannot
    silently shrink the mesh. Raises if the backend was already
    initialized with a different platform/count (too late to change).
    """
    flags = os.environ.get("XLA_FLAGS", "")
    want = f"{_COUNT_FLAG}={n_devices}"
    if _COUNT_FLAG in flags:
        flags = re.sub(rf"{_COUNT_FLAG}=\d+", want, flags)
    else:
        flags = f"{flags} {want}".strip()
    os.environ["XLA_FLAGS"] = flags
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault("JAX_ENABLE_X64", "0")

    import jax

    jax.config.update("jax_platforms", "cpu")
    got = len(jax.devices("cpu"))
    if got < n_devices:
        raise RuntimeError(
            f"virtual CPU platform has {got} devices, wanted {n_devices}: "
            "the XLA backend was already initialized before "
            "force_cpu_platform() ran — call it before any jax.devices()/"
            "dispatch")
