"""Fast HTTP handler base for the data plane.

BaseHTTPRequestHandler parses request headers with the email package,
which (a) walks a feed parser state machine per request and (b) for
multipart uploads compiles a regex from the request's unique boundary
string — a guaranteed re-cache miss costing ~0.5 ms per POST. The
reference's data plane is Go's net/http, whose header parse is a tight
loop over bytes (net/textproto Reader.ReadMIMEHeader); FastHandler is
that idea on top of the stdlib server plumbing: same request-line
semantics and error replies as BaseHTTPRequestHandler.parse_request,
but headers land in a plain lowercase-keyed dict.

Handlers keep the whole BaseHTTPRequestHandler API (send_response /
send_header / end_headers / wfile / rfile); only parsing and the
per-response Date header (cached per second) are replaced.
"""

from __future__ import annotations

import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

_MAX_LINE = 65536
_MAX_HEADERS = 100


class HeaderDict(dict):
    """Case-insensitive read access; keys are stored lowercase.

    Every header consumer in this codebase either calls .get()/[] (both
    case-insensitive here) or lowercases keys itself when iterating
    (s3api SigV4, aws_auth, filer proxy), so lowercase storage is safe.
    """

    __slots__ = ()

    def get(self, key, default=None):
        return dict.get(self, key.lower(), default)

    def __getitem__(self, key):
        return dict.__getitem__(self, key.lower())

    def __contains__(self, key):
        return dict.__contains__(self, key.lower())


_date_cache = (0, "")


def http_date() -> str:
    """RFC 7231 date, cached per second (one response header per
    request; strftime per call is measurable at data-plane rates)."""
    global _date_cache
    now = int(time.time())
    if _date_cache[0] != now:
        t = time.gmtime(now)
        _date_cache = (now, (
            f"{('Mon','Tue','Wed','Thu','Fri','Sat','Sun')[t.tm_wday]}, "
            f"{t.tm_mday:02d} "
            f"{('Jan','Feb','Mar','Apr','May','Jun','Jul','Aug','Sep','Oct','Nov','Dec')[t.tm_mon-1]} "
            f"{t.tm_year} {t.tm_hour:02d}:{t.tm_min:02d}:{t.tm_sec:02d} GMT"))
    return _date_cache[1]


class TrackingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer that force-closes established connections on
    server_close.

    With keep-alive clients, handler threads park in readline() waiting
    for the next request; stock server_close only closes the LISTENER,
    so a stopped server keeps answering on old connections — and once
    the OS reuses its port for a new server, pooled clients talk to a
    ghost. Tracking and shutting the accepted sockets makes stop mean
    stop (Go's http.Server.Close closes active conns the same way)."""

    daemon_threads = True

    def __init__(self, *args, **kwargs):
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        super().__init__(*args, **kwargs)

    def process_request(self, request, client_address):
        with self._conns_lock:
            self._conns.add(request)
        super().process_request(request, client_address)

    def shutdown_request(self, request):
        with self._conns_lock:
            self._conns.discard(request)
        super().shutdown_request(request)

    def server_close(self):
        super().server_close()
        with self._conns_lock:
            conns = list(self._conns)
            self._conns.clear()
        for s in conns:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass


class FastHandler(BaseHTTPRequestHandler):
    """BaseHTTPRequestHandler with a fast header parser."""

    protocol_version = "HTTP/1.1"
    disable_nagle_algorithm = True

    def date_time_string(self, timestamp=None):
        if timestamp is not None:
            return super().date_time_string(timestamp)
        return http_date()

    def parse_request(self) -> bool:
        """Semantics of BaseHTTPRequestHandler.parse_request (status
        codes and close_connection behavior) with dict headers."""
        self.command = None
        self.request_version = version = self.default_request_version
        self.close_connection = True
        requestline = str(self.raw_requestline, "iso-8859-1").rstrip("\r\n")
        self.requestline = requestline
        words = requestline.split()
        if len(words) == 3:
            command, path, version = words
            if not version.startswith("HTTP/"):
                self.send_error(400, f"Bad request version ({version!r})")
                return False
            try:
                major, _, minor = version[5:].partition(".")
                version_number = (int(major), int(minor))
            except ValueError:
                self.send_error(400, f"Bad request version ({version!r})")
                return False
            if version_number >= (1, 1) and \
                    self.protocol_version >= "HTTP/1.1":
                self.close_connection = False
            if version_number >= (2, 0):
                self.send_error(505,
                                f"Invalid HTTP version ({version!r})")
                return False
        elif len(words) == 2:
            command, path = words
            self.close_connection = True
            if command != "GET":
                self.send_error(400,
                                f"Bad HTTP/0.9 request type ({command!r})")
                return False
        elif not words:
            return False
        else:
            self.send_error(400, f"Bad request syntax ({requestline!r})")
            return False
        self.command, self.path, self.request_version = \
            command, path, version

        headers = HeaderDict()
        rfile = self.rfile
        count = 0
        while True:
            line = rfile.readline(_MAX_LINE + 1)
            if len(line) > _MAX_LINE:
                self.send_error(431, "Header line too long")
                return False
            if line in (b"\r\n", b"\n", b""):
                break
            count += 1
            if count > _MAX_HEADERS:
                self.send_error(431, "Too many headers")
                return False
            colon = line.find(b":")
            if colon <= 0:
                # bare continuation lines / malformed headers: the email
                # parser tolerates them silently; skip likewise
                continue
            key = line[:colon].decode("iso-8859-1").strip().lower()
            value = line[colon + 1:].decode("iso-8859-1").strip()
            if key not in headers:
                # first value wins on duplicates, matching how the email
                # parser's .get() behaved for every consumer here (and
                # keeping framing headers like Content-Length parseable)
                dict.__setitem__(headers, key, value)
        self.headers = headers

        conntype = headers.get("connection", "").lower()
        if conntype == "close":
            self.close_connection = True
        elif conntype == "keep-alive" and \
                self.protocol_version >= "HTTP/1.1":
            self.close_connection = False
        if headers.get("expect", "").lower() == "100-continue" and \
                self.protocol_version >= "HTTP/1.1" and \
                self.request_version != "HTTP/0.9":
            if not self.handle_expect_100():
                return False
        return True
