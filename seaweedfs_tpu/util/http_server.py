"""Fast HTTP handler base for the data plane.

BaseHTTPRequestHandler parses request headers with the email package,
which (a) walks a feed parser state machine per request and (b) for
multipart uploads compiles a regex from the request's unique boundary
string — a guaranteed re-cache miss costing ~0.5 ms per POST. The
reference's data plane is Go's net/http, whose header parse is a tight
loop over bytes (net/textproto Reader.ReadMIMEHeader); FastHandler is
that idea on top of the stdlib server plumbing: same request-line
semantics and error replies as BaseHTTPRequestHandler.parse_request,
but headers land in a plain lowercase-keyed dict.

Handlers keep the whole BaseHTTPRequestHandler API (send_response /
send_header / end_headers / wfile / rfile); only parsing and the
per-response Date header (cached per second) are replaced.
"""

from __future__ import annotations

import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

_MAX_LINE = 65536
_MAX_HEADERS = 100

# status -> reason phrase for fast_reply (same table BaseHTTPRequestHandler
# uses, flattened once at import)
_REASONS = {code: msg for code, (msg, _longmsg)
            in BaseHTTPRequestHandler.responses.items()}


class HeaderDict(dict):
    """Case-insensitive read access; keys are stored lowercase.

    Every header consumer in this codebase either calls .get()/[] (both
    case-insensitive here) or lowercases keys itself when iterating
    (s3api SigV4, aws_auth, filer proxy), so lowercase storage is safe.
    """

    __slots__ = ()

    def get(self, key, default=None):
        # first probe as-given: hot callers pass lowercase literals and
        # skip the per-call key.lower() (values are never None)
        v = dict.get(self, key)
        if v is not None:
            return v
        return dict.get(self, key.lower(), default)

    def __getitem__(self, key):
        v = dict.get(self, key)
        if v is not None:
            return v
        return dict.__getitem__(self, key.lower())

    def __contains__(self, key):
        return dict.__contains__(self, key) or \
            dict.__contains__(self, key.lower())


def parse_header_block(rfile, headers: dict,
                       max_headers: int = 0) -> Optional[str]:
    """Read a CRLF-terminated header block from a BufferedReader into
    `headers` (lowercase keys, first value wins). Shared by the server
    (FastHandler.parse_request) and the client (http_client._roundtrip)
    so their header parsing cannot silently diverge.

    Fast path: the whole block usually sits in the reader's buffer
    already (the request/status line was just read from it), so peek +
    one decode + one split replaces a readline/decode/strip per line.
    Returns None on success, "toolong" / "toomany" on limit breach.
    """
    setdefault = dict.setdefault
    buf = rfile.peek(_MAX_LINE)
    if buf.startswith(b"\r\n"):  # zero headers: bare blank line
        rfile.read(2)
        return None
    end = buf.find(b"\r\n\r\n")
    if 0 <= end < _MAX_LINE:
        block = rfile.read(end + 4)[:end]
        lines = block.decode("iso-8859-1").split("\r\n") if block else []
        if max_headers and len(lines) > max_headers:
            return "toomany"
        for line in lines:
            key, sep, value = line.partition(":")
            if not sep or not key:
                # bare continuation lines / malformed headers: the email
                # parser tolerated them silently; skip likewise
                continue
            setdefault(headers, key.strip().lower(), value.strip())
        return None
    count = 0
    while True:
        line = rfile.readline(_MAX_LINE + 1)
        if len(line) > _MAX_LINE:
            return "toolong"
        if line in (b"\r\n", b"\n", b""):
            return None
        count += 1
        if max_headers and count > max_headers:
            return "toomany"
        colon = line.find(b":")
        if colon <= 0:
            continue
        key = line[:colon].decode("iso-8859-1").strip().lower()
        value = line[colon + 1:].decode("iso-8859-1").strip()
        setdefault(headers, key, value)


_date_cache = (0, "")


def http_date() -> str:
    """RFC 7231 date, cached per second (one response header per
    request; strftime per call is measurable at data-plane rates)."""
    global _date_cache
    now = int(time.time())
    if _date_cache[0] != now:
        t = time.gmtime(now)
        _date_cache = (now, (
            f"{('Mon','Tue','Wed','Thu','Fri','Sat','Sun')[t.tm_wday]}, "
            f"{t.tm_mday:02d} "
            f"{('Jan','Feb','Mar','Apr','May','Jun','Jul','Aug','Sep','Oct','Nov','Dec')[t.tm_mon-1]} "
            f"{t.tm_year} {t.tm_hour:02d}:{t.tm_min:02d}:{t.tm_sec:02d} GMT"))
    return _date_cache[1]


class TrackingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer that force-closes established connections on
    server_close.

    With keep-alive clients, handler threads park in readline() waiting
    for the next request; stock server_close only closes the LISTENER,
    so a stopped server keeps answering on old connections — and once
    the OS reuses its port for a new server, pooled clients talk to a
    ghost. Tracking and shutting the accepted sockets makes stop mean
    stop (Go's http.Server.Close closes active conns the same way)."""

    daemon_threads = True

    def __init__(self, *args, **kwargs):
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        super().__init__(*args, **kwargs)

    def process_request(self, request, client_address):
        with self._conns_lock:
            self._conns.add(request)
        super().process_request(request, client_address)

    def shutdown_request(self, request):
        with self._conns_lock:
            self._conns.discard(request)
        super().shutdown_request(request)

    def server_close(self):
        super().server_close()
        with self._conns_lock:
            conns = list(self._conns)
            self._conns.clear()
        for s in conns:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass


class FastHandler(BaseHTTPRequestHandler):
    """BaseHTTPRequestHandler with a fast header parser."""

    protocol_version = "HTTP/1.1"
    disable_nagle_algorithm = True
    # Buffered wfile: stock socketserver uses an unbuffered writer, so
    # every response costs two sendall syscalls (joined header block,
    # then body) and wakes the peer twice — measurable at data-plane
    # rates on loopback. handle_one_request() flushes after each
    # handler, so buffering coalesces each response into ONE send
    # (Go's net/http response writer buffers the same way).
    wbufsize = 65536

    def handle_expect_100(self):
        """The interim 100 Continue must reach the client BEFORE we
        block reading the body — flush past the buffered wfile."""
        ok = super().handle_expect_100()
        if ok:
            self.wfile.flush()
        return ok

    def fast_reply(self, code: int, body: bytes = b"",
                   headers=None, ctype: str = "") -> None:
        """Whole response head as one f-string + one buffered write.

        send_response/send_header/end_headers cost ~5 Python calls and
        a list-append/join per response; at small-file data-plane rates
        that machinery is a measurable share of the server's cycles.
        Semantics kept: Date header, Connection: close when the request
        asked for it, no body on HEAD. (Go's net/http writes its
        response head the same single-buffer way.)"""
        reason = _REASONS.get(code, "")
        # mirrored by the instrumented send_response hook: the cluster
        # tracer's tail sampler keeps 5xx requests by final status
        self.last_status = code
        parts = [f"HTTP/1.1 {code} {reason}\r\nDate: {http_date()}\r\n"]
        if ctype:
            parts.append(f"Content-Type: {ctype}\r\n")
        if headers:
            for k, v in headers.items():
                parts.append(f"{k}: {v}\r\n")
        if self.close_connection:
            parts.append("Connection: close\r\n")
        parts.append(f"Content-Length: {len(body)}\r\n\r\n")
        self.wfile.write("".join(parts).encode("latin-1"))
        if body and self.command != "HEAD":
            self.wfile.write(body)

    def date_time_string(self, timestamp=None):
        if timestamp is not None:
            return super().date_time_string(timestamp)
        return http_date()

    def parse_request(self) -> bool:
        """Semantics of BaseHTTPRequestHandler.parse_request (status
        codes and close_connection behavior) with dict headers."""
        self.command = None
        self.request_version = version = self.default_request_version
        self.close_connection = True
        requestline = str(self.raw_requestline, "iso-8859-1").rstrip("\r\n")
        self.requestline = requestline
        words = requestline.split()
        if len(words) == 3:
            command, path, version = words
            if not version.startswith("HTTP/"):
                self.send_error(400, f"Bad request version ({version!r})")
                return False
            try:
                major, _, minor = version[5:].partition(".")
                version_number = (int(major), int(minor))
            except ValueError:
                self.send_error(400, f"Bad request version ({version!r})")
                return False
            if version_number >= (1, 1) and \
                    self.protocol_version >= "HTTP/1.1":
                self.close_connection = False
            if version_number >= (2, 0):
                self.send_error(505,
                                f"Invalid HTTP version ({version!r})")
                return False
        elif len(words) == 2:
            command, path = words
            self.close_connection = True
            if command != "GET":
                self.send_error(400,
                                f"Bad HTTP/0.9 request type ({command!r})")
                return False
        elif not words:
            return False
        else:
            self.send_error(400, f"Bad request syntax ({requestline!r})")
            return False
        self.command, self.path, self.request_version = \
            command, path, version

        headers = HeaderDict()
        err = parse_header_block(self.rfile, headers,
                                 max_headers=_MAX_HEADERS)
        if err == "toolong":
            self.send_error(431, "Header line too long")
            return False
        if err == "toomany":
            self.send_error(431, "Too many headers")
            return False
        self.headers = headers
        return self._finish_parse(headers)

    def _finish_parse(self, headers: "HeaderDict") -> bool:
        conntype = headers.get("connection", "").lower()
        if conntype == "close":
            self.close_connection = True
        elif conntype == "keep-alive" and \
                self.protocol_version >= "HTTP/1.1":
            self.close_connection = False
        if headers.get("expect", "").lower() == "100-continue" and \
                self.protocol_version >= "HTTP/1.1" and \
                self.request_version != "HTTP/0.9":
            if not self.handle_expect_100():
                return False
        return True
