"""Fast HTTP handler base for the data plane.

BaseHTTPRequestHandler parses request headers with the email package,
which (a) walks a feed parser state machine per request and (b) for
multipart uploads compiles a regex from the request's unique boundary
string — a guaranteed re-cache miss costing ~0.5 ms per POST. The
reference's data plane is Go's net/http, whose header parse is a tight
loop over bytes (net/textproto Reader.ReadMIMEHeader); FastHandler is
that idea on top of the stdlib server plumbing: same request-line
semantics and error replies as BaseHTTPRequestHandler.parse_request,
but headers land in a plain lowercase-keyed dict.

Handlers keep the whole BaseHTTPRequestHandler API (send_response /
send_header / end_headers / wfile / rfile); only parsing and the
per-response Date header (cached per second) are replaced.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

_MAX_LINE = 65536
_MAX_HEADERS = 100
# one chunk-size line of a chunked body (hex digits + extensions)
_MAX_CHUNK_LINE = 1024
# copy window for threaded file-span bodies (async connections hand
# the span to os.sendfile instead)
_SPAN_COPY = 65536

# status -> reason phrase for fast_reply (same table BaseHTTPRequestHandler
# uses, flattened once at import)
_REASONS = {code: msg for code, (msg, _longmsg)
            in BaseHTTPRequestHandler.responses.items()}


class HeaderDict(dict):
    """Case-insensitive read access; keys are stored lowercase.

    Every header consumer in this codebase either calls .get()/[] (both
    case-insensitive here) or lowercases keys itself when iterating
    (s3api SigV4, aws_auth, filer proxy), so lowercase storage is safe.
    """

    __slots__ = ()

    def get(self, key, default=None):
        # first probe as-given: hot callers pass lowercase literals and
        # skip the per-call key.lower() (values are never None)
        v = dict.get(self, key)
        if v is not None:
            return v
        return dict.get(self, key.lower(), default)

    def __getitem__(self, key):
        v = dict.get(self, key)
        if v is not None:
            return v
        return dict.__getitem__(self, key.lower())

    def __contains__(self, key):
        return dict.__contains__(self, key) or \
            dict.__contains__(self, key.lower())


def parse_header_block(rfile, headers: dict,
                       max_headers: int = 0) -> Optional[str]:
    """Read a CRLF-terminated header block from a BufferedReader into
    `headers` (lowercase keys, first value wins). Shared by the server
    (FastHandler.parse_request) and the client (http_client._roundtrip)
    so their header parsing cannot silently diverge.

    Fast path: the whole block usually sits in the reader's buffer
    already (the request/status line was just read from it), so peek +
    one decode + one split replaces a readline/decode/strip per line.
    Returns None on success, "toolong" / "toomany" on limit breach.
    """
    setdefault = dict.setdefault
    buf = rfile.peek(_MAX_LINE)
    if buf.startswith(b"\r\n"):  # zero headers: bare blank line
        rfile.read(2)
        return None
    end = buf.find(b"\r\n\r\n")
    if 0 <= end < _MAX_LINE:
        block = rfile.read(end + 4)[:end]
        lines = block.decode("iso-8859-1").split("\r\n") if block else []
        if max_headers and len(lines) > max_headers:
            return "toomany"
        for line in lines:
            key, sep, value = line.partition(":")
            if not sep or not key:
                # bare continuation lines / malformed headers: the email
                # parser tolerated them silently; skip likewise
                continue
            setdefault(headers, key.strip().lower(), value.strip())
        return None
    count = 0
    while True:
        line = rfile.readline(_MAX_LINE + 1)
        if len(line) > _MAX_LINE:
            return "toolong"
        if line in (b"\r\n", b"\n", b""):
            return None
        count += 1
        if max_headers and count > max_headers:
            return "toomany"
        colon = line.find(b":")
        if colon <= 0:
            continue
        key = line[:colon].decode("iso-8859-1").strip().lower()
        value = line[colon + 1:].decode("iso-8859-1").strip()
        setdefault(headers, key, value)


_date_cache = (0, "")


def http_date() -> str:
    """RFC 7231 date, cached per second (one response header per
    request; strftime per call is measurable at data-plane rates)."""
    global _date_cache
    now = int(time.time())
    if _date_cache[0] != now:
        t = time.gmtime(now)
        _date_cache = (now, (
            f"{('Mon','Tue','Wed','Thu','Fri','Sat','Sun')[t.tm_wday]}, "
            f"{t.tm_mday:02d} "
            f"{('Jan','Feb','Mar','Apr','May','Jun','Jul','Aug','Sep','Oct','Nov','Dec')[t.tm_mon-1]} "
            f"{t.tm_year} {t.tm_hour:02d}:{t.tm_min:02d}:{t.tm_sec:02d} GMT"))
    return _date_cache[1]


def parse_content_length(headers) -> int:
    """Declared body length, 0 when absent/unparseable. Shared by both
    server models so their framing decisions cannot diverge."""
    try:
        return int(headers.get("content-length") or 0)
    except (TypeError, ValueError):
        return 0


def is_chunked(headers) -> bool:
    return "chunked" in (headers.get("transfer-encoding") or "").lower()


class BodyReader:
    """Framing-aware request-body reader shared by BOTH server models.

    Wraps the raw connection reader (threaded model) or a buffer of the
    already-received body bytes (async model) and exposes exactly the
    request body: reads are capped at the Content-Length, and a
    ``Transfer-Encoding: chunked`` body is decoded transparently —
    identical decode code on both models, so a chunked PUT answers
    byte-identically whichever core serves it. ``drain()`` consumes
    whatever the handler left unread, keeping keep-alive/pipelined
    framing intact."""

    __slots__ = ("_raw", "_chunked", "_remaining", "_done")

    def __init__(self, raw, headers):
        self._raw = raw
        self._chunked = is_chunked(headers)
        self._remaining = 0 if self._chunked \
            else parse_content_length(headers)
        self._done = not self._chunked and self._remaining == 0

    def readable(self) -> bool:
        return True

    def _next_chunk(self) -> bool:
        """Advance to the next chunk; False at the terminal chunk."""
        line = self._raw.readline(_MAX_CHUNK_LINE + 2)
        if line in (b"\r\n", b"\n"):  # CRLF after the previous chunk
            line = self._raw.readline(_MAX_CHUNK_LINE + 2)
        if not line or len(line) > _MAX_CHUNK_LINE:
            raise ValueError("bad chunk-size line")
        size_s = line.split(b";", 1)[0].strip()
        try:
            size = int(size_s, 16)
        except ValueError:
            raise ValueError(f"bad chunk size {size_s[:32]!r}")
        if size == 0:
            # trailers run until a blank line (or EOF)
            while True:
                t = self._raw.readline(_MAX_LINE + 1)
                if t in (b"\r\n", b"\n", b""):
                    break
            self._done = True
            return False
        self._remaining = size
        return True

    def read(self, n: int = -1) -> bytes:
        if self._done:
            return b""
        if not self._chunked:
            want = self._remaining if n is None or n < 0 \
                else min(n, self._remaining)
            data = self._raw.read(want) if want else b""
            self._remaining -= len(data)
            if self._remaining <= 0 or len(data) < want:
                self._done = True  # satisfied (or peer hung up early)
            return data
        out = []
        budget = None if n is None or n < 0 else n
        while not self._done and (budget is None or budget > 0):
            if self._remaining == 0 and not self._next_chunk():
                break
            want = self._remaining if budget is None \
                else min(budget, self._remaining)
            data = self._raw.read(want)
            if len(data) < want:  # peer hung up mid-chunk
                self._done = True
            self._remaining -= len(data)
            out.append(data)
            if budget is not None:
                budget -= len(data)
        return b"".join(out)

    def read_all(self) -> bytes:
        return self.read(-1)

    def drain(self) -> None:
        """Discard whatever the handler left unread."""
        while not self._done:
            if not self.read(_SPAN_COPY):
                break

    def close(self) -> None:
        pass


class FileSpan:
    """A file-backed response body: (fd, offset, length).

    Produced by the volume read path's zero-copy seam
    (Store.read_needle_span) and consumed by ``send_span``: async
    connections hand it straight to os.sendfile (payload never enters
    Python), threaded connections stream it in `_SPAN_COPY` pread
    windows. Owns its (dup'd) fd; close() exactly once."""

    __slots__ = ("fd", "offset", "length")

    def __init__(self, fd: int, offset: int, length: int):
        self.fd = fd
        self.offset = offset
        self.length = length

    def close(self) -> None:
        if self.fd >= 0:
            try:
                os.close(self.fd)
            except OSError:
                pass
            self.fd = -1

    def __del__(self):  # leak-proofing; normal paths close explicitly
        self.close()


@dataclass
class ServeConfig:
    """-serve.* knobs, one object per server role (0 = built-in
    default; see util/async_server.py for the defaults)."""
    async_mode: bool = False
    max_conns: int = 0
    keepalive_budget: int = 0
    workers: int = 0
    sendfile: bool = True


def make_http_server(addr, handler_cls, role: str = "",
                     serve: Optional[ServeConfig] = None):
    """The one seam every role builds its data-plane HTTP server
    through: the selector-based async core under -serve.async, the
    thread-per-connection TrackingHTTPServer otherwise. The async
    module is imported ONLY under the flag — a default server
    constructs no selector, no state-machine objects, no pool
    (test_perf_gates.test_serve_async_disabled_overhead)."""
    if serve is not None and serve.async_mode:
        from seaweedfs_tpu.util.async_server import AsyncHTTPServer
        return AsyncHTTPServer(addr, handler_cls, role=role,
                               max_conns=serve.max_conns,
                               keepalive_budget=serve.keepalive_budget,
                               workers=serve.workers)
    return TrackingHTTPServer(addr, handler_cls)


class TrackingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer that force-closes established connections on
    server_close.

    With keep-alive clients, handler threads park in readline() waiting
    for the next request; stock server_close only closes the LISTENER,
    so a stopped server keeps answering on old connections — and once
    the OS reuses its port for a new server, pooled clients talk to a
    ghost. Tracking and shutting the accepted sockets makes stop mean
    stop (Go's http.Server.Close closes active conns the same way)."""

    daemon_threads = True

    def __init__(self, *args, **kwargs):
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        super().__init__(*args, **kwargs)

    def process_request(self, request, client_address):
        with self._conns_lock:
            self._conns.add(request)
        super().process_request(request, client_address)

    def shutdown_request(self, request):
        with self._conns_lock:
            self._conns.discard(request)
        super().shutdown_request(request)

    def server_close(self):
        super().server_close()
        with self._conns_lock:
            conns = list(self._conns)
            self._conns.clear()
        for s in conns:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass


class FastHandler(BaseHTTPRequestHandler):
    """BaseHTTPRequestHandler with a fast header parser."""

    protocol_version = "HTTP/1.1"
    disable_nagle_algorithm = True
    # Buffered wfile: stock socketserver uses an unbuffered writer, so
    # every response costs two sendall syscalls (joined header block,
    # then body) and wakes the peer twice — measurable at data-plane
    # rates on loopback. handle_one_request() flushes after each
    # handler, so buffering coalesces each response into ONE send
    # (Go's net/http response writer buffers the same way).
    wbufsize = 65536
    # set per-instance by the async core: the _Connection driving this
    # request, None when the threaded model is serving. Handlers use
    # it to choose zero-copy paths (volume GET sendfile); everything
    # else is model-agnostic. One attr read on the hot path when off.
    async_conn = None

    def handle_expect_100(self):
        """The interim 100 Continue must reach the client BEFORE we
        block reading the body — flush past the buffered wfile. The
        async core sends the interim reply itself at head-parse time
        (the body hasn't been received yet when the shim re-parses),
        so a shim marked _expect_sent skips the write."""
        if getattr(self, "_expect_sent", False):
            return True
        ok = super().handle_expect_100()
        if ok:
            self.wfile.flush()
        return ok

    def _head_bytes(self, code: int, length: int, headers=None,
                    ctype: str = "") -> bytes:
        """One response head as a single bytes blob — shared by
        fast_reply (in-memory body) and send_span (file-backed body)
        so the two reply styles cannot diverge on the wire."""
        reason = _REASONS.get(code, "")
        # mirrored by the instrumented send_response hook: the cluster
        # tracer's tail sampler keeps 5xx requests by final status
        self.last_status = code
        parts = [f"HTTP/1.1 {code} {reason}\r\nDate: {http_date()}\r\n"]
        if ctype:
            parts.append(f"Content-Type: {ctype}\r\n")
        if headers:
            for k, v in headers.items():
                parts.append(f"{k}: {v}\r\n")
        if self.close_connection:
            parts.append("Connection: close\r\n")
        parts.append(f"Content-Length: {length}\r\n\r\n")
        return "".join(parts).encode("latin-1")

    def fast_reply(self, code: int, body: bytes = b"",
                   headers=None, ctype: str = "") -> None:
        """Whole response head as one f-string + one buffered write.

        send_response/send_header/end_headers cost ~5 Python calls and
        a list-append/join per response; at small-file data-plane rates
        that machinery is a measurable share of the server's cycles.
        Semantics kept: Date header, Connection: close when the request
        asked for it, no body on HEAD. (Go's net/http writes its
        response head the same single-buffer way.)"""
        self.wfile.write(self._head_bytes(code, len(body), headers,
                                          ctype))
        if body and self.command != "HEAD":
            self.wfile.write(body)

    def send_span(self, code: int, span: "FileSpan", headers=None,
                  ctype: str = "") -> None:
        """Reply whose body is a FileSpan: identical head bytes to
        fast_reply, body straight from the file. On an async
        connection the span rides os.sendfile (zero-copy, the
        dominant-verb GET path); on a threaded connection it streams
        in bounded pread windows — byte-identical either way."""
        self.wfile.write(self._head_bytes(code, span.length, headers,
                                          ctype))
        if span.length == 0 or self.command == "HEAD":
            span.close()
            return
        add_span = getattr(self.wfile, "add_span", None)
        if add_span is not None:  # async response writer
            add_span(span)
            return
        off, remaining = span.offset, span.length
        try:
            while remaining > 0:
                chunk = os.pread(span.fd, min(_SPAN_COPY, remaining),
                                 off)
                if not chunk:
                    raise OSError(
                        f"file span truncated at {off} "
                        f"({remaining} bytes short)")
                self.wfile.write(chunk)
                off += len(chunk)
                remaining -= len(chunk)
        finally:
            span.close()

    def read_body(self) -> bytes:
        """The full request body, whatever the framing: the installed
        BodyReader decodes Content-Length or chunked identically on
        both server models; bodiless requests read b"" for free."""
        r = self.rfile
        if isinstance(r, BodyReader):
            return r.read_all()
        n = parse_content_length(self.headers)
        return r.read(n) if n else b""

    def handle_one_request(self):
        """Stock dispatch + body framing: a request that declares a
        body gets a BodyReader installed as self.rfile for the
        handler's duration, and whatever the handler leaves unread is
        drained afterwards — so keep-alive and pipelined framing
        survive handlers that ignore (or partially read) bodies, and
        chunked uploads work on every role. Bodiless requests (the
        dominant GET path) take the stock path with zero new
        objects."""
        try:
            self.raw_requestline = self.rfile.readline(_MAX_LINE + 1)
            if len(self.raw_requestline) > _MAX_LINE:
                self.requestline = ""
                self.request_version = ""
                self.command = ""
                self.send_error(414)
                return
            if not self.raw_requestline:
                self.close_connection = True
                return
            if not self.parse_request():
                return
            body = None
            if is_chunked(self.headers) or \
                    parse_content_length(self.headers) > 0:
                body = BodyReader(self.rfile, self.headers)
            mname = "do_" + self.command
            if not hasattr(self, mname):
                self.send_error(
                    501, "Unsupported method (%r)" % self.command)
                return
            if body is None:
                getattr(self, mname)()
            else:
                raw = self.rfile
                self.rfile = body
                try:
                    getattr(self, mname)()
                finally:
                    self.rfile = raw
                    if not self.close_connection:
                        try:
                            body.drain()
                        except (OSError, ValueError):
                            self.close_connection = True
            self.wfile.flush()
        except TimeoutError as e:
            self.log_error("Request timed out: %r", e)
            self.close_connection = True

    def date_time_string(self, timestamp=None):
        if timestamp is not None:
            return super().date_time_string(timestamp)
        return http_date()

    def parse_request(self) -> bool:
        """Semantics of BaseHTTPRequestHandler.parse_request (status
        codes and close_connection behavior) with dict headers."""
        self.command = None
        self.request_version = version = self.default_request_version
        self.close_connection = True
        requestline = str(self.raw_requestline, "iso-8859-1").rstrip("\r\n")
        self.requestline = requestline
        words = requestline.split()
        if len(words) == 3:
            command, path, version = words
            if not version.startswith("HTTP/"):
                self.send_error(400, f"Bad request version ({version!r})")
                return False
            try:
                major, _, minor = version[5:].partition(".")
                version_number = (int(major), int(minor))
            except ValueError:
                self.send_error(400, f"Bad request version ({version!r})")
                return False
            if version_number >= (1, 1) and \
                    self.protocol_version >= "HTTP/1.1":
                self.close_connection = False
            if version_number >= (2, 0):
                self.send_error(505,
                                f"Invalid HTTP version ({version!r})")
                return False
        elif len(words) == 2:
            command, path = words
            self.close_connection = True
            if command != "GET":
                self.send_error(400,
                                f"Bad HTTP/0.9 request type ({command!r})")
                return False
        elif not words:
            return False
        else:
            self.send_error(400, f"Bad request syntax ({requestline!r})")
            return False
        self.command, self.path, self.request_version = \
            command, path, version

        headers = HeaderDict()
        err = parse_header_block(self.rfile, headers,
                                 max_headers=_MAX_HEADERS)
        if err == "toolong":
            self.send_error(431, "Header line too long")
            return False
        if err == "toomany":
            self.send_error(431, "Too many headers")
            return False
        self.headers = headers
        return self._finish_parse(headers)

    def _finish_parse(self, headers: "HeaderDict") -> bool:
        conntype = headers.get("connection", "").lower()
        if conntype == "close":
            self.close_connection = True
        elif conntype == "keep-alive" and \
                self.protocol_version >= "HTTP/1.1":
            self.close_connection = False
        if headers.get("expect", "").lower() == "100-continue" and \
                self.protocol_version >= "HTTP/1.1" and \
                self.request_version != "HTTP/0.9":
            if not self.handle_expect_100():
                return False
        return True
