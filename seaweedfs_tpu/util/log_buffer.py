"""In-memory buffered event log with periodic flush
(reference: weed/util/log_buffer/log_buffer.go).

Mutation events are appended as (ts_ns, key, payload) records; a
background ticker flushes the buffer to a sink callback every
`flush_seconds` (2s in the reference) or when the buffer fills. Recent
records stay readable in memory so subscribers can catch up without
touching the flushed files; older reads fall back to the flush sink's
storage (handled by the caller, filer_notify).
"""

from __future__ import annotations

import struct
import threading
import time
from dataclasses import dataclass
from typing import Callable, List, Optional

BUFFER_LIMIT = 4 << 20   # flush when in-memory bytes exceed this
PREV_BUFFERS = 32        # retained flushed generations for catch-up reads


@dataclass
class LogEntry:
    ts_ns: int
    partition_key_hash: int
    data: bytes

    def pack(self) -> bytes:
        """uint32 length-prefixed wire framing, like the reference's
        flushed log files (filer_notify.go)."""
        body = struct.pack(">qi", self.ts_ns, self.partition_key_hash) + self.data
        return struct.pack(">I", len(body)) + body

    @classmethod
    def unpack_stream(cls, blob: bytes) -> List["LogEntry"]:
        out, pos = [], 0
        while pos + 4 <= len(blob):
            (n,) = struct.unpack_from(">I", blob, pos)
            pos += 4
            if pos + n > len(blob):
                break  # torn tail
            ts_ns, key = struct.unpack_from(">qi", blob, pos)
            out.append(cls(ts_ns, key, blob[pos + 12:pos + n]))
            pos += n
        return out


class LogBuffer:
    """Thread-safe append log with timed flush and in-memory replay."""

    def __init__(self, flush_seconds: float = 2.0,
                 flush_fn: Optional[Callable[[int, int, bytes], None]] = None,
                 notify_fn: Optional[Callable[[], None]] = None):
        self.flush_fn = flush_fn
        self.notify_fn = notify_fn
        self.flush_seconds = flush_seconds
        self._lock = threading.Condition()
        self._entries: List[LogEntry] = []
        self._bytes = 0
        self._prev: List[List[LogEntry]] = []   # flushed, still in memory
        self._last_ts = 0
        self._stopping = False
        # flusher spawns lazily on the first add(): a process that
        # never appends a meta event never grows this thread (the
        # zero-threads-until-first-use house rule, `gate` check)
        self._flusher: Optional[threading.Thread] = None

    def _ensure_flusher(self) -> None:  # requires(self._lock)
        if self._flusher is None and not self._stopping:
            # lint: thread-ok(periodic flush daemon owns no request context)
            self._flusher = threading.Thread(
                target=self._flush_loop, name="log-buffer-flush",
                daemon=True)
            self._flusher.start()

    def add(self, data: bytes, key_hash: int = 0,
            ts_ns: Optional[int] = None) -> int:
        with self._lock:
            self._ensure_flusher()
            ts = ts_ns if ts_ns is not None else time.time_ns()
            if ts <= self._last_ts:      # strictly monotonic, like the ref
                ts = self._last_ts + 1
            self._last_ts = ts
            self._entries.append(LogEntry(ts, key_hash, data))
            self._bytes += len(data) + 16
            if self._bytes >= BUFFER_LIMIT:
                self._flush_locked()
            self._lock.notify_all()
        if self.notify_fn:
            self.notify_fn()
        return ts

    def _flush_locked(self) -> None:  # requires(self._lock)
        if not self._entries:
            return
        batch = self._entries
        self._entries, self._bytes = [], 0
        self._prev.append(batch)
        del self._prev[:-PREV_BUFFERS]
        if self.flush_fn:
            blob = b"".join(e.pack() for e in batch)
            self.flush_fn(batch[0].ts_ns, batch[-1].ts_ns, blob)

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def _flush_loop(self) -> None:
        while not self._stopping:
            time.sleep(self.flush_seconds)
            self.flush()

    def read_since(self, ts_ns: int) -> List[LogEntry]:
        """All in-memory entries with ts > ts_ns (flushed + pending)."""
        with self._lock:
            out = [e for gen in self._prev for e in gen if e.ts_ns > ts_ns]
            out.extend(e for e in self._entries if e.ts_ns > ts_ns)
            return out

    def earliest_in_memory(self) -> Optional[int]:
        with self._lock:
            for gen in self._prev:
                if gen:
                    return gen[0].ts_ns
            return self._entries[0].ts_ns if self._entries else None

    def wait_for_data(self, after_ts_ns: int, timeout: float) -> bool:
        with self._lock:
            if self._last_ts > after_ts_ns:
                return True
            self._lock.wait(timeout)
            return self._last_ts > after_ts_ns

    def close(self) -> None:
        self._stopping = True
        self.flush()
