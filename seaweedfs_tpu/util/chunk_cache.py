"""Tiered chunk cache: memory SLRU + size-classed on-disk tiers
(reference: weed/util/chunk_cache/chunk_cache.go:16-130).

The reference caches chunks ≤1MB in memory, and on disk in three tiers
keyed by chunk size (≤1MB, ≤4MB, bigger). Here the on-disk tiers are
directories of fid-named files with byte-budget LRU eviction, and the
memory tier rides `cache.SegmentedLRU` — the same scan-resistant
probation/protected policy the volume server's read cache uses, so one
`filer.copy` of a large tree can no longer flush the filer's hot chunk
set.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Optional

from seaweedfs_tpu.cache.read_cache import SegmentedLRU

MEM_UNIT = 1 << 20        # chunks up to 1MB may live in memory
DISK_UNITS = (1 << 20, 4 << 20)   # tier boundaries


class MemCache:
    """Byte-bounded RAM tier over SegmentedLRU (scan-resistant: new
    chunks enter probation; only a second touch protects them)."""

    def __init__(self, limit_bytes: int):
        self.limit = limit_bytes
        # items up to the full budget stay admissible (the historical
        # MemCache contract; TieredChunkCache already routes oversized
        # chunks to disk by size class)
        self._lru = SegmentedLRU(limit_bytes, max_item_bytes=limit_bytes)

    def get(self, key: str) -> Optional[bytes]:
        return self._lru.get(key)

    def set(self, key: str, value: bytes) -> None:
        self._lru.set(key, value)


class DiskTier:
    def __init__(self, directory: str, limit_bytes: int):
        self.dir = directory
        self.limit = limit_bytes
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        self._lru: OrderedDict[str, int] = OrderedDict()  # guarded_by(self._lock)
        self._bytes = 0  # guarded_by(self._lock)
        for name in os.listdir(directory):
            p = os.path.join(directory, name)
            if os.path.isfile(p):
                sz = os.path.getsize(p)
                self._lru[name] = sz
                self._bytes += sz

    @staticmethod
    def _fname(key: str) -> str:
        return key.replace("/", "_").replace(",", "_")

    def get(self, key: str) -> Optional[bytes]:
        name = self._fname(key)
        with self._lock:
            if name not in self._lru:
                return None
            self._lru.move_to_end(name)
        try:
            with open(os.path.join(self.dir, name), "rb") as f:
                return f.read()
        except OSError:
            return None

    def set(self, key: str, value: bytes) -> None:
        name = self._fname(key)
        tmp = os.path.join(self.dir, name + ".tmp")
        with open(tmp, "wb") as f:
            f.write(value)
        os.replace(tmp, os.path.join(self.dir, name))
        with self._lock:
            self._bytes -= self._lru.pop(name, 0)
            self._lru[name] = len(value)
            self._bytes += len(value)
            while self._bytes > self.limit and self._lru:
                victim, sz = self._lru.popitem(last=False)
                self._bytes -= sz
                try:
                    os.unlink(os.path.join(self.dir, victim))
                except OSError:
                    pass


class TieredChunkCache:
    """get/set by fileId; routes by chunk size like the reference."""

    def __init__(self, mem_limit_bytes: int = 64 << 20,
                 disk_dir: Optional[str] = None,
                 disk_limit_bytes: int = 256 << 20):
        self.mem = MemCache(mem_limit_bytes)
        self.tiers = []
        if disk_dir:
            per = disk_limit_bytes // 4
            self.tiers = [
                DiskTier(os.path.join(disk_dir, "t0"), per),
                DiskTier(os.path.join(disk_dir, "t1"), per),
                DiskTier(os.path.join(disk_dir, "t2"), disk_limit_bytes - 2 * per),
            ]

    def _tier(self, size: int) -> Optional[DiskTier]:
        if not self.tiers:
            return None
        if size <= DISK_UNITS[0]:
            return self.tiers[0]
        if size <= DISK_UNITS[1]:
            return self.tiers[1]
        return self.tiers[2]

    def get(self, file_id: str, size_hint: int = 0) -> Optional[bytes]:
        v = self.mem.get(file_id)
        if v is not None:
            return v
        for t in self.tiers:
            v = t.get(file_id)
            if v is not None:
                if len(v) <= MEM_UNIT:
                    self.mem.set(file_id, v)
                return v
        return None

    def set(self, file_id: str, data: bytes) -> None:
        if len(data) <= MEM_UNIT:
            self.mem.set(file_id, data)
        t = self._tier(len(data))
        if t is not None:
            t.set(file_id, data)
