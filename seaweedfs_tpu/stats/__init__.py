"""Prometheus-style metrics (reference: weed/stats)."""

from seaweedfs_tpu.stats.metrics import (  # noqa: F401
    Counter, Gauge, Histogram, Registry, REGISTRY,
    start_metrics_server,
)
