"""Prometheus-style metrics + span tracing + cluster-wide trace
propagation (reference: weed/stats)."""

from seaweedfs_tpu.stats import trace  # noqa: F401
from seaweedfs_tpu.stats.metrics import (  # noqa: F401
    Counter, Gauge, Histogram, Registry, REGISTRY,
    instrument_grpc_method, instrument_http_handler,
    start_metrics_server,
)
from seaweedfs_tpu.stats import cluster_trace  # noqa: E402,F401
