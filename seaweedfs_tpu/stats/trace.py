"""Lightweight span tracer: where the pipeline's time actually goes.

The fleet scheduler (ec/fleet.py) runs as reader pool -> fused RS
dispatch -> tagged retire -> per-volume writer lanes, four thread
families handing work to each other — a cProfile flattens that into
function totals and loses the overlap structure, which is exactly what
a perf PR needs to see. This module records *spans*: named, tagged
[t0, t0+dur) intervals per thread, with parent/child nesting inside a
thread (thread-local stack) and explicit handoff tokens across threads
(the packing thread mints a token, the writer lane opens its span under
it), exported as Chrome trace-event JSON that chrome://tracing and
Perfetto load directly.

Cost discipline: tracing is OFF by default and `span()` checks the
module flag before allocating anything — the disabled path is one
function call returning a shared no-op context manager (gated by
tests/test_perf_gates.py). Enabled spans land in a bounded ring buffer
(deque append is atomic under the GIL; no lock on the hot path), so a
forgotten-enabled tracer costs memory-bounded ring slots, never
unbounded growth.

Set SEAWEED_TRACE=1 to enable at import (how bench_profile.py turns on
tracing inside spawned server subprocesses); in-process callers use
enable()/disable(). `/debug/trace` on the metrics port serves the
Chrome JSON of everything currently in the ring.
"""

from __future__ import annotations

import contextvars
import itertools
import json
import os
import random
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

# Ring capacity: a fleet encode of 64 volumes emits a few spans per
# chunk — tens of thousands of spans for a big run. 1<<17 slots keep
# the whole run while bounding memory (~100 bytes/span -> ~13MB worst
# case).
DEFAULT_CAPACITY = 1 << 17

_enabled = bool(os.environ.get("SEAWEED_TRACE", "") not in ("", "0"))
_ring: deque = deque(maxlen=DEFAULT_CAPACITY)
_ids = itertools.count(1)      # .__next__ is atomic under the GIL
# Span ids are 64-bit and unique ACROSS processes: a per-process random
# high word (bit 62 forced so ids never collide with the small ids of a
# process that lost its randomness) ORed with the local counter. The
# cluster stitcher dedupes by span id, so two processes must never mint
# the same one.
_ID_BASE = (random.getrandbits(30) | (1 << 29)) << 33
_tls = threading.local()
_thread_names: Dict[int, str] = {}

# perf_counter -> wall-clock offset, taken once at import: the cluster
# collector exports span timestamps on the epoch timebase so spans from
# different PROCESSES line up in one stitched view (NTP-grade skew is
# acceptable at the millisecond scale these traces are read at).
EPOCH_OFFSET = time.time() - time.perf_counter()

# Cluster-trace hook (stats/cluster_trace.py): when on, spans are also
# appended to the ambient request's bounded buffer, carried across
# threads by contextvars (FanOutPool copies the context at submit).
# Kept as one module flag + one ContextVar so the fully-disabled span()
# fast path stays two attribute checks.
_cluster_enabled = False
_req_ctx: "contextvars.ContextVar[Optional[object]]" = \
    contextvars.ContextVar("seaweed_trace_req", default=None)


def next_span_id() -> int:
    """A fresh 64-bit process-unique span/trace id."""
    return _ID_BASE | next(_ids)


def request_ctx():
    """The ambient cluster-trace request context (or None)."""
    return _req_ctx.get()


def is_enabled() -> bool:
    return _enabled


def enable(capacity: Optional[int] = None) -> None:
    """Turn tracing on (optionally resizing the ring, which clears it)."""
    global _enabled, _ring
    if capacity is not None and capacity != _ring.maxlen:
        _ring = deque(maxlen=capacity)
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def clear() -> None:
    _ring.clear()
    _thread_names.clear()


class _NoopSpan:
    """Shared do-nothing context manager: the disabled fast path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def token(self) -> None:
        return None


NOOP = _NoopSpan()


class Span:
    __slots__ = ("name", "tags", "id", "parent_id", "t0", "dur", "tid",
                 "trace_id")

    def __init__(self, name: str, parent: Optional[int], tags: dict):
        self.name = name
        self.tags = tags
        self.id = _ID_BASE | next(_ids)
        self.parent_id = parent
        self.t0 = 0.0
        self.dur = 0.0
        self.tid = 0
        self.trace_id = 0

    def __enter__(self) -> "Span":
        tid = threading.get_ident()
        self.tid = tid
        if tid not in _thread_names:
            _thread_names[tid] = threading.current_thread().name
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        if self.parent_id is None and stack:
            self.parent_id = stack[-1]
        if _cluster_enabled:
            ctx = _req_ctx.get()
            if ctx is not None:
                self.trace_id = ctx.trace_id
                ctx.current = self.name   # flight-recorder "where is it"
                if self.parent_id is None:
                    # first span on a pool/hedge worker thread: parent
                    # to the request span across the thread boundary
                    self.parent_id = ctx.span_id
        stack.append(self.id)
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        self.dur = time.perf_counter() - self.t0
        stack = getattr(_tls, "stack", None)
        if stack and stack[-1] == self.id:
            stack.pop()
        if _enabled:
            _ring.append(self)
        if _cluster_enabled:
            ctx = _req_ctx.get()
            if ctx is not None:
                ctx.add_span(self)
        return False

    def token(self) -> int:
        """Handoff token: pass to span(parent=...) in another thread so
        the child nests under this span across the thread boundary."""
        return self.id


def span(name: str, parent: Optional[int] = None, **tags):
    """Context manager recording one span; no-op while disabled.

    `parent` is a handoff token from Span.token() (or handoff()) for
    cross-thread nesting; same-thread nesting is automatic. Callers on
    paths hot enough that even the kwargs dict matters should gate on
    is_enabled() themselves.

    Enabled means EITHER the local span ring (SEAWEED_TRACE) or the
    cluster tracer (stats/cluster_trace.py) is on — with both off the
    fast path is two module-flag checks returning the shared no-op.
    """
    if not _enabled and not _cluster_enabled:
        return NOOP
    return Span(name, parent, tags)


def active() -> bool:
    """True when span() would record anything right now — the guard
    hot callers use before building a tags dict."""
    return _enabled or (_cluster_enabled and _req_ctx.get() is not None)


def handoff() -> Optional[int]:
    """Token for the innermost open span of THIS thread (None when
    disabled or no span is open): hand it to the thread that continues
    the work so its spans parent here."""
    if not _enabled:
        return None
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


# -- export -------------------------------------------------------------------

def spans() -> List[Span]:
    """Snapshot of the ring, oldest first."""
    return list(_ring)


def chrome_trace(extra: Sequence[Span] = ()) -> dict:
    """Chrome trace-event JSON object (the 'JSON Object Format':
    {"traceEvents": [...]}), loadable by chrome://tracing / Perfetto.

    Spans become 'X' (complete) events; thread names become 'M'
    metadata events so Perfetto labels the lanes. ts/dur are in
    microseconds on the perf_counter timebase (arbitrary origin is fine
    for these viewers).
    """
    pid = os.getpid()
    events: List[dict] = []
    for tid, tname in list(_thread_names.items()):
        events.append({"ph": "M", "pid": pid, "tid": tid,
                       "name": "thread_name", "args": {"name": tname}})
    for s in list(_ring) + list(extra):
        ev = {"ph": "X", "pid": pid, "tid": s.tid, "name": s.name,
              "ts": round(s.t0 * 1e6, 3), "dur": round(s.dur * 1e6, 3)}
        args = dict(s.tags) if s.tags else {}
        args["id"] = s.id
        if s.parent_id is not None:
            args["parent"] = s.parent_id
        if s.trace_id:
            args["trace"] = f"{s.trace_id:016x}"
        ev["args"] = args
        events.append(ev)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def span_dict(s: Span) -> dict:
    """One span as the cluster collector exports it: epoch-based
    microsecond timestamps (comparable across processes), hex ids."""
    d = {"name": s.name,
         "ts_us": round((s.t0 + EPOCH_OFFSET) * 1e6, 3),
         "dur_us": round(s.dur * 1e6, 3),
         "id": f"{s.id:016x}",
         "tid": s.tid}
    if s.parent_id:
        d["parent"] = f"{s.parent_id:016x}"
    if s.trace_id:
        d["trace"] = f"{s.trace_id:016x}"
    if s.tags:
        d["tags"] = {k: str(v) for k, v in s.tags.items()}
    return d


def chrome_trace_json() -> str:
    return json.dumps(chrome_trace())


# -- rollups ------------------------------------------------------------------

def rollup(items: Optional[Sequence[Span]] = None) -> Dict[str, dict]:
    """Per-span-name totals: {name: {count, total_s, max_s}} — the
    stage-attribution summary bench.py attaches to its BENCH JSON."""
    out: Dict[str, dict] = {}
    for s in (spans() if items is None else items):
        r = out.get(s.name)
        if r is None:
            r = out[s.name] = {"count": 0, "total_s": 0.0, "max_s": 0.0}
        r["count"] += 1
        r["total_s"] += s.dur
        r["max_s"] = max(r["max_s"], s.dur)
    for r in out.values():
        r["total_s"] = round(r["total_s"], 6)
        r["max_s"] = round(r["max_s"], 6)
    return out


def busy_union_s(items: Sequence[Span], t0: float, t1: float,
                 prefixes: Optional[Sequence[str]] = None) -> float:
    """Seconds of [t0, t1] covered by at least one span (optionally
    restricted to names starting with any of `prefixes`): the coverage
    measure behind the bench --trace >=90% acceptance gate. Spans run
    on many threads, so this is interval union, not a sum."""
    ivals = []
    for s in items:
        if prefixes is not None and \
                not any(s.name.startswith(p) for p in prefixes):
            continue
        a, b = max(s.t0, t0), min(s.t0 + s.dur, t1)
        if b > a:
            ivals.append((a, b))
    ivals.sort()
    covered = 0.0
    cur_a = cur_b = None
    for a, b in ivals:
        if cur_b is None or a > cur_b:
            if cur_b is not None:
                covered += cur_b - cur_a
            cur_a, cur_b = a, b
        else:
            cur_b = max(cur_b, b)
    if cur_b is not None:
        covered += cur_b - cur_a
    return covered
