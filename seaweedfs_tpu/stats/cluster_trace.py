"""Cluster-wide distributed tracing + flight recorder.

PR 2's span tracer sees one process; a request that fans
filer -> volume -> replica -> EC shard shatters into disconnected
per-process rings. This module is the Dapper-style glue:

  propagate   every traced request carries a 64-bit trace id and its
              current span id across hops — the `X-Seaweed-Trace`
              header on HTTP (riding util/http_client, the exact seam
              X-Seaweed-Deadline uses) and `x-seaweed-trace` metadata
              on gRPC (riding the rpc stubs). The shared ingress
              wrappers (stats.metrics.instrument_http_handler /
              instrument_grpc_method) re-anchor the context into the
              handler, and FanOutPool's contextvars.copy_context()
              carries it across thread hops for free.
  tail-sample ids always propagate; full span DETAIL survives only for
              requests that finish slow (duration >= max(-trace.slowMs,
              the tracked per-verb p95)) or errored, pinned in a
              bounded per-process ring. A short `recent` ring keeps the
              last N finished requests regardless, so stitching a slow
              request's trace still recovers the FAST downstream hops
              it touched (a tail decision on the filer cannot reach
              back into a replica that already dropped its spans — the
              grace ring is what makes cluster stitching whole).
              `-trace.sample` head-samples a fraction unconditionally
              (the sampled bit rides the header so downstream keeps
              too).
  recorder    `/debug/requests` lists in-flight requests (verb, age,
              current span, peer, remaining deadline budget, trace
              id); a rate-limited slow-request log line carries the
              trace id; OpenMetrics exemplars on the request
              histograms link /metrics buckets to trace ids.
  collect     `/debug/trace?trace_id=` returns every span this process
              holds for one trace; `cluster.trace` (shell) fans that
              over the topology and stitches one Chrome trace.

Zero-cost-disabled contract (the house rule, gated by
tests/test_perf_gates.py::test_cluster_trace_disabled_overhead): off
by default; each ingress/egress seam pays ONE module-flag check; no
thread is ever spawned (pure data structures). Enable with
-trace.sample / -trace.slowMs or SEAWEED_TRACE_SAMPLE=<fraction>.
"""

from __future__ import annotations

import os
import random
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from seaweedfs_tpu.resilience import deadline as deadline_mod
from seaweedfs_tpu.stats import trace
from seaweedfs_tpu.util import wlog

log = wlog.logger("trace")

# Wire names (HTTP header / gRPC metadata key). Value format:
# "<trace_id:016x>-<span_id:016x>[-s]"; the "-s" suffix marks a
# head-sampled trace so every downstream hop keeps its spans too.
HEADER = "X-Seaweed-Trace"
HEADER_LOWER = "x-seaweed-trace"
GRPC_KEY = "x-seaweed-trace"

# Retention bounds (per process).
SAMPLED_RING = 256        # kept (slow/errored/head-sampled) requests
RECENT_RING = 1024        # grace ring of ALL finished traced requests
MAX_SPANS_PER_REQUEST = 512

# Per-verb latency window for the tail threshold (the Hedger's p95
# discipline: sorted-window estimate, recomputed every N observations).
_P95_WINDOW = 128
_P95_RECALC = 16

# Rate limit for the structured slow-request log line.
_SLOW_LOG_INTERVAL_S = 1.0

_enabled = False
slow_ms = 200.0           # floor for the tail-keep threshold
sample = 0.0              # head-sample fraction (0..1)

_lock = threading.Lock()
# live_count()/table snapshots read lock-free (flight-recorder views
# may be one request stale); insert/remove lock
_live: Dict[int, "TraceCtx"] = {}  # guarded_by(_lock, writes)
_sampled: deque = deque(maxlen=SAMPLED_RING)
_recent: deque = deque(maxlen=RECENT_RING)
# per-verb trackers: finish() inserts via GIL-atomic setdefault on the
# hot path (the tracker's own window lock guards its contents); only
# reset() needs the module lock
_p95: Dict[str, "_VerbP95"] = {}  # guarded_by(_lock, writes)
_last_slow_log = 0.0


class _VerbP95:
    __slots__ = ("lat", "since", "p95", "_lock")

    def __init__(self):
        self.lat: deque = deque(maxlen=_P95_WINDOW)
        self.since = 0
        self.p95 = 0.0
        self._lock = threading.Lock()

    def observe(self, seconds: float) -> float:
        # locked like the Hedger's window: sorted() iterates the deque,
        # and a concurrent append from another finishing request would
        # raise "deque mutated during iteration" out of the ingress
        # wrapper's finally block
        with self._lock:
            self.lat.append(seconds)
            self.since += 1
            if self.since >= _P95_RECALC or len(self.lat) < _P95_RECALC:
                self.since = 0
                ordered = sorted(self.lat)
                self.p95 = ordered[int(0.95 * (len(ordered) - 1))]
            return self.p95


class TraceCtx:
    """One traced request in this process: its identity, its request
    span, and the bounded buffer its spans accumulate into. The buffer
    OBJECT is shared across thread hops (contextvars copies are
    shallow), so FanOutPool / hedge workers append to the same list."""

    __slots__ = ("trace_id", "span_id", "head", "role", "verb", "path",
                 "peer", "server", "t0", "buf", "dropped", "error",
                 "deadline", "current", "_span", "_token", "_key")

    def __init__(self, trace_id: int, parent_span: Optional[int],
                 head: bool, role: str, verb: str, path: str,
                 peer: str, server: str):
        self.trace_id = trace_id
        self.head = head
        self.role = role
        self.verb = verb
        self.path = path
        self.peer = peer
        self.server = server
        self.buf: List[trace.Span] = []
        self.dropped = 0
        self.error = False
        self.deadline = deadline_mod.get()
        # the request span: root of everything this process does for
        # the request; its parent is the CALLER's span from the header
        sp = trace.Span(f"request.{role}.{verb}", parent_span,
                        {"path": path, "peer": peer, "server": server})
        self._span = sp
        sp.trace_id = trace_id
        self.span_id = sp.id
        # most-recently-entered span name: the flight recorder's
        # "current span" column (approximate under concurrency, which
        # is fine for a live debugging table)
        self.current = sp.name
        self.t0 = 0.0       # set at begin()
        self._token = None
        self._key = sp.id

    def add_span(self, s: trace.Span) -> None:
        if len(self.buf) < MAX_SPANS_PER_REQUEST:
            self.buf.append(s)
        else:
            self.dropped += 1

    def trace_hex(self) -> str:
        return f"{self.trace_id:016x}"

    def current_span_name(self) -> str:
        return self.current

    def spans(self) -> List[dict]:
        out = [trace.span_dict(s) for s in [self._span] + self.buf]
        for d in out:
            d["role"] = self.role
            d["server"] = self.server
        return out


# -- enable/disable -----------------------------------------------------------


def enabled() -> bool:
    return _enabled


def enable(sample_fraction: Optional[float] = None,
           slow_threshold_ms: Optional[float] = None) -> None:
    global _enabled, sample, slow_ms
    if sample_fraction is not None:
        sample = min(max(float(sample_fraction), 0.0), 1.0)
    if slow_threshold_ms is not None:
        slow_ms = max(float(slow_threshold_ms), 0.0)
    _enabled = True
    trace._cluster_enabled = True
    from seaweedfs_tpu.stats.metrics import TraceLiveGauge
    TraceLiveGauge.set_function(live_count)


def disable() -> None:
    global _enabled
    _enabled = False
    trace._cluster_enabled = False


def reset() -> None:
    """Drop all retained state (tests)."""
    with _lock:
        _live.clear()
        _sampled.clear()
        _recent.clear()
        _p95.clear()


# -- header codec -------------------------------------------------------------


def format_header(trace_id: int, span_id: int, head: bool = False) -> str:
    v = f"{trace_id:016x}-{span_id:016x}"
    return v + "-s" if head else v


def parse_header(value) -> Optional[Tuple[int, int, bool]]:
    """(trace_id, parent_span_id, head_sampled), or None on junk — a
    malformed header must never fail the request, it just starts a
    fresh trace."""
    if not value:
        return None
    parts = str(value).split("-")
    if len(parts) < 2:
        return None
    try:
        tid = int(parts[0], 16)
        sid = int(parts[1], 16)
    except ValueError:
        return None
    if tid == 0:
        return None
    return tid, sid, len(parts) > 2 and parts[2] == "s"


def outbound_header() -> Optional[str]:
    """Header/metadata value for the next hop: the ambient trace id
    plus the INNERMOST open span of this thread (so the remote request
    span nests under the local client-side span), falling back to the
    request span when no local span is open."""
    ctx = trace.request_ctx()
    if ctx is None:
        return None
    parent = trace.handoff() if trace._enabled else None
    if parent is None:
        stack = getattr(trace._tls, "stack", None)
        parent = stack[-1] if stack else ctx.span_id
    return format_header(ctx.trace_id, parent, ctx.head)


# -- ingress ------------------------------------------------------------------


def begin(role: str, verb: str, path: str, header_value,
          peer: str = "", server: str = "") -> TraceCtx:
    """Open a traced request at an ingress point. Returns the ctx the
    caller must pass to finish(); the contextvar is set so every span
    (and every hop) inside the handler inherits the trace."""
    parsed = parse_header(header_value)
    if parsed is not None:
        trace_id, parent_span, head = parsed
    else:
        trace_id = trace.next_span_id()
        parent_span = None
        head = sample > 0 and random.random() < sample
    ctx = TraceCtx(trace_id, parent_span, head, role, verb, path,
                   peer, server)
    ctx._span.__enter__()
    ctx.t0 = ctx._span.t0
    ctx._token = trace._req_ctx.set(ctx)
    with _lock:
        _live[ctx._key] = ctx
    return ctx


def finish(ctx: TraceCtx, exc: Optional[BaseException] = None,
           status: int = 0) -> Optional[str]:
    """Close a traced request: keep-or-drop (tail sampling), p95
    tracking, slow log. Returns the trace id hex when the request was
    KEPT (the exemplar hook), else None."""
    global _last_slow_log
    # reset the contextvar BEFORE closing the request span, or the
    # span's own __exit__ hook would append it into its own buffer
    trace._req_ctx.reset(ctx._token)
    ctx._span.__exit__(None, None, None)
    with _lock:
        _live.pop(ctx._key, None)
    dur = ctx._span.dur
    key = f"{ctx.role}.{ctx.verb}"
    tracker = _p95.get(key)
    if tracker is None:
        # lint: guard-ok(setdefault is GIL-atomic; two racing finishes agree on one tracker)
        tracker = _p95.setdefault(key, _VerbP95())
    p95 = tracker.observe(dur)
    ctx.error = ctx.error or exc is not None or status >= 500
    threshold = max(slow_ms / 1000.0, p95)
    if ctx.error:
        outcome = "error"
    elif dur >= threshold:
        outcome = "slow"
    elif ctx.head:
        outcome = "sample"
    else:
        outcome = "drop"
    from seaweedfs_tpu.stats.metrics import TraceRequestsCounter
    TraceRequestsCounter.labels(outcome).inc()
    # ring appends under the lock: spans_for/sampled_traces snapshot
    # with list(ring), and a deque mutated mid-iteration raises
    with _lock:
        _recent.append(ctx)
        if outcome != "drop":
            _sampled.append(ctx)
    if outcome == "drop":
        return None
    if outcome in ("error", "slow"):
        now = time.monotonic()
        if now - _last_slow_log >= _SLOW_LOG_INTERVAL_S:
            _last_slow_log = now
            log.warning(
                "%s request trace=%s role=%s verb=%s path=%s peer=%s "
                "dur_ms=%.1f p95_ms=%.1f spans=%d",
                outcome, ctx.trace_hex(), ctx.role, ctx.verb, ctx.path,
                ctx.peer, dur * 1e3, p95 * 1e3, len(ctx.buf) + 1)
    return ctx.trace_hex()


# -- collector / flight recorder ----------------------------------------------


def spans_for(trace_id_hex: str) -> List[dict]:
    """Every span this process holds for one trace id: pinned sampled
    requests, the recent grace ring, and still-live requests (a
    mid-stall request shows its partial spans)."""
    try:
        tid = int(trace_id_hex, 16)
    except (TypeError, ValueError):
        return []
    out: List[dict] = []
    seen = set()
    with _lock:
        live = list(_live.values())
        pinned = list(_sampled) + list(_recent)
    for ctx in pinned + live:
        if ctx.trace_id != tid or ctx._key in seen:
            continue
        seen.add(ctx._key)
        spans = ctx.spans()
        if ctx in live and spans:
            # the request span is still open: export what ran so far
            spans[0]["dur_us"] = round(
                (time.perf_counter() - ctx.t0) * 1e6, 3)
            spans[0]["in_flight"] = True
        out.extend(spans)
    return out


def sampled_traces(limit: int = 50) -> List[dict]:
    """Newest-first summaries of kept requests (the no-param
    /debug/trace?sampled=1 listing an operator starts from)."""
    out = []
    with _lock:
        newest_first = list(_sampled)[::-1]
    for ctx in newest_first[:limit]:
        out.append({"trace_id": ctx.trace_hex(), "role": ctx.role,
                    "verb": ctx.verb, "path": ctx.path,
                    "server": ctx.server,
                    "dur_ms": round(ctx._span.dur * 1e3, 3),
                    "error": ctx.error,
                    "spans": len(ctx.buf) + 1})
    return out


def live_requests() -> List[dict]:
    """The flight recorder's live table: every in-flight traced
    request in this process."""
    now = time.perf_counter()
    mono = time.monotonic()
    with _lock:
        ctxs = list(_live.values())
    out = []
    for ctx in ctxs:
        d = {"trace_id": ctx.trace_hex(), "role": ctx.role,
             "verb": ctx.verb, "path": ctx.path, "peer": ctx.peer,
             "server": ctx.server,
             # the request-span id: a STABLE identity for this request
             # (cluster.requests dedupes on it — an in-process cluster
             # answers the same table from every endpoint)
             "id": f"{ctx.span_id:016x}",
             "age_ms": round((now - ctx.t0) * 1e3, 3),
             "current_span": ctx.current_span_name(),
             "spans": len(ctx.buf) + 1}
        if ctx.deadline is not None:
            d["deadline_left_ms"] = round((ctx.deadline - mono) * 1e3, 3)
        out.append(d)
    out.sort(key=lambda d: -d["age_ms"])
    return out


def live_count() -> int:
    return len(_live)


def debug_payload(raw_path: str, role: str, server: str) -> dict:
    """The JSON body for GET /debug/trace | /debug/requests on a ROLE
    http server (the data port), shared by master/volume/filer so the
    three carve-outs cannot drift. `raw_path` is the handler's
    self.path including the query string."""
    from urllib.parse import parse_qs
    path, _, query = raw_path.partition("?")
    params = parse_qs(query) if query else {}
    if path == "/debug/requests":
        return {"role": role, "server": server,
                "requests": live_requests()}
    tid = params.get("trace_id", [""])[0]
    if tid:
        return {"role": role, "server": server, "trace_id": tid,
                "spans": spans_for(tid)}
    return {"role": role, "server": server,
            "sampled": sampled_traces()}


# env enable for spawned server subprocesses (bench_profile / bench
# --trace-cluster arm their children this way, like SEAWEED_TRACE)
_env_sample = os.environ.get("SEAWEED_TRACE_SAMPLE", "")
if _env_sample not in ("", "0"):
    try:
        enable(sample_fraction=float(_env_sample),
               slow_threshold_ms=float(
                   os.environ.get("SEAWEED_TRACE_SLOW_MS", "") or slow_ms))
    except ValueError:
        pass
