"""Minimal Prometheus client: counters/gauges/histograms with labels and
text exposition over HTTP (reference: weed/stats/metrics.go:21-182).

The reference registers request counters + latency histograms for
master/volume/filer/S3 and exposes them by pull (`-metricsPort`) or by
pushing to a gateway. Same surface here, implemented directly (the
prometheus_client package is not in the image).
"""

from __future__ import annotations

import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Iterable, Optional, Tuple

from seaweedfs_tpu.util.http_server import TrackingHTTPServer

_DEFAULT_BUCKETS = (
    0.0001, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


def _fmt_labels(names: Tuple[str, ...], values: Tuple[str, ...],
                extra: str = "") -> str:
    parts = [f'{n}="{v}"' for n, v in zip(names, values)]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_text: str,
                 label_names: Iterable[str] = ()):
        self.name = name
        self.help = help_text
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}

    def labels(self, *values: str):
        values = tuple(str(v) for v in values)
        if len(values) != len(self.label_names):
            raise ValueError(f"{self.name}: want {self.label_names}")
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = self._new_child()
                self._children[values] = child
            return child

    def _default(self):
        return self.labels() if not self.label_names else None

    def collect(self) -> str:
        raise NotImplementedError


class _CounterChild:
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount


class Counter(_Metric):
    kind = "counter"

    def _new_child(self):
        return _CounterChild()

    def inc(self, amount: float = 1.0) -> None:
        self.labels().inc(amount)

    def collect(self) -> str:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.kind}"]
        with self._lock:
            items = list(self._children.items())
        for values, child in items:
            lines.append(f"{self.name}"
                         f"{_fmt_labels(self.label_names, values)}"
                         f" {child.value}")
        return "\n".join(lines)


class _GaugeChild(_CounterChild):
    def set(self, v: float) -> None:
        with self._lock:
            self.value = v

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)


class Gauge(Counter):
    kind = "gauge"

    def _new_child(self):
        return _GaugeChild()

    def set(self, v: float) -> None:
        self.labels().set(v)


class _HistogramChild:
    __slots__ = ("buckets", "counts", "total", "count", "_lock")

    def __init__(self, buckets):
        self.buckets = buckets
        self.counts = [0] * len(buckets)
        self.total = 0.0
        self.count = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        with self._lock:
            self.total += v
            self.count += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self.counts[i] += 1

    def time(self):
        return _Timer(self)


class _Timer:
    def __init__(self, child):
        self.child = child

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.child.observe(time.perf_counter() - self.t0)
        return False


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help_text, label_names=(),
                 buckets=_DEFAULT_BUCKETS):
        super().__init__(name, help_text, label_names)
        self.buckets = tuple(buckets)

    def _new_child(self):
        return _HistogramChild(self.buckets)

    def observe(self, v: float) -> None:
        self.labels().observe(v)

    def collect(self) -> str:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.kind}"]
        with self._lock:
            items = list(self._children.items())
        for values, child in items:
            for b, c in zip(child.buckets, child.counts):
                le = 'le="%s"' % b
                lines.append(
                    f"{self.name}_bucket"
                    f"{_fmt_labels(self.label_names, values, le)}"
                    f" {c}")
            le_inf = 'le="+Inf"'
            lines.append(
                f"{self.name}_bucket"
                f"{_fmt_labels(self.label_names, values, le_inf)}"
                f" {child.count}")
            lines.append(f"{self.name}_sum"
                         f"{_fmt_labels(self.label_names, values)}"
                         f" {child.total}")
            lines.append(f"{self.name}_count"
                         f"{_fmt_labels(self.label_names, values)}"
                         f" {child.count}")
        return "\n".join(lines)


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def register(self, metric: _Metric) -> _Metric:
        with self._lock:
            return self._metrics.setdefault(metric.name, metric)

    def counter(self, name, help_text="", label_names=()) -> Counter:
        return self.register(Counter(name, help_text, label_names))

    def gauge(self, name, help_text="", label_names=()) -> Gauge:
        return self.register(Gauge(name, help_text, label_names))

    def histogram(self, name, help_text="", label_names=(),
                  buckets=_DEFAULT_BUCKETS) -> Histogram:
        return self.register(Histogram(name, help_text, label_names, buckets))

    def render(self) -> str:
        with self._lock:
            metrics = list(self._metrics.values())
        return "\n".join(m.collect() for m in metrics) + "\n"


REGISTRY = Registry()

# The reference's metric families (stats/metrics.go:21-127), shared by
# every server role in-process.
RequestCounter = REGISTRY.counter(
    "SeaweedFS_request_total", "number of requests", ("type", "name"))
RequestHistogram = REGISTRY.histogram(
    "SeaweedFS_request_seconds", "request latency", ("type", "name"))
VolumeServerVolumeCounter = REGISTRY.gauge(
    "SeaweedFS_volumeServer_volumes", "volume count", ("collection", "type"))
VolumeServerDiskSizeGauge = REGISTRY.gauge(
    "SeaweedFS_volumeServer_total_disk_size", "disk size", ("collection", "type"))


def start_metrics_server(port: int, registry: Registry = REGISTRY,
                         ip: str = "") -> ThreadingHTTPServer:
    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            body = registry.render().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # quiet
            pass

    srv = TrackingHTTPServer((ip, port), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True,
                     name=f"metrics-{port}").start()
    return srv


def loop_pushing_metric(name: str, instance: str, addr: str,
                        interval_seconds: int,
                        registry: Registry = REGISTRY,
                        stop_event: Optional[threading.Event] = None) -> threading.Thread:
    """Push-gateway loop (reference: stats/metrics.go:149)."""
    url = f"http://{addr}/metrics/job/{name}/instance/{instance}"

    def loop():
        while not (stop_event and stop_event.is_set()):
            try:
                req = urllib.request.Request(
                    url, data=registry.render().encode(), method="PUT")
                urllib.request.urlopen(req, timeout=5).close()
            except OSError:
                pass
            if stop_event:
                if stop_event.wait(interval_seconds):
                    break
            else:
                time.sleep(interval_seconds)

    t = threading.Thread(target=loop, daemon=True, name="metrics-push")
    t.start()
    return t
