"""Minimal Prometheus client: counters/gauges/histograms with labels and
text exposition over HTTP (reference: weed/stats/metrics.go:21-182).

The reference registers request counters + latency histograms for
master/volume/filer/S3 and exposes them by pull (`-metricsPort`) or by
pushing to a gateway. Same surface here, implemented directly (the
prometheus_client package is not in the image).
"""

from __future__ import annotations

import os
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Iterable, Optional, Tuple

from seaweedfs_tpu.util.http_server import TrackingHTTPServer

_DEFAULT_BUCKETS = (
    0.0001, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


def _escape_label_value(v: str) -> str:
    """Prometheus text-format label escaping: backslash, double-quote
    and newline must be escaped or the exposition is unparseable
    (https://prometheus.io/docs/instrumenting/exposition_formats/)."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(names: Tuple[str, ...], values: Tuple[str, ...],
                extra: str = "") -> str:
    parts = [f'{n}="{_escape_label_value(v)}"'
             for n, v in zip(names, values)]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_text: str,
                 label_names: Iterable[str] = ()):
        self.name = name
        self.help = help_text
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}  # guarded_by(self._lock)

    def labels(self, *values: str):
        values = tuple(str(v) for v in values)
        if len(values) != len(self.label_names):
            raise ValueError(f"{self.name}: want {self.label_names}")
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = self._new_child()
                self._children[values] = child
            return child

    def remove(self, *values: str) -> bool:
        """Drop one labeled child from the exposition (label hygiene:
        a deleted volume's per-vid gauge must not linger forever — the
        unbounded-cardinality failure mode the `metric` lint polices).
        Returns True when a child was present."""
        values = tuple(str(v) for v in values)
        with self._lock:
            return self._children.pop(values, None) is not None

    def _default(self):
        return self.labels() if not self.label_names else None

    def collect(self, openmetrics: bool = False) -> str:
        raise NotImplementedError


class _CounterChild:
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount


class Counter(_Metric):
    kind = "counter"

    def _new_child(self):
        return _CounterChild()

    def inc(self, amount: float = 1.0) -> None:
        self.labels().inc(amount)

    def collect(self, openmetrics: bool = False) -> str:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.kind}"]
        with self._lock:
            items = list(self._children.items())
        for values, child in items:
            v = child.value() if callable(child.value) else child.value
            lines.append(f"{self.name}"
                         f"{_fmt_labels(self.label_names, values)}"
                         f" {v}")
        return "\n".join(lines)


class _GaugeChild(_CounterChild):
    def set(self, v: float) -> None:
        with self._lock:
            self.value = v

    def set_function(self, fn) -> None:
        """Evaluate `fn()` at collection time instead of holding a
        static value — for gauges like scan lag that must keep moving
        between writes (a stalled producer would otherwise freeze the
        exported value at its last set())."""
        with self._lock:
            self.value = fn

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)


class Gauge(Counter):
    kind = "gauge"

    def _new_child(self):
        return _GaugeChild()

    def set(self, v: float) -> None:
        self.labels().set(v)

    def set_function(self, fn) -> None:
        self.labels().set_function(fn)

    def dec(self, amount: float = 1.0) -> None:
        self.labels().dec(amount)


class _HistogramChild:
    __slots__ = ("buckets", "counts", "total", "count", "_lock",
                 "exemplars")

    def __init__(self, buckets):
        self.buckets = buckets
        self.counts = [0] * len(buckets)
        self.total = 0.0
        self.count = 0
        self._lock = threading.Lock()
        # bucket index -> (trace_id_hex, value, unix_ts): the last
        # sampled observation that landed in that bucket. None until
        # cluster tracing records one — the exemplar-free exposition is
        # byte-identical to the pre-exemplar format.
        self.exemplars: Optional[Dict[int, tuple]] = None

    def observe(self, v: float) -> None:
        with self._lock:
            self.total += v
            self.count += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self.counts[i] += 1

    def observe_exemplar(self, v: float, trace_id: str) -> None:
        """observe() plus an OpenMetrics exemplar linking the bucket
        this value landed in to the trace id — the /metrics ->
        cluster.trace pivot."""
        with self._lock:
            self.total += v
            self.count += 1
            hit = None
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self.counts[i] += 1
                    if hit is None:
                        hit = i
            if self.exemplars is None:
                self.exemplars = {}
            self.exemplars[len(self.buckets) if hit is None else hit] = \
                (trace_id, v, time.time())

    def time(self):
        return _Timer(self)


class _Timer:
    def __init__(self, child):
        self.child = child

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.child.observe(time.perf_counter() - self.t0)
        return False


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help_text, label_names=(),
                 buckets=_DEFAULT_BUCKETS):
        super().__init__(name, help_text, label_names)
        self.buckets = tuple(buckets)

    def _new_child(self):
        return _HistogramChild(self.buckets)

    def observe(self, v: float) -> None:
        self.labels().observe(v)

    def collect(self, openmetrics: bool = False) -> str:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.kind}"]
        with self._lock:
            items = list(self._children.items())
        for values, child in items:
            # exemplars are ONLY legal in the OpenMetrics exposition —
            # a classic text-format (0.0.4) parser hits the '#' after
            # the value and fails the whole scrape, so the default
            # render stays byte-identical to the pre-exemplar format
            ex = child.exemplars if openmetrics else None
            for i, (b, c) in enumerate(zip(child.buckets, child.counts)):
                le = 'le="%s"' % b
                line = (f"{self.name}_bucket"
                        f"{_fmt_labels(self.label_names, values, le)}"
                        f" {c}")
                if ex and i in ex:
                    # OpenMetrics exemplar: "# {trace_id=...} v ts" —
                    # emitted only once cluster tracing has linked one
                    tid, v, ts = ex[i]
                    line += (f' # {{trace_id="{tid}"}} {v:.6f} '
                             f"{ts:.3f}")
                lines.append(line)
            le_inf = 'le="+Inf"'
            line = (f"{self.name}_bucket"
                    f"{_fmt_labels(self.label_names, values, le_inf)}"
                    f" {child.count}")
            if ex and len(child.buckets) in ex:
                tid, v, ts = ex[len(child.buckets)]
                line += f' # {{trace_id="{tid}"}} {v:.6f} {ts:.3f}'
            lines.append(line)
            lines.append(f"{self.name}_sum"
                         f"{_fmt_labels(self.label_names, values)}"
                         f" {child.total}")
            lines.append(f"{self.name}_count"
                         f"{_fmt_labels(self.label_names, values)}"
                         f" {child.count}")
        return "\n".join(lines)


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def register(self, metric: _Metric) -> _Metric:
        with self._lock:
            return self._metrics.setdefault(metric.name, metric)

    def counter(self, name, help_text="", label_names=()) -> Counter:
        return self.register(Counter(name, help_text, label_names))

    def gauge(self, name, help_text="", label_names=()) -> Gauge:
        return self.register(Gauge(name, help_text, label_names))

    def histogram(self, name, help_text="", label_names=(),
                  buckets=_DEFAULT_BUCKETS) -> Histogram:
        return self.register(Histogram(name, help_text, label_names, buckets))

    def render(self, openmetrics: bool = False) -> str:
        """Text exposition. `openmetrics=True` adds exemplar suffixes
        (and is only served under the application/openmetrics-text
        content type — classic 0.0.4 parsers reject exemplars)."""
        with self._lock:
            metrics = list(self._metrics.values())
        return "\n".join(m.collect(openmetrics) for m in metrics) + "\n"


REGISTRY = Registry()

# The reference's metric families (stats/metrics.go:21-127), shared by
# every server role in-process.
RequestCounter = REGISTRY.counter(
    "SeaweedFS_request_total", "number of requests", ("type", "name"))
RequestHistogram = REGISTRY.histogram(
    "SeaweedFS_request_seconds", "request latency", ("type", "name"))
# lint: metric-ok(reference family name predates the lowercase rule; renaming breaks dashboards)
VolumeServerVolumeCounter = REGISTRY.gauge(
    "SeaweedFS_volumeServer_volumes", "volume count", ("collection", "type"))
# lint: metric-ok(reference family name predates the lowercase rule; renaming breaks dashboards)
VolumeServerDiskSizeGauge = REGISTRY.gauge(
    "SeaweedFS_volumeServer_total_disk_size", "disk size", ("collection", "type"))
MetricsPushErrorCounter = REGISTRY.counter(
    "SeaweedFS_metrics_push_errors_total",
    "failed pushes to the metrics gateway")

# Fleet-pipeline families (ec/fleet.py): the EC scheduler's stages as
# first-class metrics, so the next perf PR sees which stage saturates
# without attaching a tracer.
FleetStageSecondsHistogram = REGISTRY.histogram(
    "SeaweedFS_fleet_stage_seconds",
    "fleet scheduler per-stage latency", ("stage",))
FleetReaderQueueGauge = REGISTRY.gauge(
    "SeaweedFS_fleet_reader_queue_depth",
    "spans prefetched by the reader pool, not yet packed")
FleetDispatchBatchHistogram = REGISTRY.histogram(
    "SeaweedFS_fleet_dispatch_batch_spans",
    "volume spans fused into one RS dispatch",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128))
FleetDispatchedBytesCounter = REGISTRY.counter(
    "SeaweedFS_fleet_dispatched_bytes_total",
    "data bytes through fused RS dispatches")
FleetWriterBacklogGauge = REGISTRY.gauge(
    "SeaweedFS_fleet_writer_lane_backlog",
    "writes queued on one writer lane", ("lane",))

# Unified mesh scheduler families (parallel/mesh_fleet.py): the
# pod-scale data plane's bucket stream. `op` is the dispatch kind
# (encode | verify | rebuild); fallback `reason` is bounded
# (unavailable | timeout | error).
FleetMeshBucketsCounter = REGISTRY.counter(
    "SeaweedFS_fleet_mesh_buckets_total",
    "fixed-shape sharded buckets dispatched over the mesh", ("op",))
FleetMeshInflightGauge = REGISTRY.gauge(
    "SeaweedFS_fleet_mesh_inflight_buckets",
    "mesh buckets uploaded/computing, not yet retired")
FleetMeshFallbacksCounter = REGISTRY.counter(
    "SeaweedFS_fleet_mesh_fallbacks_total",
    "pod passes demoted to the per-device fleet schedulers",
    ("reason",))

# Scrub families (seaweedfs_tpu/scrub/): the background integrity
# subsystem's ledger. `kind` distinguishes what was damaged: a needle
# in a normal volume ("needle"), an EC data shard ("ec_data"), an EC
# parity shard ("ec_parity"), or a corruption surfaced by a client
# read under SEAWEED_VERIFY_READS ("read").
ScrubScannedBytesCounter = REGISTRY.counter(
    "SeaweedFS_scrub_scanned_bytes_total",
    "bytes read and verified by the scrub scanner")
ScrubNeedlesVerifiedCounter = REGISTRY.counter(
    "SeaweedFS_scrub_needles_verified_total",
    "needle CRCs recomputed by the scrub scanner")
ScrubStripesVerifiedCounter = REGISTRY.counter(
    "SeaweedFS_scrub_stripes_verified_total",
    "EC stripe spans re-encoded and compared against stored parity")
ScrubCorruptionsFoundCounter = REGISTRY.counter(
    "SeaweedFS_scrub_corruptions_found_total",
    "silent corruptions detected", ("kind",))
ScrubCorruptionsRepairedCounter = REGISTRY.counter(
    "SeaweedFS_scrub_corruptions_repaired_total",
    "corruptions reconstructed back to byte-identical", ("kind",))
ScrubUnrecoverableCounter = REGISTRY.counter(
    "SeaweedFS_scrub_unrecoverable_total",
    "corruptions beyond local repair (left quarantined)")
ScrubPassSecondsHistogram = REGISTRY.histogram(
    "SeaweedFS_scrub_pass_seconds",
    "wall time of one full scrub pass",
    buckets=(0.01, 0.1, 1, 10, 60, 600, 3600, 6 * 3600, 24 * 3600))
ScrubScanLagGauge = REGISTRY.gauge(
    "SeaweedFS_scrub_scan_lag_seconds",
    "seconds since the last completed scrub pass")

# Read-serving families (seaweedfs_tpu/reads/, ec/ec_volume.py): the
# degraded-read path's ledger — how much traffic is riding RS
# reconstruction instead of healthy shards, and how well the decode
# fleet fuses it.
ReadsDegradedCounter = REGISTRY.counter(
    "SeaweedFS_reads_degraded_total",
    "intervals served by on-the-fly RS reconstruction")
ReadsDegradedBatchHistogram = REGISTRY.histogram(
    "SeaweedFS_reads_degraded_batch_spans",
    "reconstruction spans fused into one RS decode dispatch",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128))
ReadsDecodedBytesCounter = REGISTRY.counter(
    "SeaweedFS_reads_decoded_bytes_total",
    "bytes produced by read-path RS reconstruction")
ReadsShortShardCounter = REGISTRY.counter(
    "SeaweedFS_reads_short_shard_total",
    "local shard reads that came back short (shard truncated on disk) "
    "and fell into reconstruction", ("vid", "shard"))
ReadsSingleFlightWaitCounter = REGISTRY.counter(
    "SeaweedFS_reads_singleflight_waits_total",
    "reads that waited on another thread's in-flight reconstruction "
    "instead of launching their own")

# Tiered read cache families (seaweedfs_tpu/cache/): hit/miss/admit/
# evict per tier plus invalidation reasons, so operators can see both
# how hot the cache runs and why entries leave it.
CacheHitCounter = REGISTRY.counter(
    "SeaweedFS_cache_hits_total", "read cache hits", ("tier",))
CacheMissCounter = REGISTRY.counter(
    "SeaweedFS_cache_misses_total", "read cache misses (all tiers)")
CacheAdmitCounter = REGISTRY.counter(
    "SeaweedFS_cache_admitted_total", "entries admitted", ("tier",))
CacheEvictCounter = REGISTRY.counter(
    "SeaweedFS_cache_evictions_total", "entries evicted", ("tier",))
CacheInvalidateCounter = REGISTRY.counter(
    "SeaweedFS_cache_invalidations_total",
    "entries dropped by invalidation", ("reason",))
CacheBytesGauge = REGISTRY.gauge(
    "SeaweedFS_cache_bytes", "bytes resident per cache tier", ("tier",))

# Ingest-pipeline families (operation/assign_lease.py, server/filer.py,
# server/volume.py): the write path's ledger — how well assigns
# amortize, how full the chunk-upload pipeline runs, and what replica
# fan-outs cost.
IngestLeaseDepthGauge = REGISTRY.gauge(
    "SeaweedFS_ingest_lease_pool_depth",
    "leased fids banked and ready to hand out without a master trip")
IngestLeaseAssignsCounter = REGISTRY.counter(
    "SeaweedFS_ingest_lease_assigns_total",
    "count=N master assign round trips made by the lease cache")
IngestLeaseServedCounter = REGISTRY.counter(
    "SeaweedFS_ingest_lease_served_total",
    "fids served from the lease pool (master round trip avoided)")
IngestLeaseDiscardsCounter = REGISTRY.counter(
    "SeaweedFS_ingest_lease_discards_total",
    "banked leases dropped before use", ("reason",))
IngestPipelineChunksHistogram = REGISTRY.histogram(
    "SeaweedFS_ingest_pipeline_batch_chunks",
    "chunks per pipelined multi-chunk upload",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128))
IngestPipelineOccupancyGauge = REGISTRY.gauge(
    "SeaweedFS_ingest_pipeline_occupancy",
    "chunk uploads in flight on the filer's ingest pool")
IngestReplicaFanoutSecondsHistogram = REGISTRY.histogram(
    "SeaweedFS_ingest_replica_fanout_seconds",
    "wall time of one concurrent replica fan-out", ("op",))

# Data-plane connection-pool families (util/http_client.py): how many
# keep-alive sockets sit banked per process and how often a pooled
# socket turned out stale at first use (the idle-close race).
HttpPoolIdleGauge = REGISTRY.gauge(
    "SeaweedFS_http_pool_idle_connections",
    "pooled keep-alive connections currently idle")
HttpPoolStaleRetryCounter = REGISTRY.counter(
    "SeaweedFS_http_pool_stale_retries_total",
    "requests replayed on a fresh connection after a pooled one "
    "proved stale")
HttpPoolReapedCounter = REGISTRY.counter(
    "SeaweedFS_http_pool_reaped_total",
    "pooled connections closed for exceeding the idle age cap")

# Async serving core families (util/async_server.py, -serve.async):
# how many sockets the selector loop holds, how much GET payload
# leaves through zero-copy sendfile, and what backpressure sheds.
# `kind` is bounded: accept (listener paused at -serve.maxConns) |
# keepalive (idle LRU closed over -serve.keepAliveBudget).
ServeConnectionsGauge = REGISTRY.gauge(
    "SeaweedFS_serve_open_connections",
    "sockets held open by the async serving core", ("role",))
ServeSendfileBytesCounter = REGISTRY.counter(
    "SeaweedFS_serve_sendfile_bytes_total",
    "GET payload bytes sent zero-copy via os.sendfile", ("role",))
ServeShedCounter = REGISTRY.counter(
    "SeaweedFS_serve_shed_total",
    "connections shed by the async core's backpressure",
    ("role", "kind"))

# Multi-tenant QoS families (seaweedfs_tpu/qos/, -qos.*). `tenant`
# cardinality is bounded by -qos.maxTenants: past the cap every new
# name charges (and labels as) the shared "_other" tenant. `reason`
# is bounded: requests | bytes | global | conns. `kind` is bounded:
# requests | bytes.
QosAdmittedCounter = REGISTRY.counter(
    "SeaweedFS_qos_admitted_total",
    "requests admitted by QoS admission control", ("tenant",))
QosShedCounter = REGISTRY.counter(
    "SeaweedFS_qos_shed_total",
    "requests and connections shed by QoS admission control",
    ("tenant", "reason"))
QosQueuedSecondsHistogram = REGISTRY.histogram(
    "SeaweedFS_qos_queued_seconds",
    "time tasks waited in the weighted-fair pool queues", ("tenant",),
    buckets=(.0005, .001, .0025, .005, .01, .025, .05, .1, .25, .5,
             1.0, 2.5))
QosTokensGauge = REGISTRY.gauge(
    "SeaweedFS_qos_tokens",
    "current admission bucket credit per tenant",
    ("tenant", "kind"))
QosTenantsGauge = REGISTRY.gauge(
    "SeaweedFS_qos_tenants",
    "tenants tracked by the QoS manager")

# Swallowed-error ledger (the `swallow` house rule, ISSUE 8): broad
# except handlers that deliberately absorb an error must leave a trace
# — either a log line or this counter. `site` is a short static label
# naming the handler ("masterclient.follow", "s3.iam_watch"), never a
# path or fid.
SwallowedErrorsCounter = REGISTRY.counter(
    "SeaweedFS_swallowed_errors_total",
    "errors absorbed by intentional broad except handlers", ("site",))

# Runtime concurrency sanitizer (util/sanitizer.py, SEAWEED_SANITIZE):
# `kind` is "cycle" (lock-order cycle = potential deadlock) or "hold"
# (lock held past the watchdog threshold).
SanitizerFindingsCounter = REGISTRY.counter(
    "SeaweedFS_sanitizer_findings_total",
    "concurrency sanitizer findings", ("kind",))


def swallowed(site: str) -> None:
    """Bump the swallowed-error counter for a named handler site —
    the one-liner the static analyzer (`swallow` check) recognizes as
    error accounting."""
    SwallowedErrorsCounter.labels(site).inc()

# Resilience families (seaweedfs_tpu/resilience/): the failure-handling
# substrate's ledger — injected faults, breaker state, hedging volume,
# retry outcomes, and work refused because its deadline was spent.
FailpointTriggersCounter = REGISTRY.counter(
    "SeaweedFS_failpoint_triggers_total",
    "armed failpoints fired", ("site", "action"))
BreakerStateGauge = REGISTRY.gauge(
    "SeaweedFS_breaker_state",
    "circuit breaker state per peer (0 closed, 1 half-open, 2 open)",
    ("peer",))
BreakerTransitionsCounter = REGISTRY.counter(
    "SeaweedFS_breaker_transitions_total",
    "circuit breaker state transitions", ("peer", "to"))
HedgeRequestsCounter = REGISTRY.counter(
    "SeaweedFS_hedge_requests_total",
    "hedge-eligible fetches (the budget denominator)")
HedgeIssuedCounter = REGISTRY.counter(
    "SeaweedFS_hedge_issued_total",
    "speculative second requests actually sent")
HedgeWinsCounter = REGISTRY.counter(
    "SeaweedFS_hedge_wins_total",
    "fetches where the hedge answered before the primary")
HedgeDeniedCounter = REGISTRY.counter(
    "SeaweedFS_hedge_budget_denied_total",
    "hedges withheld because the <=budget_pct extra-request cap "
    "was spent")
RetryAttemptsCounter = REGISTRY.counter(
    "SeaweedFS_retry_attempts_total",
    "retry attempts by outcome", ("name", "outcome"))
MasterReconnectsCounter = REGISTRY.counter(
    "SeaweedFS_master_reconnects_total",
    "master client stream redials after a break")
DeadlineRefusedCounter = REGISTRY.counter(
    "SeaweedFS_deadline_refused_total",
    "work refused because the request's budget was already spent",
    ("where",))

# Cluster-trace families (stats/cluster_trace.py): the tail sampler's
# ledger — how many traced requests finished in each keep/drop class —
# plus the flight recorder's live-table depth.
TraceRequestsCounter = REGISTRY.counter(
    "SeaweedFS_trace_requests_total",
    "traced requests by sampling outcome "
    "(slow | error | sample | drop)", ("outcome",))
TraceLiveGauge = REGISTRY.gauge(
    "SeaweedFS_trace_live_requests",
    "in-flight traced requests (the /debug/requests table depth)")

# Heat telemetry (stats/heat.py): read-path access rate per volume —
# the measurement half of the heat-driven lifecycle (ROADMAP item 3).
VolumeHeatGauge = REGISTRY.gauge(
    "SeaweedFS_volume_heat",
    "reads of this volume within the sliding heat window", ("vid",))

# Heat-driven lifecycle families (seaweedfs_tpu/lifecycle/): the policy
# engine's ledger — what it decided, what it moved, and where every
# volume sits in the hot/warm/cold lattice right now. The cluster heat
# gauge is the master-side aggregate of every volume server's
# heartbeat-carried HeatTracker summary.
ClusterVolumeHeatGauge = REGISTRY.gauge(
    "SeaweedFS_cluster_volume_heat",
    "cluster-wide reads of this volume within the heat window "
    "(summed over the heartbeat heat map)", ("vid",))
LifecycleTransitionsCounter = REGISTRY.counter(
    "SeaweedFS_lifecycle_transitions_total",
    "lifecycle transitions by kind (encode | decode | offload | "
    "download) and outcome (ok | error | dry_run)", ("kind", "outcome"))
LifecycleQueueDepthGauge = REGISTRY.gauge(
    "SeaweedFS_lifecycle_queue_depth",
    "transitions planned or forced but not yet executed")
LifecycleBytesMovedCounter = REGISTRY.counter(
    "SeaweedFS_lifecycle_bytes_moved_total",
    "volume bytes moved across tiers by the policy engine", ("kind",))
LifecycleVolumeStatesGauge = REGISTRY.gauge(
    "SeaweedFS_lifecycle_volume_states",
    "volumes currently tracked in each lifecycle state", ("state",))
LifecyclePassSecondsHistogram = REGISTRY.histogram(
    "SeaweedFS_lifecycle_pass_seconds",
    "wall time of one policy pass including executed transitions",
    buckets=(0.001, 0.01, 0.1, 1, 10, 60, 600, 3600))

# Metadata-plane families (wdclient/lookup_cache.py +
# filer/listing_cache.py, ISSUE 12): the coalescing vid-lookup cache's
# ledger and the event-invalidated listing cache's. Labels are bounded
# enums: lookup `outcome` ∈ hit | negative_hit | miss, listing
# `outcome` ∈ hit | miss, invalidation `reason` ∈ read_failure |
# explicit (lookup) / local | peer (listing).
MetaLookupCounter = REGISTRY.counter(
    "SeaweedFS_meta_lookup_total",
    "vid lookups through the coalescing cache by outcome "
    "(hit | negative_hit | miss)", ("outcome",))
MetaLookupBatchHistogram = REGISTRY.histogram(
    "SeaweedFS_meta_lookup_batch_vids",
    "vids fused into one batched master lookup round trip",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128))
MetaLookupWaitersCounter = REGISTRY.counter(
    "SeaweedFS_meta_lookup_singleflight_waiters_total",
    "lookups that waited on another caller's in-flight fetch "
    "instead of issuing their own")
MetaLookupInvalidationsCounter = REGISTRY.counter(
    "SeaweedFS_meta_lookup_invalidations_total",
    "cached vid answers dropped by reason", ("reason",))
MetaListingCounter = REGISTRY.counter(
    "SeaweedFS_meta_listing_total",
    "filer directory-listing pages by cache outcome (hit | miss)",
    ("outcome",))
MetaListingInvalidationsCounter = REGISTRY.counter(
    "SeaweedFS_meta_listing_invalidations_total",
    "listing-cache pages dropped by the metadata event log "
    "(reason: local | peer)", ("reason",))

# Process self-telemetry: evaluated at scrape time only (callable
# gauges), so every bench gets RSS/fd/thread/GC correlation for free.
ProcessRSSGauge = REGISTRY.gauge(
    "SeaweedFS_process_resident_memory_bytes",
    "resident set size of this process")
ProcessFdsGauge = REGISTRY.gauge(
    "SeaweedFS_process_open_fds", "open file descriptors")
ProcessThreadsGauge = REGISTRY.gauge(
    "SeaweedFS_process_threads", "live python threads")
ProcessGcCollectionsGauge = REGISTRY.gauge(
    "SeaweedFS_process_gc_collections",
    "cumulative garbage collections across all generations")


def _rss_bytes() -> float:
    try:
        with open("/proc/self/statm", "rb") as f:
            return int(f.read().split()[1]) * (os.sysconf("SC_PAGE_SIZE")
                                               if hasattr(os, "sysconf")
                                               else 4096)
    except (OSError, ValueError, IndexError):
        return 0.0


def _open_fds() -> float:
    try:
        return float(len(os.listdir("/proc/self/fd")))
    except OSError:
        return 0.0


def _gc_collections() -> float:
    import gc
    return float(sum(s.get("collections", 0) for s in gc.get_stats()))


def _register_process_metrics() -> None:
    ProcessRSSGauge.set_function(_rss_bytes)
    ProcessFdsGauge.set_function(_open_fds)
    ProcessThreadsGauge.set_function(lambda: float(threading.active_count()))
    ProcessGcCollectionsGauge.set_function(_gc_collections)


_register_process_metrics()


# -- shared request instrumentation -------------------------------------------
#
# Every server role wires RequestCounter/RequestHistogram (and, when
# tracing is enabled, a span per request) through these two wrappers
# instead of hand-rolling per-handler timing. Labeled children are
# resolved once at wrap time — labels() takes a lock per call, which is
# measurable at data-plane request rates.

# QoS admission seam: seaweedfs_tpu.qos.configure() installs its
# manager here (and tears it out on reset()). The wrappers below are
# ALSO the QoS ingress for every enforced role — None (the default)
# keeps both request paths one identity check away from unchanged.
_qos_http = None

# roles whose ingress enforces admission (the QoS design's contract:
# volumeServer/filer/s3 are the tenant-facing planes (the role
# strings the servers instrument with); master and webdav
# control/edge traffic is observed but never shed here
_QOS_ROLES = ("volumeServer", "filer", "s3")

def instrument_http_handler(handler_cls, role: str):
    """Wrap every do_* verb method of a BaseHTTPRequestHandler subclass
    with the request counter + latency histogram (+ a trace span when
    tracing is on). Wraps the do_* dispatch, not handle_one_request, so
    keep-alive idle time between requests is never measured as request
    latency. Returns the class for chaining.

    Also the single deadline AND trace-context ingress point for HTTP:
    a request carrying X-Seaweed-Deadline has its remaining budget
    re-anchored into the handler thread's contextvar, and (when
    cluster tracing is on) X-Seaweed-Trace re-anchors the trace
    context the same way, so every outbound hop the handler makes
    (pooled HTTP, gRPC, retries, fan-out pools) inherits both.
    Requests without the headers pay one dict lookup + one flag check."""
    from seaweedfs_tpu.resilience import deadline as deadline_mod
    from seaweedfs_tpu.qos import tenant as qos_tenant
    from seaweedfs_tpu.stats import cluster_trace, trace
    qos_enforced = role in _QOS_ROLES

    if not getattr(handler_cls, "_status_hooked", False):
        # record the last status code sent, so the tail sampler can
        # keep 5xx requests that answered instead of raising (both
        # reply styles: fast_reply sets last_status itself)
        handler_cls._status_hooked = True
        orig_send = handler_cls.send_response

        def send_response(self, code, *a):
            self.last_status = code
            return orig_send(self, code, *a)
        handler_cls.send_response = send_response

    def _wrap(methname):
        orig = getattr(handler_cls, methname)
        verb = methname[3:].lower()
        counter = RequestCounter.labels(role, verb)
        histogram = RequestHistogram.labels(role, verb)
        span_name = f"http.{role}.{verb}"

        def wrapped(self):
            t0 = time.perf_counter()
            qtok = None
            if qos_enforced and _qos_http is not None:
                # admission BEFORE any per-request machinery: a shed
                # request writes its 429/503 + Retry-After and costs
                # only the counter/histogram observation below
                qtok = _qos_http.http_enter(self, role)
                if qtok is None:
                    counter.inc()
                    histogram.observe(time.perf_counter() - t0)
                    return
            token = None
            hdr = self.headers.get(deadline_mod.HEADER_LOWER)
            if hdr is not None:
                rem = deadline_mod.parse_header(hdr)
                if rem is not None:
                    token = deadline_mod.set_budget(rem)
            ct = None
            if cluster_trace._enabled:
                self.last_status = 0
                ct = cluster_trace.begin(
                    role, verb, self.path,
                    self.headers.get(cluster_trace.HEADER_LOWER),
                    peer=self.client_address[0],
                    server="%s:%d" % self.server.server_address[:2])
            sp = trace.span(span_name, path=self.path) \
                if trace.is_enabled() else trace.NOOP
            sp.__enter__()
            exc = None
            try:
                orig(self)
            except BaseException as e:
                exc = e
                raise
            finally:
                sp.__exit__(None, None, None)
                if qtok is not None:
                    qos_tenant.current.reset(qtok)
                if token is not None:
                    deadline_mod.reset(token)
                counter.inc()
                dur = time.perf_counter() - t0
                if ct is not None:
                    kept = cluster_trace.finish(
                        ct, exc, getattr(self, "last_status", 0))
                    if kept is not None:
                        histogram.observe_exemplar(dur, kept)
                    else:
                        histogram.observe(dur)
                else:
                    histogram.observe(dur)
        wrapped.__name__ = methname
        return wrapped

    for methname in [m for m in dir(handler_cls) if m.startswith("do_")]:
        setattr(handler_cls, methname, _wrap(methname))
    return handler_cls


def instrument_grpc_method(fn, role: str, method_name: str,
                           server_streaming: bool = False,
                           server: str = ""):
    """Wrap one gRPC servicer method with the request counter + latency
    histogram (+ trace span). Used by rpc.generic_handler for every
    service a server registers — the single gRPC instrumentation point.

    Server-streaming methods count at stream START and get no latency
    histogram or span: streams can live for the process lifetime
    (SendHeartbeat, SubscribeMetadata), so an end-of-stream observation
    would report nothing while the cluster runs and then poison
    _sum/_count with one hours-long sample at shutdown.

    Unary methods are also the deadline AND trace-context ingress
    point for gRPC: the caller's deadline (context.time_remaining())
    re-anchors into the handler thread's contextvar, and the
    x-seaweed-trace metadata key re-anchors the cluster-trace context
    (streams are exempt — they live for the process lifetime)."""
    from seaweedfs_tpu.resilience import deadline as deadline_mod
    from seaweedfs_tpu.qos import tenant as qos_tenant
    from seaweedfs_tpu.stats import cluster_trace, trace
    qos_enforced = role in _QOS_ROLES
    counter = RequestCounter.labels(role, method_name)
    histogram = RequestHistogram.labels(role, method_name)
    span_name = f"grpc.{role}.{method_name}"

    if server_streaming:
        def wrapped(request, context):
            counter.inc()
            yield from fn(request, context)
    else:
        def wrapped(request, context):
            qtok = None
            if qos_enforced and _qos_http is not None:
                # shed aborts the call with RESOURCE_EXHAUSTED (abort
                # raises, so nothing below runs for a shed request)
                qtok = _qos_http.grpc_enter(context)
            t0 = time.perf_counter()
            token = None
            rem = context.time_remaining()
            # no-deadline calls report None OR int64-max seconds
            # depending on grpc version; only a real budget (< a year)
            # is worth anchoring — and feeding the int64 sentinel back
            # into an outbound timeout would overflow grpc's deadline
            # math into an instant DEADLINE_EXCEEDED
            if rem is not None and rem < 86400.0 * 365:
                token = deadline_mod.set_budget(rem)
            ct = None
            if cluster_trace._enabled:
                hdr = None
                for k, v in (context.invocation_metadata() or ()):
                    if k == cluster_trace.GRPC_KEY:
                        hdr = v
                        break
                ct = cluster_trace.begin(role, method_name,
                                         f"grpc/{method_name}", hdr,
                                         peer=context.peer() or "",
                                         server=server)
            sp = trace.span(span_name) if trace.is_enabled() else trace.NOOP
            sp.__enter__()
            exc = None
            try:
                return fn(request, context)
            except BaseException as e:
                exc = e
                raise
            finally:
                sp.__exit__(None, None, None)
                if qtok is not None:
                    qos_tenant.current.reset(qtok)
                if token is not None:
                    deadline_mod.reset(token)
                counter.inc()
                dur = time.perf_counter() - t0
                if ct is not None:
                    kept = cluster_trace.finish(ct, exc)
                    if kept is not None:
                        histogram.observe_exemplar(dur, kept)
                    else:
                        histogram.observe(dur)
                else:
                    histogram.observe(dur)
    wrapped.__name__ = method_name
    return wrapped


def start_metrics_server(port: int, registry: Registry = REGISTRY,
                         ip: str = "", role: str = "") -> ThreadingHTTPServer:
    """Serve GET /metrics (Prometheus text), GET /healthz (role +
    uptime JSON, the readiness probe tests/cluster_util.py polls),
    GET /debug/trace (Chrome trace-event JSON of the span ring;
    ?trace_id=<hex> switches to the cluster collector answering one
    trace's spans, ?sampled=1 lists kept traces), GET /debug/requests
    (the flight recorder's live request table) and GET|POST
    /debug/failpoint (the fault-injection control plane: GET lists the
    armed table, POST arms/disarms — see resilience/failpoint.py for
    the JSON body). Any other path is 404; other methods get the stock
    501."""
    import json as _json
    from urllib.parse import parse_qs as _parse_qs

    from seaweedfs_tpu.resilience import failpoint
    from seaweedfs_tpu.stats import cluster_trace, trace

    started = time.time()

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            path, _, query = self.path.partition("?")
            params = _parse_qs(query) if query else {}
            if path == "/metrics":
                # exemplar suffixes only on the EXPLICIT ?exemplars=1
                # opt-in, never by content negotiation: Prometheus
                # sends an openmetrics Accept by default, and this
                # exposition is not fully OpenMetrics-conformant (no
                # `# EOF`, counter naming) — answering that Accept
                # with exemplars would fail every default scrape.
                # The default render stays plain 0.0.4 text.
                om = bool(params.get("exemplars", [""])[0])
                body = registry.render(openmetrics=om).encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif path == "/healthz":
                body = _json.dumps({
                    "role": role or "unknown",
                    "uptime_seconds": round(time.time() - started, 3),
                }).encode()
                ctype = "application/json"
            elif path == "/debug/trace":
                if params.get("trace_id", [""])[0] or \
                        params.get("sampled", [""])[0]:
                    # the shared collector payload (same shape as the
                    # role data-port carve-outs — one implementation)
                    body = _json.dumps(cluster_trace.debug_payload(
                        self.path, role or "unknown", "")).encode()
                else:
                    # bare /debug/trace keeps the PR 2 contract: the
                    # Chrome trace JSON of the local span ring
                    body = trace.chrome_trace_json().encode()
                ctype = "application/json"
            elif path == "/debug/requests":
                body = _json.dumps(cluster_trace.debug_payload(
                    self.path, role or "unknown", "")).encode()
                ctype = "application/json"
            elif path == "/debug/failpoint":
                body = _json.dumps(failpoint.active()).encode()
                ctype = "application/json"
            else:
                body = b"404 not found\n"
                self.send_response(404)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_POST(self):
            path = self.path.partition("?")[0]
            if path != "/debug/failpoint":
                self._answer(404, {"error": "not found"})
                return
            if not failpoint.http_control_enabled():
                # fault injection over the network needs the process's
                # explicit opt-in (SEAWEED_FAILPOINTS, even just "on")
                self._answer(403, {"error":
                                   "failpoint control disabled; set "
                                   "SEAWEED_FAILPOINTS to enable"})
                return
            try:
                n = int(self.headers.get("Content-Length") or 0)
                req = _json.loads(self.rfile.read(n) or b"{}")
                action = req.get("action", "")
                if action == "reset":
                    failpoint.disarm()
                elif action == "off":
                    failpoint.disarm(req["site"])
                else:
                    failpoint.arm(
                        req["site"], action,
                        arg=float(req.get("arg", 0.0)),
                        p=float(req.get("p", 1.0)),
                        count=req.get("count"),
                        match=req.get("match"))
            except (KeyError, TypeError, ValueError) as e:
                self._answer(400, {"error": str(e)})
                return
            self._answer(200, failpoint.active())

        def _answer(self, code: int, payload) -> None:
            body = _json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # quiet
            pass

    srv = TrackingHTTPServer((ip, port), Handler)
    # lint: thread-ok(metrics listener daemon; no request context)
    threading.Thread(target=srv.serve_forever, daemon=True,
                     name=f"metrics-{port}").start()
    return srv


def loop_pushing_metric(name: str, instance: str, addr: str,
                        interval_seconds: int,
                        registry: Registry = REGISTRY,
                        stop_event: Optional[threading.Event] = None) -> threading.Thread:
    """Push-gateway loop (reference: stats/metrics.go:149).

    Push failures are counted (SeaweedFS_metrics_push_errors_total) and
    logged once per state TRANSITION (ok->failing, failing->ok), never
    per attempt — a down gateway must not log every interval forever.
    """
    from seaweedfs_tpu.util import wlog
    log = wlog.logger("metrics")
    url = f"http://{addr}/metrics/job/{name}/instance/{instance}"

    def loop():
        failing = False
        while not (stop_event and stop_event.is_set()):
            try:
                req = urllib.request.Request(
                    url, data=registry.render().encode(), method="PUT")
                urllib.request.urlopen(req, timeout=5).close()
                if failing:
                    failing = False
                    log.info("metrics push to %s recovered", addr)
            except OSError as e:
                MetricsPushErrorCounter.inc()
                if not failing:
                    failing = True
                    log.warning("metrics push to %s failing: %s", addr, e)
            if stop_event:
                if stop_event.wait(interval_seconds):
                    break
            else:
                time.sleep(interval_seconds)

    # lint: thread-ok(push-gateway daemon; no request context)
    t = threading.Thread(target=loop, daemon=True, name="metrics-push")
    t.start()
    return t
