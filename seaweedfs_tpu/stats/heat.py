"""Read-path heat telemetry: who is hot, right now.

The heat-driven lifecycle (ROADMAP item 3 — EC-encode cold volumes,
un-cool ones that heat back up, batch-offload frozen ones) needs a
measurement plane before any policy loop can decide. This module is
that half, shipped ahead of the policy: a per-volume sliding window of
read counts (a ring of time buckets, so the exported number is "reads
in the last window", not an ever-growing total) plus a sampled
per-needle counter that surfaces the hottest keys inside a hot volume
(the f4-style "is it one object or the whole volume" question).

Exported as `SeaweedFS_volume_heat{vid}` (collection-time callables —
scrapes see a moving window with zero writes between reads) and as the
Heat block on the volume server's /status.

Cost discipline (house rule, gated by
tests/test_perf_gates.py::test_cluster_trace_disabled_overhead): the
tracker is absent — not merely idle — unless -heat.track is set, so
the disabled read path pays one None check. Enabled, record() is a few
dict/list ops under the GIL; counts may race and lose the odd
increment, which is fine for telemetry (same trade the hedger's
latency window makes). No threads, ever.
"""

from __future__ import annotations

import math
import threading
import time
import weakref
from typing import Dict, List, Optional

BUCKETS = 8
# EWMA time constant in units of the heat window: sized so a sustained
# rate change converges in ~3-4 windows while a single-pulse spike
# moves the average only fractionally (the lifecycle's hysteresis
# partner — thresholds compare against BOTH the instantaneous window
# and this decayed rate)
EWMA_TAU_WINDOWS = 2.0
# below this decayed rate (one read per ~17 minutes) the EWMA snaps to
# an exact 0.0: exponential decay otherwise never reaches zero, which
# would make the lifecycle's default coolThreshold=0 unreachable
EWMA_ZERO = 1e-3

# Live trackers + the vids with a registered gauge child. The gauge's
# per-vid callable sums over LIVE trackers via this weak set, so a
# stopped server's tracker is collectable (its gauge reading decays to
# the survivors' counts instead of freezing), and two heat-tracking
# volume servers in one process (in-process test clusters) SUM instead
# of last-registration-wins clobbering.
_TRACKERS: "weakref.WeakSet[HeatTracker]" = weakref.WeakSet()
_registered_vids: set = set()
_reg_lock = threading.Lock()


def _vid_reads(vid: int) -> float:
    return float(sum(t.window_reads(vid) for t in list(_TRACKERS)))


def _register_vid_gauge(vid: int) -> None:
    with _reg_lock:
        if vid in _registered_vids:
            return
        _registered_vids.add(vid)
    from seaweedfs_tpu.stats.metrics import VolumeHeatGauge
    VolumeHeatGauge.labels(str(vid)).set_function(
        lambda vid=vid: _vid_reads(vid))


class _VolHeat:
    __slots__ = ("stamps", "counts", "total", "needles", "ewma",
                 "ewma_ts")

    def __init__(self):
        self.stamps = [0] * BUCKETS     # which time slot each bucket holds
        self.counts = [0] * BUCKETS
        self.total = 0
        self.needles: Dict[int, int] = {}
        # decayed average of the window-read rate, updated lazily at
        # summary() time (the heartbeat cadence): the policy engine's
        # anti-flap signal — a one-pulse burst barely moves it, a
        # sustained change converges within a few windows
        self.ewma = 0.0
        self.ewma_ts = 0.0


class HeatTracker:
    def __init__(self, window_s: float = 60.0, needle_sample: int = 16,
                 top_n: int = 8):
        self.window_s = window_s
        self.bucket_s = window_s / BUCKETS
        self.needle_sample = max(1, needle_sample)
        self.top_n = max(1, top_n)
        # lock-free reads are the documented trade (telemetry may lose
        # the odd increment); every INSERT/DROP takes the lock
        self._vols: Dict[int, _VolHeat] = {}  # guarded_by(self._lock, writes)
        self._lock = threading.Lock()   # vid insert + gauge child reg only
        _TRACKERS.add(self)

    # -- hot path -------------------------------------------------------------

    def record(self, vid: int, needle_id: int = 0) -> None:
        v = self._vols.get(vid)
        if v is None:
            v = self._add(vid)
        slot = int(time.monotonic() / self.bucket_s)
        i = slot % BUCKETS
        if v.stamps[i] != slot:
            v.stamps[i] = slot
            v.counts[i] = 0
        v.counts[i] += 1
        v.total += 1
        if needle_id and v.total % self.needle_sample == 0:
            n = v.needles
            n[needle_id] = n.get(needle_id, 0) + 1
            if len(n) > self.top_n * 8:
                # prune the cold tail; the hot keys keep their counts
                for nid, _c in sorted(n.items(),
                                      key=lambda kv: kv[1])[:len(n) // 2]:
                    del n[nid]

    def _add(self, vid: int) -> _VolHeat:
        with self._lock:
            v = self._vols.get(vid)
            if v is None:
                v = self._vols[vid] = _VolHeat()
                _register_vid_gauge(vid)
            return v

    def close(self) -> None:
        """Detach from the gauge registry (server stop): the per-vid
        gauge stops counting this tracker immediately instead of
        waiting for the GC."""
        _TRACKERS.discard(self)

    def forget(self, vid: int) -> None:
        """Drop everything tracked for a volume that left this server
        (delete, unmount, EC conversion). Without this a dead vid's
        `SeaweedFS_volume_heat{vid}` child and needle counters linger
        forever — unbounded label growth, the exact cardinality smell
        the `metric` lint polices. The gauge child is unregistered only
        once NO live tracker still holds the vid (two in-process
        servers may share one)."""
        with self._lock:
            self._vols.pop(vid, None)
        if any(vid in t._vols for t in list(_TRACKERS)):
            return
        with _reg_lock:
            if vid not in _registered_vids:
                return
            _registered_vids.discard(vid)
        from seaweedfs_tpu.stats.metrics import VolumeHeatGauge
        VolumeHeatGauge.remove(str(vid))

    # -- read side ------------------------------------------------------------

    def window_reads(self, vid: int) -> int:
        """Reads of vid within the sliding window (stale buckets are
        excluded by their slot stamp, so an idle volume decays to 0
        without anyone writing)."""
        v = self._vols.get(vid)
        if v is None:
            return 0
        newest = int(time.monotonic() / self.bucket_s)
        return sum(c for s, c in zip(v.stamps, v.counts)
                   if newest - s < BUCKETS)

    def summary(self) -> List[dict]:
        """The heartbeat heat payload: per-vid window reads plus the
        decayed EWMA of the window-read rate (reads/s). Called once per
        pulse; the EWMA decays with time constant EWMA_TAU_WINDOWS heat
        windows, so it keeps falling while a volume sits idle (no reads
        means no record() calls, but the heartbeat still reports the
        cooling trajectory)."""
        now = time.monotonic()
        out = []
        for vid in list(self._vols):
            v = self._vols.get(vid)
            if v is None:
                continue
            rate = self.window_reads(vid) / self.window_s
            if v.ewma_ts == 0.0:
                v.ewma = rate
            else:
                tau = EWMA_TAU_WINDOWS * self.window_s
                alpha = 1.0 - math.exp(-(now - v.ewma_ts) / tau)
                v.ewma += alpha * (rate - v.ewma)
                if v.ewma < EWMA_ZERO:
                    # exponential decay never reaches 0.0 (a once-read
                    # volume would carry a denormal for ~a day) — snap
                    # to an honest zero so a coolThreshold of 0 can
                    # actually be met by an idle volume
                    v.ewma = 0.0
            v.ewma_ts = now
            out.append({"id": vid,
                        "reads_window": self.window_reads(vid),
                        "ewma": v.ewma})
        return out

    def hot_needles(self, vid: int) -> List[List]:
        v = self._vols.get(vid)
        if v is None:
            return []
        top = sorted(v.needles.items(), key=lambda kv: -kv[1])
        return [[f"{nid:x}", c] for nid, c in top[:self.top_n]]

    def snapshot(self) -> dict:
        """The /status Heat block."""
        out = {"enabled": True, "window_s": self.window_s,
               "needle_sample": self.needle_sample, "volumes": {}}
        for vid in list(self._vols):
            v = self._vols.get(vid)
            if v is None:
                continue
            out["volumes"][str(vid)] = {
                "reads_window": self.window_reads(vid),
                "reads_total": v.total,
                "hot_needles": self.hot_needles(vid),
            }
        return out


def make_tracker(enabled: bool, window_s: float = 60.0,
                 needle_sample: int = 16) -> Optional[HeatTracker]:
    """None unless enabled — the read path's heat branch must be a
    None check, never an idle object with live method calls."""
    if not enabled:
        return None
    return HeatTracker(window_s=window_s, needle_sample=needle_sample)
