"""Read-serving subsystem: the degraded-read decode fleet.

Fuses concurrent on-the-fly RS reconstructions from the serving path
into batched `[B, 10, span]` decode dispatches — the read-side twin of
the `ec/fleet.py` encode/verify/rebuild schedulers.
"""

from seaweedfs_tpu.reads.decode_fleet import DegradedReadFleet  # noqa: F401
