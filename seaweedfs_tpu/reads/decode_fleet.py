"""Degraded-read decode fleet: fused RS reconstruction for serving.

`EcVolume._recover_interval` solves a one-row RS reconstruction per
request — under concurrent degraded traffic (a dead shard behind a hot
key range) every HTTP/gRPC handler thread pays its own shard fetches
and its own tiny decode dispatch. This fleet lifts the batch dimension
to requests-ACROSS-handlers, the same move `ec/fleet.py` made for
encode/verify/rebuild:

  queue     handler threads enqueue reconstruction requests and block
            on a per-request event; a single dispatcher thread owns
            batching, so admission costs one queue put.
  window    the dispatcher takes the first request immediately and
            drains the queue for at most `batch_window_s` more (a few
            ms) — a lone request never waits longer than the window,
            and under load the window fills toward `max_batch`.
  fetch     source rows (10 per request: local shard reads + remote
            shard fetches) run on a shared reader pool, overlapped
            ACROSS the whole batch — the slow part of a degraded read
            is fetching 10x the bytes, and serial fetch is exactly
            what the satellite fallback path does without the fleet.
  solve     requests sharing a (present, missing) signature share one
            decode matrix, so their spans pad to a common width and
            stack into ONE `[B, 10, span]` reconstruct dispatch on the
            same ReedSolomon backend the encode fleet uses.
  latch     errors stay per-request: an unreachable volume (fewer than
            10 rows) fails only its own request's event; the rest of
            the batch decodes normally.

Zero-cost-disabled contract: constructing the fleet spawns NOTHING —
no thread, no pool — until the first decode() call (gated by
tests/test_perf_gates.py::test_degraded_decode_disabled_overhead).
When the fleet is disabled entirely the EC read path falls back to
`EcVolume._recover_interval`'s parallel in-place recovery.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from seaweedfs_tpu.ops.rs_code import DATA_SHARDS, TOTAL_SHARDS, ReedSolomon
from seaweedfs_tpu.resilience import deadline as deadline_mod
from seaweedfs_tpu.stats import trace
from seaweedfs_tpu.stats.metrics import (
    FleetMeshFallbacksCounter, ReadsDecodedBytesCounter,
    ReadsDegradedBatchHistogram, ReadsDegradedCounter)
from seaweedfs_tpu.util import wlog

log = wlog.logger("reads")

# How long the dispatcher keeps the window open after the first request
# of a batch: long enough to fuse a concurrent burst, short enough to
# be invisible next to the shard fetches a degraded read already pays.
BATCH_WINDOW_S = 0.002

# Fused spans per decode dispatch (the [B, 10, span] B bound).
MAX_BATCH = 64

# Reader-pool width for source-row fetches, shared by the whole batch.
FLEET_READERS = 8


# Ceiling on waiting for one source-row fetch future: local reads are
# instant and remote reads carry their own gRPC deadline, so anything
# past this is a wedged peer — fail the ROW, keep the batch moving.
FETCH_TIMEOUT_S = 30.0


class _Request:
    __slots__ = ("ecv", "missing", "offset", "length", "remote_reader",
                 "rows", "ids", "result", "error", "done", "_local_futs",
                 "_remote_futs", "_candidates")

    def __init__(self, ecv, missing: int, offset: int, length: int,
                 remote_reader: Optional[Callable]):
        self.ecv = ecv
        self.missing = missing
        self.offset = offset
        self.length = length
        self.remote_reader = remote_reader
        self.rows: List[np.ndarray] = []
        self.ids: List[int] = []
        self.result: Optional[bytes] = None
        self.error: Optional[BaseException] = None
        self.done = threading.Event()


def _read_local(shard, offset: int, length: int) -> Optional[bytes]:
    try:
        b = shard.read_at(offset, length)
    except OSError:
        return None
    return b if len(b) == length else None


def _read_remote(remote_reader, sid: int, offset: int,
                 length: int) -> Optional[bytes]:
    try:
        b = remote_reader(sid, offset, length)
    # lint: swallow-ok(remote fetch must never poison the batch; errors latch per request)
    except Exception:
        return None
    return b if b is not None and len(b) == length else None


def _await_row(fut) -> Optional[bytes]:
    """One fetch future's row, or None if it failed or wedged — a
    stuck row costs its request a source shard, never the dispatcher."""
    try:
        return fut.result(timeout=FETCH_TIMEOUT_S)
    # lint: swallow-ok(a wedged row costs a source shard; the decode latches real errors)
    except Exception:
        return None


class DegradedReadFleet:
    """Fuses concurrent degraded-read reconstructions into batched RS
    decode dispatches. Thread-safe; threads spawn lazily on first use."""

    def __init__(self, backend: str = "auto",
                 batch_window_s: float = BATCH_WINDOW_S,
                 max_batch: int = MAX_BATCH,
                 readers: int = FLEET_READERS,
                 use_mesh: bool = False):
        self.backend = backend
        self.batch_window_s = batch_window_s
        self.max_batch = max(1, max_batch)
        self.readers = max(1, readers)
        self.use_mesh = use_mesh
        # written once inside _ensure_started's locked section before
        # the dispatcher spawns (happens-before via Thread.start), so
        # worker-side reads are lock-free by design
        self._rs: Optional[ReedSolomon] = None  # guarded_by(self._start_lock, writes)
        self._mesh = None  # guarded_by(self._start_lock, writes)
        self._q: "queue.Queue[Optional[_Request]]" = queue.Queue()
        self._start_lock = threading.Lock()
        self._dispatcher: Optional[threading.Thread] = None  # guarded_by(self._start_lock, writes)
        self._pool: Optional[ThreadPoolExecutor] = None  # guarded_by(self._start_lock, writes)
        self._workers: Optional[ThreadPoolExecutor] = None  # guarded_by(self._start_lock, writes)
        self._stopping = False  # guarded_by(self._start_lock, writes)
        # introspection for tests/bench: fused dispatches issued and
        # their occupancy (also exported via the Prometheus histogram)
        self.dispatches = 0
        self.spans_decoded = 0

    # -- lifecycle ----------------------------------------------------------

    def _ensure_started(self) -> None:
        if self._dispatcher is not None:
            return
        with self._start_lock:
            if self._dispatcher is not None or self._stopping:
                return
            self._rs = ReedSolomon(backend=self.backend)
            if self.use_mesh:
                # -ec.mesh: fused decode dispatches ride the pod-scale
                # sharded reconstruct. Resolved ONCE here (first
                # degraded read): a single-device host simply keeps
                # the per-batch host dispatch, no per-request probing.
                from seaweedfs_tpu.ec.fleet import mesh_fleet_or_none
                mesh_fleet = mesh_fleet_or_none()
                if mesh_fleet is not None:
                    try:
                        self._mesh = mesh_fleet._resolve_mesh(None)
                    except mesh_fleet.MeshError:
                        self._mesh = None
            # lint: thread-ok(decode fleet pool; decode enforces the deadline on the caller thread)
            self._pool = ThreadPoolExecutor(
                max_workers=self.readers,
                thread_name_prefix="reads-fetch")
            # batches process on a small worker pool, NOT on the
            # dispatcher: a batch wedged behind one blackholed peer
            # must stall only itself, never batch formation for
            # healthy volumes (head-of-line containment). The
            # semaphore mirrors the pool width so the dispatcher can
            # tell when every worker is busy — and keep accumulating
            # instead of queueing micro-batches behind them.
            # lint: thread-ok(decode batch workers; decode enforces the deadline on the caller thread)
            self._workers = ThreadPoolExecutor(
                max_workers=2, thread_name_prefix="reads-batch")
            self._slots = threading.Semaphore(2)
            # lint: thread-ok(dispatcher daemon; requests rendezvous on per-request events)
            t = threading.Thread(target=self._run, name="reads-decode",
                                 daemon=True)
            t.start()
            self._dispatcher = t

    def stop(self) -> None:
        # snapshot the machinery under the SAME lock that builds it: a
        # stop() racing a first-request _ensure_started either sees the
        # fully-built dispatcher/pools (and joins them) or wins the
        # lock first, after which _ensure_started's _stopping check
        # refuses to build — no window where a just-spawned dispatcher
        # or pool escapes shutdown (guard-check finding, ISSUE 10)
        with self._start_lock:
            self._stopping = True
            dispatcher = self._dispatcher
            workers = self._workers
            pool = self._pool
            if dispatcher is None:
                return
        self._q.put(None)
        dispatcher.join(timeout=10)
        if workers is not None:
            workers.shutdown(wait=True)
        if pool is not None:
            pool.shutdown(wait=True)
        # requests that slipped in between the dispatcher's final
        # drain and its exit must not wait out their 60s timeout
        self._fail_pending("decode fleet stopped")

    # -- serving surface ----------------------------------------------------

    def decode(self, ecv, missing_shard: int, offset: int, length: int,
               remote_reader: Optional[Callable] = None) -> bytes:
        """Reconstruct one interval of `ecv`'s missing shard. Blocks
        until the fused batch containing it retires; raises
        EcShardNotFound when fewer than 10 source rows are reachable."""
        from seaweedfs_tpu.ec.ec_volume import EcShardNotFound
        self._ensure_started()
        if self._stopping:
            raise EcShardNotFound(
                f"vid {ecv.volume_id} shard {missing_shard}: decode "
                "fleet stopped")
        # request-scoped span on the CALLER thread: the fleet's own
        # batch/decode spans are shared across requests, but this one
        # rides the ambient cluster-trace context, so a stitched trace
        # shows how long THIS request waited on fused reconstruction
        sp = trace.span("reads.degraded", vid=ecv.volume_id,
                        shard=missing_shard, length=length) \
            if trace.active() else trace.NOOP
        with sp:
            return self._decode_blocking(ecv, missing_shard, offset,
                                         length, remote_reader)

    def _decode_blocking(self, ecv, missing_shard: int, offset: int,
                         length: int,
                         remote_reader: Optional[Callable]) -> bytes:
        from seaweedfs_tpu.ec.ec_volume import EcShardNotFound
        req = _Request(ecv, missing_shard, offset, length, remote_reader)
        self._q.put(req)
        if self._stopping:
            # stop() may have drained the queue between our check and
            # the put — fail whatever is queued (including req) now
            # rather than letting callers wait out the full timeout
            self._fail_pending("decode fleet stopped")
        # a request whose client already gave up must not pin this
        # handler thread for the full fleet timeout — cap the wait to
        # the ambient budget (the batch may still retire for siblings)
        wait_s = 60.0
        rem = deadline_mod.remaining()
        if rem is not None:
            if rem <= 0:
                raise deadline_mod.DeadlineExceeded(
                    f"degraded read vid {ecv.volume_id}")
            wait_s = min(wait_s, rem)
        if not req.done.wait(timeout=wait_s):
            if deadline_mod.expired():
                raise deadline_mod.DeadlineExceeded(
                    f"degraded read vid {ecv.volume_id} "
                    f"shard {missing_shard}")
            req.error = EcShardNotFound(
                f"vid {ecv.volume_id} shard {missing_shard}: decode "
                "fleet timed out")
        if req.error is not None:
            raise req.error
        return req.result

    # -- dispatcher ---------------------------------------------------------

    def _run(self) -> None:
        while True:
            req = self._q.get()
            if req is None:
                self._fail_pending("decode fleet stopped")
                return
            batch = [req]
            deadline = time.monotonic() + self.batch_window_s
            while len(batch) < self.max_batch:
                try:
                    # whatever is ALREADY queued fuses for free; the
                    # blocking window only opens once the batch proves
                    # concurrent — a lone request never waits
                    nxt = self._q.get_nowait()
                except queue.Empty:
                    if len(batch) == 1:
                        break
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    try:
                        nxt = self._q.get(timeout=remaining)
                    except queue.Empty:
                        break
                if nxt is None:
                    self._submit(batch)
                    self._fail_pending("decode fleet stopped")
                    return
                batch.append(nxt)
            # while every worker is busy, keep draining the queue into
            # THIS batch — the accumulation that makes fused decode
            # dispatches full exactly when decode is the bottleneck.
            # An idle fleet takes a slot immediately: a lone request
            # still never waits.
            got_slot = self._slots.acquire(blocking=False)
            while not got_slot and len(batch) < self.max_batch:
                try:
                    nxt = self._q.get(timeout=0.002)
                except queue.Empty:
                    pass
                else:
                    if nxt is None:
                        self._slots.acquire()
                        self._submit(batch, have_slot=True)
                        self._fail_pending("decode fleet stopped")
                        return
                    batch.append(nxt)
                got_slot = self._slots.acquire(blocking=False)
            if not got_slot:
                self._slots.acquire()  # batch full: wait for a worker
            self._submit(batch, have_slot=True)

    def _submit(self, batch: List[_Request], have_slot: bool = False) -> None:
        if not have_slot:
            self._slots.acquire()
        self._workers.submit(self._process_guarded, batch)

    def _process_guarded(self, batch: List[_Request]) -> None:
        try:
            self._process(batch)
        except BaseException as e:  # noqa: BLE001 - latch, never die
            log.exception("degraded decode batch failed")
            for r in batch:
                if r.error is None and r.result is None:
                    r.error = e
                r.done.set()
        finally:
            self._slots.release()

    def _fail_pending(self, why: str) -> None:
        from seaweedfs_tpu.ec.ec_volume import EcShardNotFound
        while True:
            try:
                r = self._q.get_nowait()
            except queue.Empty:
                return
            if r is not None:
                r.error = EcShardNotFound(why)
                r.done.set()

    def _process(self, batch: List[_Request]) -> None:
        sp = trace.span("reads.batch", spans=len(batch)) \
            if trace.is_enabled() else trace.NOOP
        with sp:
            self._fetch_rows(batch)
            self._solve(batch)
        for req in batch:
            req.done.set()

    def _fetch_rows(self, batch: List[_Request]) -> None:
        """Gather 10 source rows per request, overlapped across the
        batch: all local reads first (parallel), then remote fetches
        only for each request's deficit."""
        # phase A: every request's local shard reads, in flight at once
        for req in batch:
            req._local_futs = []
            for sid in range(TOTAL_SHARDS):
                if sid == req.missing:
                    continue
                shard = req.ecv.shards.get(sid)
                if shard is not None:
                    req._local_futs.append((sid, self._pool.submit(
                        _read_local, shard, req.offset, req.length)))
        # phase B: collect locals; submit the remote deficit (+1 slack)
        for req in batch:
            local_ok = set()
            for sid, fut in req._local_futs:
                b = _await_row(fut)
                if b is not None and len(req.ids) < DATA_SHARDS:
                    req.ids.append(sid)
                    req.rows.append(np.frombuffer(b, dtype=np.uint8))
                    local_ok.add(sid)
            req._candidates = [
                sid for sid in range(TOTAL_SHARDS)
                if sid != req.missing and sid not in local_ok] \
                if req.remote_reader is not None else []
            deficit = DATA_SHARDS - len(req.ids)
            req._remote_futs = []
            if deficit > 0 and req._candidates:
                take, req._candidates = (req._candidates[:deficit + 1],
                                         req._candidates[deficit + 1:])
                for sid in take:
                    req._remote_futs.append((sid, self._pool.submit(
                        _read_remote, req.remote_reader, sid,
                        req.offset, req.length)))
        # phase C: collect remotes. On a failure the WHOLE remaining
        # candidate set is submitted at once — chained one-by-one
        # top-ups would serialize this thread behind each wedged
        # peer's timeout in turn (head-of-line for the whole fleet)
        from seaweedfs_tpu.ec.ec_volume import EcShardNotFound
        for req in batch:
            futs = list(req._remote_futs)
            while futs and len(req.ids) < DATA_SHARDS:
                sid, fut = futs.pop(0)
                b = _await_row(fut)
                if b is not None:
                    if len(req.ids) < DATA_SHARDS:
                        req.ids.append(sid)
                        req.rows.append(np.frombuffer(b, dtype=np.uint8))
                elif req._candidates:
                    spares, req._candidates = req._candidates, []
                    futs.extend(
                        (nxt, self._pool.submit(
                            _read_remote, req.remote_reader, nxt,
                            req.offset, req.length))
                        for nxt in spares)
            if len(req.ids) < DATA_SHARDS:
                req.error = EcShardNotFound(
                    f"vid {req.ecv.volume_id} shard {req.missing}: only "
                    f"{len(req.ids)} shards reachable, need {DATA_SHARDS}")
                continue
            # canonical sid order: locals landed first, remotes after,
            # so sort rows with ids — the (present, missing) signature
            # must not depend on discovery order or identical shard
            # sets split into separate dispatches
            order = sorted(range(DATA_SHARDS), key=lambda i: req.ids[i])
            req.rows = [req.rows[i] for i in order]
            req.ids = [req.ids[i] for i in order]

    def _solve(self, batch: List[_Request]) -> None:
        """Group healthy requests by decode signature and issue one
        fused [B, 10, span] reconstruct per group."""
        groups: Dict[Tuple[Tuple[int, ...], int], List[_Request]] = {}
        for req in batch:
            if req.error is not None:
                continue
            # ids were sorted at the end of the fetch phase, so the
            # signature — and hence the decode matrix — is canonical
            groups.setdefault((tuple(req.ids), req.missing),
                              []).append(req)
        for (present, missing), members in groups.items():
            span = max(r.length for r in members)
            src = np.zeros((len(members), DATA_SHARDS, span),
                           dtype=np.uint8)
            for i, r in enumerate(members):
                for row, data in enumerate(r.rows):
                    src[i, row, :len(data)] = data
            sp = trace.span("reads.decode", batch=len(members),
                            span=span) if trace.is_enabled() else trace.NOOP
            try:
                with sp:
                    out = None
                    if self._mesh is not None and len(members) >= 2:
                        from seaweedfs_tpu.parallel import mesh_fleet
                        try:
                            out = mesh_fleet.sharded_reconstruct(
                                self._mesh, list(present), [missing],
                                src)
                        except Exception as e:
                            # demote to the host dispatch; the request
                            # must not fail on a mesh-only error
                            FleetMeshFallbacksCounter.labels(
                                "error").inc()
                            log.warning(
                                "mesh decode fell back (%r); "
                                "re-solving on the host path", e)
                            out = None
                    if out is None:
                        out = self._rs.reconstruct_some(
                            list(present), [missing], src)  # [B, 1, span]
            except BaseException as e:  # noqa: BLE001 - latch per group
                for r in members:
                    r.error = e
                continue
            self.dispatches += 1
            self.spans_decoded += len(members)
            ReadsDegradedBatchHistogram.observe(len(members))
            ReadsDegradedCounter.inc(len(members))
            for i, r in enumerate(members):
                r.result = out[i, 0, :r.length].tobytes()
                ReadsDecodedBytesCounter.inc(float(r.length))
