"""Admission control: per-tenant token buckets + explicit backpressure.

This generalizes util/throttler.Throttler — the blocking bytes/s pacer
the scrub and compaction paths use — into a NON-blocking admission
bucket: instead of sleeping the caller until the deficit is repaid,
try_admit() answers "no, and here is when" so the ingress seams can
shed with an honest ``Retry-After`` (HTTP 429/503, S3 ``SlowDown``,
gRPC RESOURCE_EXHAUSTED) while the admitted path stays byte-identical.

Differences from Throttler, both deliberate:

  - the bucket starts FULL (Throttler starts empty so "the first bytes
    pay full price"): admission must not shed the first request after
    a restart — burst capacity is the contract for well-behaved bursts
  - overdraw is allowed for oversized charges: one charge larger than
    the whole burst (a single huge PUT against a small bytes bucket)
    admits whenever the bucket is full and drives the credit negative,
    so it is PACED by the sheds that follow instead of being
    unadmittable forever. Ordinary charges need full credit — the
    admit/shed boundary is exact, not a race against clock granularity

Retry-After math (documented in ARCHITECTURE.md): a shed at credit c
(<= 0) for a charge of n reports (n - c) / rate seconds — the exact
time the bucket needs to refill past the charge at the configured
rate. HTTP rounds that up to whole seconds (delta-seconds grammar).

Heat-aware shed ordering: when the GLOBAL bucket (cluster overload,
not per-tenant misbehavior) runs dry, traffic for provably-hot volumes
(stats/heat.HeatTracker window reads at or above the fleet mean) may
draw from a smaller hot-reserve bucket, so the traffic that keeps
cache-warm, demonstrably-demanded data flowing is the LAST to shed and
cold-volume traffic sheds first.
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from seaweedfs_tpu.qos import tenant as tenant_mod
from seaweedfs_tpu.qos.fair import WeightedFairQueue
from seaweedfs_tpu.stats import trace


class AdmissionBucket:
    """Non-blocking token bucket. try_admit(n) -> (retry_after, credit):
    retry_after 0.0 means n was charged; a positive value is the
    seconds until the bucket could afford the charge (nothing charged).
    rate <= 0 disables the bucket — one attribute check, no clock read.
    """

    __slots__ = ("rate", "burst", "disabled", "_lock", "_credit",
                 "_last")

    def __init__(self, rate: float, burst: float = 0.0):
        self.rate = float(rate)
        # default burst: 2 seconds at rate, floor 8 — small enough to
        # bound a cold-start stampede, big enough for request pipelines
        self.burst = float(burst) if burst > 0 else \
            max(2.0 * self.rate, 8.0)
        self.disabled = self.rate <= 0
        self._lock = threading.Lock()
        self._credit = self.burst       # guarded_by(self._lock)
        self._last = time.monotonic()   # guarded_by(self._lock)

    def try_admit(self, n: float = 1.0) -> Tuple[float, float]:
        if self.disabled:
            return 0.0, float("inf")
        now = time.monotonic()
        with self._lock:
            credit = min(self.burst,
                         self._credit + (now - self._last) * self.rate)
            self._last = now
            # need full credit for the charge; an oversized charge
            # (n > burst) only needs a full bucket — it overdraws and
            # the sheds that follow pace the repayment
            if credit >= (n if n < self.burst else self.burst):
                credit -= n
                self._credit = credit
                return 0.0, credit
            self._credit = credit
            return (n - credit) / self.rate, credit

    def tokens(self) -> float:
        """Current credit (refreshed); +inf when disabled."""
        if self.disabled:
            return float("inf")
        now = time.monotonic()
        with self._lock:
            self._credit = min(
                self.burst,
                self._credit + (now - self._last) * self.rate)
            self._last = now
            return self._credit


@dataclass
class QosConfig:
    """The -qos.* flag surface (command/servers.py:_add_qos_args)."""
    request_rate: float = 0.0        # per-tenant requests/s (0 = off)
    request_burst: float = 0.0       # requests of burst (0 = 2x rate)
    bytes_mbps: float = 0.0          # per-tenant body MB/s (0 = off)
    bytes_burst_s: float = 2.0       # seconds of bytes-rate burst
    global_request_rate: float = 0.0  # whole-process requests/s
    weights: Dict[str, float] = field(default_factory=dict)
    default_weight: float = 1.0
    internal_weight: float = 0.25    # scrub/lifecycle/filer_sync lane
    max_tenants: int = 64            # distinct names before _other
    heat_shed: bool = True           # prefer shedding cold traffic


_SHED_REASONS = ("requests", "bytes", "global", "conns")


class TenantState:
    """Per-tenant buckets + metric children, resolved ONCE at creation
    (labels() takes a lock per call — the instrument-wrapper rule).
    The counter children double as the /qos/status source of truth."""

    __slots__ = ("name", "weight", "internal", "req", "bts",
                 "admitted_c", "shed_c", "queued_h", "tok_req_g",
                 "tok_bytes_g")

    def __init__(self, name: str, weight: float, cfg: QosConfig):
        from seaweedfs_tpu.stats.metrics import (
            QosAdmittedCounter, QosQueuedSecondsHistogram,
            QosShedCounter, QosTokensGauge)
        self.name = name
        self.weight = max(weight, 1e-3)
        self.internal = name == tenant_mod.INTERNAL
        self.req = AdmissionBucket(cfg.request_rate, cfg.request_burst)
        self.bts = AdmissionBucket(cfg.bytes_mbps * 1024 * 1024,
                                   cfg.bytes_mbps * 1024 * 1024 *
                                   cfg.bytes_burst_s)
        self.admitted_c = QosAdmittedCounter.labels(name)
        self.shed_c = {r: QosShedCounter.labels(name, r)
                       for r in _SHED_REASONS}
        self.queued_h = QosQueuedSecondsHistogram.labels(name)
        self.tok_req_g = QosTokensGauge.labels(name, "requests")
        self.tok_bytes_g = QosTokensGauge.labels(name, "bytes")


class QosManager:
    """The per-process QoS brain: tenant table, admission, weighted
    shares, heat-aware global shed, and the /qos/status payload.
    qos.configure() installs one of these into every consumer seam."""

    # fraction of global rate reserved for hot-volume traffic while
    # the global bucket is dry (heat-aware shed ordering)
    HOT_RESERVE_FRACTION = 0.25
    # how long a computed hot threshold stays cached (the overload
    # path must not recompute a fleet summary per shed decision)
    HOT_CUT_TTL_S = 1.0

    def __init__(self, cfg: QosConfig):
        self.cfg = cfg
        self._lock = threading.Lock()
        self._tenants: Dict[str, TenantState] = {}  # guarded_by(self._lock, writes)
        self._conns: Dict[str, int] = {}  # guarded_by(self._lock)
        self._global = AdmissionBucket(cfg.global_request_rate)
        self._hot_reserve = AdmissionBucket(
            cfg.global_request_rate * self.HOT_RESERVE_FRACTION)
        self.heat = None   # HeatTracker; the volume role attaches its own
        self._hot_cut = 1.0      # guarded_by(self._lock)
        self._hot_cut_at = 0.0   # guarded_by(self._lock)
        from seaweedfs_tpu.stats.metrics import QosTenantsGauge
        QosTenantsGauge.set_function(lambda: float(len(self._tenants)))

    # -- tenant table --------------------------------------------------------

    def weight_of(self, name: str) -> float:
        w = self.cfg.weights.get(name)
        if w is not None:
            return max(w, 1e-3)
        if name == tenant_mod.INTERNAL:
            return max(self.cfg.internal_weight, 1e-3)
        return max(self.cfg.default_weight, 1e-3)

    def state_of(self, name: str) -> TenantState:
        """Get-or-create; past -qos.maxTenants distinct names the
        overflow maps to the shared "_other" tenant, bounding bucket
        memory and metric label cardinality alike."""
        st = self._tenants.get(name)
        if st is not None:
            return st
        with self._lock:
            st = self._tenants.get(name)
            if st is not None:
                return st
            if len(self._tenants) >= self.cfg.max_tenants and \
                    name != tenant_mod.OTHER:
                name = tenant_mod.OTHER
                st = self._tenants.get(name)
                if st is not None:
                    return st
            st = TenantState(name, self.weight_of(name), self.cfg)
            self._tenants[name] = st
            return st

    def make_wfq(self, pool_name: str) -> WeightedFairQueue:
        return WeightedFairQueue(self, pool_name)

    def resolve(self, headers, path: str = "") -> str:
        """Tenant identity from request metadata (the async loop calls
        this so util/ modules never import the qos package)."""
        return tenant_mod.resolve(headers, path)

    def observe_queued(self, state: TenantState, waited: float) -> None:
        state.queued_h.observe(waited)
        if trace.is_enabled():
            with trace.span("qos.queue", tenant=state.name,
                            queued_ms=round(waited * 1000.0, 3)):
                pass

    # -- admission -----------------------------------------------------------

    def admit(self, name: str, nbytes: int = 0,
              vid: int = 0) -> Tuple[float, str]:
        """-> (retry_after, reason). retry_after 0.0 = admitted.
        Internal background work is exempt (it is deprioritized in the
        pool queues instead — shedding repair traffic would trade
        latency for durability)."""
        st = self.state_of(name)
        if st.internal:
            st.admitted_c.inc()
            return 0.0, ""
        ra, credit = st.req.try_admit(1.0)
        if not st.req.disabled:
            st.tok_req_g.set(credit)
        if ra > 0.0:
            st.shed_c["requests"].inc()
            return ra, "requests"
        if nbytes > 0 and not st.bts.disabled:
            ra, credit = st.bts.try_admit(float(nbytes))
            st.tok_bytes_g.set(credit)
            if ra > 0.0:
                st.shed_c["bytes"].inc()
                return ra, "bytes"
        if not self._global.disabled:
            ra, _ = self._global.try_admit(1.0)
            if ra > 0.0:
                # global overload, not tenant misbehavior: heat-aware
                # ordering sheds cold-volume traffic first
                if vid and self.heat is not None and \
                        self.cfg.heat_shed and self._is_hot(vid):
                    ra2, _ = self._hot_reserve.try_admit(1.0)
                    if ra2 == 0.0:
                        st.admitted_c.inc()
                        return 0.0, ""
                st.shed_c["global"].inc()
                return ra, "global"
        st.admitted_c.inc()
        return 0.0, ""

    def _is_hot(self, vid: int) -> bool:
        """Window reads at or above the fleet mean (cached ~1s; the
        summary walk must not run per shed decision)."""
        now = time.monotonic()
        with self._lock:
            if now - self._hot_cut_at > self.HOT_CUT_TTL_S:
                rows = self.heat.summary()
                if rows:
                    mean = sum(r["reads_window"] for r in rows) / \
                        len(rows)
                else:
                    mean = 1.0
                self._hot_cut = max(mean, 1.0)
                self._hot_cut_at = now
            cut = self._hot_cut
        return self.heat.window_reads(vid) >= cut

    # -- ingress seams -------------------------------------------------------

    def http_enter(self, handler, role: str):
        """Admission at the instrumented do_* dispatch. Admitted: the
        ambient tenant is pinned and the contextvar reset token
        returned (the wrapper resets it in its finally). Shed: the
        backpressure reply is written and None returned."""
        headers = handler.headers
        name = tenant_mod.resolve(headers, handler.path)
        nbytes = 0
        cl = headers.get("content-length")
        if cl:
            try:
                nbytes = int(cl)
            except ValueError:
                nbytes = 0
        vid = 0
        if self.heat is not None and self.cfg.heat_shed:
            vid = _vid_of(handler.path)
        if trace.is_enabled():
            with trace.span("qos.admit", tenant=name):
                ra, reason = self.admit(name, nbytes, vid)
        else:
            ra, reason = self.admit(name, nbytes, vid)
        if ra == 0.0:
            return tenant_mod.current.set(name)
        self.shed_reply(handler, role, name, ra, reason)
        return None

    def grpc_enter(self, context):
        """Admission at the instrumented unary gRPC dispatch; aborts
        the call with RESOURCE_EXHAUSTED on shed (abort raises)."""
        name = None
        for k, v in (context.invocation_metadata() or ()):
            if k == tenant_mod.GRPC_KEY:
                name = v
                break
        if not name:
            name = tenant_mod.DEFAULT
        ra, reason = self.admit(name)
        if ra == 0.0:
            return tenant_mod.current.set(name)
        import grpc
        context.abort(
            grpc.StatusCode.RESOURCE_EXHAUSTED,
            "qos: tenant %s over %s budget; retry after %.3fs"
            % (name, reason, ra))
        return None   # unreachable; abort raises

    def shed_reply(self, handler, role: str, name: str, ra: float,
                   reason: str) -> None:
        """Write the role-appropriate backpressure reply: S3 speaks
        503 + SlowDown XML (the AWS throttle contract), everyone else
        429 + plain text; both carry Retry-After = ceil(bucket refill
        time) in the delta-seconds grammar."""
        retry_after = max(1, int(math.ceil(ra)))
        hdrs = {"Retry-After": str(retry_after)}
        if role == "s3":
            from seaweedfs_tpu.s3api.server import slow_down_xml
            handler.fast_reply(503, slow_down_xml(handler.path), hdrs,
                               ctype="application/xml")
        else:
            body = ("qos: tenant %s over %s budget; retry after %ds\n"
                    % (name, reason, retry_after)).encode()
            handler.fast_reply(429, body, hdrs, ctype="text/plain")

    # -- connection accounting (async serving core) --------------------------

    def conn_opened(self, name: str) -> None:
        with self._lock:
            self._conns[name] = self._conns.get(name, 0) + 1

    def conn_closed(self, name: str) -> None:
        with self._lock:
            n = self._conns.get(name, 0) - 1
            if n <= 0:
                self._conns.pop(name, None)
            else:
                self._conns[name] = n

    def conn_over_share(self, name: str, cap: int) -> bool:
        """Is this tenant past its weighted share of `cap` open
        connections? Shares divide cap by weight among tenants with
        connections open right now (floor 1 — a tenant can always hold
        one connection). Internal traffic is never conn-shed."""
        if name == tenant_mod.INTERNAL:
            return False
        w = self.weight_of(name)
        with self._lock:
            mine = self._conns.get(name, 0)
            total_w = sum(self.weight_of(t) for t in self._conns)
        if total_w <= 0.0:
            return False
        share = max(1.0, cap * w / total_w)
        if mine <= share:
            return False
        st = self.state_of(name)
        st.shed_c["conns"].inc()
        return True

    def most_over_share(self, counts: Dict[str, int],
                        cap: int) -> Optional[str]:
        """Among tenants holding idle keep-alive connections, the one
        furthest past its weighted share of the budget (None when
        nobody is over — the caller falls back to plain LRU)."""
        if not counts:
            return None
        total_w = sum(self.weight_of(t) for t in counts)
        if total_w <= 0.0:
            return None
        worst, worst_ratio = None, 1.0
        for t, n in counts.items():
            if t == tenant_mod.INTERNAL:
                continue
            share = max(1.0, cap * self.weight_of(t) / total_w)
            ratio = n / share
            if ratio > worst_ratio:
                worst, worst_ratio = t, ratio
        return worst

    # -- status --------------------------------------------------------------

    def status(self) -> dict:
        with self._lock:
            states = list(self._tenants.values())
            conns = dict(self._conns)
        tenants = {}
        for st in states:
            tenants[st.name] = {
                "weight": st.weight,
                "internal": st.internal,
                "admitted": int(st.admitted_c.value),
                "shed": {r: int(st.shed_c[r].value)
                         for r in _SHED_REASONS},
                "tokens": {
                    "requests": None if st.req.disabled
                    else round(st.req.tokens(), 3),
                    "bytes": None if st.bts.disabled
                    else round(st.bts.tokens(), 1),
                },
                "conns": conns.get(st.name, 0),
            }
        return {
            "enabled": True,
            "request_rate": self.cfg.request_rate,
            "bytes_mbps": self.cfg.bytes_mbps,
            "global_request_rate": self.cfg.global_request_rate,
            "max_tenants": self.cfg.max_tenants,
            "heat_shed": bool(self.heat is not None and
                              self.cfg.heat_shed),
            "tenants": tenants,
        }


def _vid_of(path: str) -> int:
    """Volume id out of a data-plane path ("/3,01637037d6" or
    "/dir/3,01..."), 0 when the path has no fid shape. Only called on
    the heat-aware shed path (volume role, heat tracking on)."""
    i = path.find(",")
    if i <= 0:
        return 0
    j = path.rfind("/", 0, i)
    try:
        return int(path[j + 1:i])
    except ValueError:
        return 0
