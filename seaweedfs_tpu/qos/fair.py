"""Weighted-fair queueing for the shared FanOutPool seam.

Start-time fair queueing on virtual time: each enqueue stamps a
virtual finish time ``max(vtime, tenant's last finish) + 1/weight``
and workers always pop the smallest stamp. A weight-16 tenant's tasks
therefore interleave 16:1 against weight-1 tasks under contention, and
a newly-arriving high-weight task jumps (almost) the whole backlog of
a low-weight flood — the property tests/test_qos.py proves under the
seeded schedule explorer. With a single tenant the heap degenerates to
FIFO (stamps are monotonic), so fairness costs nothing observable when
nobody competes.

The queue replaces only the ORDERING of FanOutPool's backlog, not its
transport: fanout keeps its SimpleQueue for worker wakeups (a token
per task) and its stop() sentinel semantics, so shutdown and the
inline-after-stop contract are untouched.
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Any, Optional

from seaweedfs_tpu.qos import tenant as tenant_mod


class WeightedFairQueue:
    """One per FanOutPool (built lazily on the pool's first submit
    while QoS is on). put() reads the ambient tenant contextvar; pop()
    never blocks — the pool only wakes a worker per queued item."""

    __slots__ = ("_mgr", "name", "_lock", "_heap", "_vtime",
                 "_vfinish", "_seq")

    def __init__(self, manager, name: str):
        self._mgr = manager
        self.name = name
        self._lock = threading.Lock()
        self._heap: list = []      # guarded_by(self._lock)
        self._vtime = 0.0          # guarded_by(self._lock)
        # last virtual finish per tenant; bounded — names here are
        # manager-normalized (maxTenants overflow maps to _other)
        self._vfinish: dict = {}   # guarded_by(self._lock)
        self._seq = 0              # guarded_by(self._lock)

    def __len__(self) -> int:
        with self._lock:
            return len(self._heap)

    def put(self, item: Any) -> None:
        name = tenant_mod.current.get()
        if name is None:
            name = tenant_mod.DEFAULT
        st = self._mgr.state_of(name)
        now = time.monotonic()
        with self._lock:
            start = self._vtime
            last = self._vfinish.get(st.name, 0.0)
            if last > start:
                start = last
            vf = start + 1.0 / st.weight
            self._vfinish[st.name] = vf
            self._seq += 1
            heapq.heappush(self._heap, (vf, self._seq, st, now, item))

    def pop(self) -> Optional[Any]:
        with self._lock:
            if not self._heap:
                return None
            vf, _seq, st, t_enq, item = heapq.heappop(self._heap)
            if vf > self._vtime:
                self._vtime = vf
        self._mgr.observe_queued(st, time.monotonic() - t_enq)
        return item
