"""Tenant identity for the multi-tenant QoS plane.

Resolution order (cheapest-first, first match wins):

  1. the explicit ``X-Seaweed-Tenant`` request header — the contract
     for clients that know who they are (and for cluster-internal hops:
     util/http_client and rpc forward the ambient tenant on every
     outbound call, so a filer's chunk uploads are charged to the
     ORIGINAL tenant, not to "the filer")
  2. the S3 access key parsed out of the SigV4 ``Authorization``
     header (``Credential=<KEY>/...``) — the s3api gateway's natural
     tenant identity, no extra client configuration needed
  3. the ``collection`` query parameter — collections are the
     reference's multi-tenancy unit (weed/storage collections), so
     assign/lookup traffic is charged per collection by default
  4. ``"default"`` — everyone else shares one bucket

The identity travels the process as a contextvar so work crossing a
FanOutPool hop (the pool copies the submitter's context) stays charged
to its tenant, and two reserved names exist:

  ``_internal``  background engines (scrub, lifecycle, filer_sync)
                 run under qos.internal_context(): exempt from
                 admission (shedding replication/repair would trade
                 latency for durability) but weighted LOW in the
                 weighted-fair pool queues, so the store never starves
                 foreground reads for its own housekeeping
  ``_other``     the overflow tenant once -qos.maxTenants distinct
                 names exist — bounds both bucket memory and the
                 qos metric label cardinality (the `metric` lint's
                 unbounded-label rule)
"""

from __future__ import annotations

import contextvars
from typing import Optional  # noqa: F401  # lint: dead-ok(used in the quoted contextvar annotation below)

HEADER = "X-Seaweed-Tenant"
HEADER_LOWER = "x-seaweed-tenant"
GRPC_KEY = "x-seaweed-tenant"

DEFAULT = "default"
INTERNAL = "_internal"
OTHER = "_other"

# ambient tenant of the calling thread/task; None = anonymous (and
# ALWAYS None while QoS is off — nothing ever sets it, so seams that
# forward it pay one None check)
current: "contextvars.ContextVar[Optional[str]]" = \
    contextvars.ContextVar("qos_tenant", default=None)


def resolve(headers, path: str = "") -> str:
    """Resolve the tenant name from request metadata. `headers` is any
    case-insensitive mapping with .get (email.Message or HeaderDict);
    `path` is the raw request path (query string included)."""
    t = headers.get(HEADER_LOWER)
    if t:
        return t
    auth = headers.get("authorization")
    if auth:
        # SigV4: "AWS4-HMAC-SHA256 Credential=<KEY>/<date>/..." ;
        # SigV2: "AWS <KEY>:<sig>" — both yield the access key
        i = auth.find("Credential=")
        if i >= 0:
            i += len("Credential=")
            j = auth.find("/", i)
            if j > i:
                return auth[i:j]
        elif auth.startswith("AWS "):
            j = auth.find(":", 4)
            if j > 4:
                return auth[4:j]
    q = path.find("?")
    if q >= 0:
        for part in path[q + 1:].split("&"):
            if part.startswith("collection=") and len(part) > 11:
                return part[11:]
    return DEFAULT


class _Scope:
    """Context manager pinning the ambient tenant (re-entrant safe:
    each instance holds its own reset token)."""

    __slots__ = ("_name", "_token")

    def __init__(self, name: str):
        self._name = name
        self._token = None

    def __enter__(self):
        self._token = current.set(self._name)
        return self._name

    def __exit__(self, *exc):
        current.reset(self._token)
        return False


def as_tenant(name: str) -> _Scope:
    return _Scope(name)
