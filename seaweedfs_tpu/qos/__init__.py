"""Multi-tenant QoS: admission control, weighted-fair scheduling, and
heat-aware backpressure, end to end (ROADMAP open item 2).

Three planes, one manager:

  admission    per-tenant token buckets (request rate + bytes rate,
               burst-capped) at the shared HTTP/gRPC instrumentation
               seams, plus weighted per-tenant connection budgets in
               the async serving core — an aggressive tenant is shed
               at frame time, before a worker thread is burned
  scheduling   weighted-fair queueing on util/fanout.FanOutPool (one
               seam covers fleet reader/writer lanes, degraded-decode
               batch workers, replica fan-out, ingest pipeline);
               scrub, lifecycle, and filer_sync run as the low-weight
               ``_internal`` tenant, so housekeeping provably never
               starves foreground reads
  backpressure HTTP 429/503 + Retry-After computed from bucket refill
               time, S3 SlowDown XML, gRPC RESOURCE_EXHAUSTED — and
               util/retry honors the server's Retry-After on the way
               back up, closing the loop

Cost discipline (gated by test_perf_gates.test_qos_disabled_overhead):
with -qos off NOTHING here is constructed. configure() installs the
manager into each consumer seam as a module global; every seam's
disabled path is a single ``is None`` check and the tenant contextvar
is never set, so the pool submit path, the serving loop, and both
instrument wrappers are unchanged.
"""

from __future__ import annotations

from typing import Optional

from seaweedfs_tpu.qos import tenant
from seaweedfs_tpu.qos.admission import (AdmissionBucket, QosConfig,
                                         QosManager)
from seaweedfs_tpu.qos.fair import WeightedFairQueue

__all__ = ["AdmissionBucket", "QosConfig", "QosManager",
           "WeightedFairQueue", "configure", "enabled",
           "internal_context", "manager", "reset", "tenant"]

_manager: Optional[QosManager] = None


def manager() -> Optional[QosManager]:
    return _manager


def enabled() -> bool:
    return _manager is not None


def configure(cfg: Optional[QosConfig] = None) -> QosManager:
    """Build the process-wide manager and install it into every
    consumer seam. Idempotent per call — reconfiguring replaces the
    manager (tests; a live process configures once at startup)."""
    global _manager
    mgr = QosManager(cfg or QosConfig())
    _manager = mgr
    _install(mgr)
    return mgr


def reset() -> None:
    """Tear the manager out of every seam (tests). The disabled state
    is indistinguishable from never-configured."""
    global _manager
    _manager = None
    _install(None)


def _install(mgr: Optional[QosManager]) -> None:
    from seaweedfs_tpu import rpc
    from seaweedfs_tpu.stats import metrics
    from seaweedfs_tpu.util import async_server, fanout, http_client
    fanout._qos_sched = mgr
    async_server._qos = mgr
    metrics._qos_http = mgr
    tv = tenant.current if mgr is not None else None
    http_client._qos_tenant = tv
    rpc._qos_tenant = tv


class _NullCtx:
    """Reusable allocation-free no-op context (the disabled path of
    internal_context — background loops enter it every pass)."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_CTX = _NullCtx()


def internal_context():
    """Tag the calling thread's work as the ``_internal`` background
    tenant (scrub, lifecycle, filer_sync): exempt from admission,
    low-weight in the fair queues, forwarded on outbound hops. A
    no-op while QoS is off."""
    if _manager is None:
        return _NULL_CTX
    return tenant.as_tenant(tenant.INTERNAL)
