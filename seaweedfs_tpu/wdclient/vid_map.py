"""vid -> locations cache fed by KeepConnected deltas.

Reference: weed/wdclient/vid_map.go:30-150.
"""

from __future__ import annotations

import random
import threading
from typing import Dict, List, NamedTuple

from seaweedfs_tpu.operation.file_id import parse_fid


class Location(NamedTuple):
    url: str
    public_url: str


class VidMap:
    def __init__(self):
        self._lock = threading.RLock()
        self._by_vid: Dict[int, List[Location]] = {}

    def add_location(self, vid: int, loc: Location) -> None:
        with self._lock:
            locs = self._by_vid.setdefault(vid, [])
            if loc not in locs:
                locs.append(loc)

    def delete_location(self, vid: int, url: str) -> None:
        with self._lock:
            locs = self._by_vid.get(vid)
            if not locs:
                return
            self._by_vid[vid] = [l for l in locs if l.url != url]
            if not self._by_vid[vid]:
                del self._by_vid[vid]

    def drop_node(self, url: str) -> None:
        with self._lock:
            for vid in list(self._by_vid):
                self.delete_location(vid, url)

    def lookup(self, vid: int) -> List[Location]:
        with self._lock:
            return list(self._by_vid.get(vid, []))

    def lookup_file_id(self, fid: str) -> str:
        """fid -> full url "host:port/fid" on a random replica."""
        locs = self.lookup(parse_fid(fid).volume_id)
        if not locs:
            raise KeyError(f"volume of {fid} not in cache")
        return f"{random.choice(locs).url}/{fid}"

    def __len__(self) -> int:
        with self._lock:
            return len(self._by_vid)
