"""MasterClient: live vid->location cache + leader tracking.

Holds a background KeepConnected stream to the master; deltas keep the
VidMap fresh so data-path clients never block on /dir/lookup.

Reconnect discipline: the pre-resilience loop hammered the configured
masters in a tight 0.5 s rotation — a leaderless election window
turned every client into extra election load. Now each full failed
rotation backs off exponentially with FULL jitter (U(0, wait), wait
doubling to a 5 s cap), resets on any established stream, and counts
redials in SeaweedFS_master_reconnects_total. With breakers enabled a
master that refuses streams repeatedly is skipped until its cooldown.

Reference: weed/wdclient/masterclient.go:16-160.
"""

from __future__ import annotations

import random
import threading
from typing import Dict, List, Optional

import grpc

from seaweedfs_tpu.pb import master_pb2, master_stub
from seaweedfs_tpu.resilience import breaker
from seaweedfs_tpu.wdclient.vid_map import Location, VidMap

RECONNECT_WAIT_S = 0.2     # first backoff step after a failed rotation
RECONNECT_WAIT_CAP_S = 5.0


class MasterUnreachable(TimeoutError):
    """No configured master produced a KeepConnected stream in time.
    Subclasses TimeoutError so pre-existing callers keep catching it."""

    def __init__(self, masters: List[str], timeout: float):
        super().__init__(
            f"no master reachable within {timeout:.1f}s "
            f"(tried {', '.join(masters)})")
        self.masters = list(masters)


class MasterClient:
    def __init__(self, masters: List[str], client_name: str = "client",
                 grpc_port: int = 0):
        if not masters:
            raise ValueError("need at least one master address")
        self.masters = masters
        self.client_name = client_name
        self.grpc_port = grpc_port  # advertised via ListMasterClients
        self.current_master = masters[0]
        self.vid_map = VidMap()
        self.reconnects = 0   # redials after the initial dial (ledger)
        self._stop = threading.Event()
        self._ready = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._stream = None
        self._dialed = False
        # coalescing single-flight + TTL cache over the miss path
        # (-meta.lookupTTL, ISSUE 12): ABSENT — not merely empty —
        # unless enabled, so the disabled miss path is one None check.
        # The KeepConnected-fed vid_map stays the first stop either way.
        from seaweedfs_tpu.wdclient import lookup_cache as _lc
        self._lookup_cache = _lc.make_cache(self._lookup_batch) \
            if _lc.enabled else None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "MasterClient":
        # lint: thread-ok(keep-connected daemon; reconnects use their own jittered backoff)
        self._thread = threading.Thread(
            target=self._keep_connected_loop,
            name=f"masterclient-{self.client_name}", daemon=True)
        self._thread.start()
        return self

    def wait_until_connected(self, timeout: float = 10.0) -> None:
        if not self._ready.wait(timeout):
            raise MasterUnreachable(self.masters, timeout)

    def stop(self) -> None:
        self._stop.set()
        if self._stream is not None:
            self._stream.cancel()

    # -- stream --------------------------------------------------------------

    def _keep_connected_loop(self) -> None:
        wait = RECONNECT_WAIT_S
        while not self._stop.is_set():
            progressed = False
            for target in [self.current_master] + \
                    [m for m in self.masters if m != self.current_master]:
                if self._stop.is_set():
                    return
                if breaker.enabled and target != self.current_master:
                    # skip a master whose breaker is open — EXCEPT the
                    # current one, which stays the half-open probe path
                    if breaker.is_open(target):
                        continue
                try:
                    breaker.check(target)
                except breaker.BreakerOpen:
                    continue   # a refusal is not evidence of failure
                if self._follow(target):
                    progressed = True
            if self._stop.is_set():
                return
            if progressed:
                wait = RECONNECT_WAIT_S
                continue
            # full rotation failed: full-jitter exponential backoff so
            # a fleet of clients does not synchronize on the masters
            self._stop.wait(timeout=random.random() * wait)
            wait = min(wait * 2, RECONNECT_WAIT_CAP_S)

    def _follow(self, target: str) -> bool:
        """One KeepConnected stream's lifetime. Returns True when the
        stream established (>= 1 message), i.e. the redial backoff
        should reset. Never raises — ANY failure here (grpc, an armed
        rpc.call failpoint's OSError, anything) must cost one rotation
        step, never the keep-connected thread itself."""
        if self._dialed:
            self.reconnects += 1
            from seaweedfs_tpu.stats.metrics import MasterReconnectsCounter
            MasterReconnectsCounter.inc()
        self._dialed = True
        established = False
        try:
            stub = master_stub(target)
            self._stream = stub.KeepConnected(iter(
                [master_pb2.KeepConnectedRequest(name=self.client_name,
                                                 grpc_port=self.grpc_port)]))
            for loc in self._stream:
                if not established:
                    established = True
                    breaker.record(target, True)
                if self._stop.is_set():
                    return established
                self.current_master = target
                if loc.leader and loc.leader != target:
                    # not the leader: reconnect there next
                    self.current_master = loc.leader
                    self._stream.cancel()
                    return established
                self._apply(loc)
                self._ready.set()
        except Exception:  # noqa: BLE001 - see docstring
            from seaweedfs_tpu.stats import metrics
            metrics.swallowed("masterclient.follow")
        # a stream that BROKE after establishing is not a dead master;
        # a dial that never produced a message — whether it raised or
        # closed cleanly empty — is, and MUST be recorded: breaker
        # half-open probes are reclaimed by record(), so an unrecorded
        # probe would wedge the peer's breaker
        if not established:
            breaker.record(target, False)
        return established

    def _apply(self, loc: master_pb2.VolumeLocation) -> None:
        if loc.url:
            l = Location(loc.url, loc.public_url or loc.url)
            for vid in loc.new_vids:
                self.vid_map.add_location(vid, l)
            for vid in loc.deleted_vids:
                self.vid_map.delete_location(vid, loc.url)

    # -- lookups -------------------------------------------------------------

    def lookup(self, vid: int) -> List[Location]:
        locs = self.vid_map.lookup(vid)
        if locs:
            return locs
        if self._lookup_cache is not None:
            # coalesced + single-flighted + TTL'd (incl. negative)
            return list(self._lookup_cache.lookup(vid).locations)
        # cache miss: ask the master directly and backfill
        try:
            resp = master_stub(self.current_master).LookupVolume(
                master_pb2.LookupVolumeRequest(volume_ids=[str(vid)]))
        except grpc.RpcError:
            return []
        for vl in resp.volume_id_locations:
            for l in vl.locations:
                self.vid_map.add_location(vid, Location(l.url, l.public_url))
        return self.vid_map.lookup(vid)

    @property
    def lookup_cache_enabled(self) -> bool:
        """True when the coalescing cache is armed — the one check
        callers pay before batch-prefetching (disabled: no prefetch,
        the lazy per-chunk path is byte-identical to the old one)."""
        return self._lookup_cache is not None

    def lookup_many(self, vids) -> Dict[int, List[Location]]:
        """Resolve many vids at once: stream-fed vid_map hits answer
        locally, every miss rides ONE batched LookupVolume through the
        coalescing cache — a 64-chunk read's locations in one master
        round trip. Without the cache (disabled) this is exactly a
        loop over lookup(), so behavior off is unchanged."""
        out: Dict[int, List[Location]] = {}
        misses: List[int] = []
        for vid in dict.fromkeys(vids):
            locs = self.vid_map.lookup(vid)
            if locs:
                out[vid] = locs
            else:
                misses.append(vid)
        if not misses:
            return out
        if self._lookup_cache is not None:
            for vid, res in self._lookup_cache.lookup_many(misses).items():
                out[vid] = list(res.locations)
        else:
            for vid in misses:
                out[vid] = self.lookup(vid)
        return out

    def invalidate_lookup(self, vid: int,
                          reason: str = "read_failure") -> None:
        """A caller failed to read from every location lookup()
        returned: drop the cached belief so the next lookup re-asks."""
        if self._lookup_cache is not None:
            self._lookup_cache.invalidate(vid, reason)

    def _lookup_batch(self, vids: List[int]):
        """Batched LookupVolume against the current master — the
        coalescing cache's gRPC transport. Raises on transport failure
        (the cache answers waiters and caches nothing)."""
        from seaweedfs_tpu.wdclient.lookup_cache import LookupResult
        resp = master_stub(self.current_master).LookupVolume(
            master_pb2.LookupVolumeRequest(
                volume_ids=[str(v) for v in vids]))
        out: Dict[int, LookupResult] = {}
        for vl in resp.volume_id_locations:
            try:
                vid = int(vl.volume_id.split(",")[0])
            except ValueError:
                continue
            if vl.error:
                out[vid] = LookupResult((), vl.error)
            else:
                out[vid] = LookupResult(tuple(
                    Location(l.url, l.public_url or l.url)
                    for l in vl.locations), "")
        return out

    def lookup_file_id(self, fid: str) -> str:
        from seaweedfs_tpu.operation.file_id import parse_fid
        vid = parse_fid(fid).volume_id
        locs = self.lookup(vid)
        if not locs:
            raise KeyError(f"volume {vid} has no known locations")
        return f"{locs[0].url}/{fid}"
