"""MasterClient: live vid->location cache + leader tracking.

Holds a background KeepConnected stream to the master; deltas keep the
VidMap fresh so data-path clients never block on /dir/lookup.

Reference: weed/wdclient/masterclient.go:16-160.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

import grpc

from seaweedfs_tpu.pb import master_pb2, master_stub
from seaweedfs_tpu.wdclient.vid_map import Location, VidMap


class MasterClient:
    def __init__(self, masters: List[str], client_name: str = "client",
                 grpc_port: int = 0):
        if not masters:
            raise ValueError("need at least one master address")
        self.masters = masters
        self.client_name = client_name
        self.grpc_port = grpc_port  # advertised via ListMasterClients
        self.current_master = masters[0]
        self.vid_map = VidMap()
        self._stop = threading.Event()
        self._ready = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._stream = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "MasterClient":
        self._thread = threading.Thread(
            target=self._keep_connected_loop,
            name=f"masterclient-{self.client_name}", daemon=True)
        self._thread.start()
        return self

    def wait_until_connected(self, timeout: float = 10.0) -> None:
        if not self._ready.wait(timeout):
            raise TimeoutError("master KeepConnected never came up")

    def stop(self) -> None:
        self._stop.set()
        if self._stream is not None:
            self._stream.cancel()

    # -- stream --------------------------------------------------------------

    def _keep_connected_loop(self) -> None:
        while not self._stop.is_set():
            for target in [self.current_master] + \
                    [m for m in self.masters if m != self.current_master]:
                if self._stop.is_set():
                    return
                try:
                    self._follow(target)
                except grpc.RpcError:
                    continue
            time.sleep(0.5)

    def _follow(self, target: str) -> None:
        stub = master_stub(target)
        self._stream = stub.KeepConnected(iter(
            [master_pb2.KeepConnectedRequest(name=self.client_name,
                                             grpc_port=self.grpc_port)]))
        for loc in self._stream:
            if self._stop.is_set():
                return
            self.current_master = target
            if loc.leader and loc.leader != target:
                # not the leader: reconnect there next
                self.current_master = loc.leader
                self._stream.cancel()
                return
            self._apply(loc)
            self._ready.set()

    def _apply(self, loc: master_pb2.VolumeLocation) -> None:
        if loc.url:
            l = Location(loc.url, loc.public_url or loc.url)
            for vid in loc.new_vids:
                self.vid_map.add_location(vid, l)
            for vid in loc.deleted_vids:
                self.vid_map.delete_location(vid, loc.url)

    # -- lookups -------------------------------------------------------------

    def lookup(self, vid: int) -> List[Location]:
        locs = self.vid_map.lookup(vid)
        if locs:
            return locs
        # cache miss: ask the master directly and backfill
        try:
            resp = master_stub(self.current_master).LookupVolume(
                master_pb2.LookupVolumeRequest(volume_ids=[str(vid)]))
        except grpc.RpcError:
            return []
        for vl in resp.volume_id_locations:
            for l in vl.locations:
                self.vid_map.add_location(vid, Location(l.url, l.public_url))
        return self.vid_map.lookup(vid)

    def lookup_file_id(self, fid: str) -> str:
        from seaweedfs_tpu.operation.file_id import parse_fid
        vid = parse_fid(fid).volume_id
        locs = self.lookup(vid)
        if not locs:
            raise KeyError(f"volume {vid} has no known locations")
        return f"{locs[0].url}/{fid}"
