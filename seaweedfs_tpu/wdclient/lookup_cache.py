"""Coalescing vid -> locations lookup cache: single-flight + TTL +
batched round trips — the LeaseCache discipline applied to the
metadata READ side (ISSUE 12).

Every serving path funnels through "where does volume N live?": the
filer resolves one lookup per chunk, `operations` clients one per
call, and the master answers each one as its own round trip. At high
read QPS the master becomes the wall long before the volume servers
do. This module makes those reads batch, coalesce, and cache:

  single-flight  concurrent misses for ONE vid elect a leader; every
                 other caller waits on the leader's flight and reuses
                 its answer (one RPC, not W).
  coalescing     misses arriving within a short window (a few ms) join
                 one FORMING batch; the window leader issues a single
                 batched ``/dir/lookup?volumeIds=a,b,c`` (or gRPC
                 ``LookupVolume`` with many ``volume_ids``) covering
                 everyone — a 64-chunk file read resolves in one
                 master round trip instead of 64.
  TTL            positive entries expire after `ttl_s` (a moved volume
                 is re-resolved without a restart); NOT-FOUND answers
                 are cached for the shorter `negative_ttl_s`, so a
                 miss storm on a deleted volume costs one round trip
                 per window instead of hammering the master.
  invalidation   a caller that failed to READ from every returned
                 location drops the entry (`invalidate`) — the cached
                 belief was observed wrong, the next lookup re-asks.

Transport failures resolve waiting flights with an error but are
never cached: the next call must retry the master, not trust a blip.

Cost discipline: nothing here spawns a thread — the batch leader runs
on the caller's thread and the window is a bounded sleep held OUTSIDE
the lock. Disabled (the default) no cache object exists anywhere and
every wired call site pays one module-flag check
(tests/test_perf_gates.py::test_meta_disabled_overhead).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, Iterable, List, NamedTuple, Optional, Tuple

from seaweedfs_tpu.wdclient.vid_map import Location

DEFAULT_TTL_S = 30.0
DEFAULT_NEGATIVE_TTL_S = 2.0
DEFAULT_COALESCE_MS = 2.0
DEFAULT_BATCH_MAX = 128
# How long a follower waits on a flight before giving up — generous:
# a lookup RPC is milliseconds, and an abandoned wait must not hang a
# serving thread forever behind a wedged leader.
FLIGHT_WAIT_S = 30.0


class LookupResult(NamedTuple):
    """Per-vid answer: locations, or why there are none. One bad vid
    never fails its batch — errors travel per entry."""
    locations: Tuple[Location, ...]
    error: str = ""


class _Flight:
    """One in-flight fetch of one vid. The leader writes `result`
    before setting `event` (happens-before via Event)."""

    __slots__ = ("event", "result")

    def __init__(self):
        self.event = threading.Event()
        self.result: Optional[LookupResult] = None


class CoalescingLookupCache:
    """vid -> LookupResult with TTL, single-flight, and a coalescing
    batch window. `fetch_many(vids) -> Dict[vid, LookupResult]` is the
    injected transport (HTTP or gRPC batched lookup); it may raise on
    transport failure — waiters get the error, nothing is cached."""

    def __init__(self, fetch_many: Callable[[List[int]],
                                            Dict[int, LookupResult]],
                 ttl_s: float = DEFAULT_TTL_S,
                 negative_ttl_s: float = DEFAULT_NEGATIVE_TTL_S,
                 coalesce_s: float = DEFAULT_COALESCE_MS / 1000.0,
                 batch_max: int = DEFAULT_BATCH_MAX):
        self._fetch_many = fetch_many
        self.ttl_s = ttl_s
        self.negative_ttl_s = negative_ttl_s
        self.coalesce_s = coalesce_s
        self.batch_max = max(1, int(batch_max))
        self._lock = threading.Lock()
        # vid -> (result, expires_at monotonic)
        self._cache: Dict[int, Tuple[LookupResult, float]] = {}  # guarded_by(self._lock)
        self._flights: Dict[int, _Flight] = {}  # guarded_by(self._lock)
        # the batch currently forming (misses append; its window
        # leader takes it when the window closes)
        self._forming: Optional[List[int]] = None  # guarded_by(self._lock)
        # callers currently inside lookup_many — the window leader
        # only sleeps out the coalesce window when someone ELSE is in
        # flight to join it (a lone sequential caller has nothing to
        # coalesce with and must not pay the window as pure latency)
        self._active = 0  # guarded_by(self._lock)
        # ledger (exact under the lock; also exported as metrics)
        self.hits = 0  # guarded_by(self._lock, writes)
        self.negative_hits = 0  # guarded_by(self._lock, writes)
        self.misses = 0  # guarded_by(self._lock, writes)
        self.invalidations = 0  # guarded_by(self._lock, writes)
        from seaweedfs_tpu.stats.metrics import MetaLookupCounter
        # labels() locks the family per call: resolve children once
        self._c_hit = MetaLookupCounter.labels("hit")
        self._c_neg = MetaLookupCounter.labels("negative_hit")
        self._c_miss = MetaLookupCounter.labels("miss")

    # -- lookups --------------------------------------------------------------

    def lookup(self, vid: int) -> LookupResult:
        return self.lookup_many([vid])[vid]

    def lookup_many(self, vids: Iterable[int]) -> Dict[int, LookupResult]:
        """Resolve many vids in (at most) one batched round trip for
        the misses; hits answer locally. Every requested vid is in the
        returned dict."""
        with self._lock:
            self._active += 1
        try:
            return self._lookup_many(vids)
        finally:
            with self._lock:
                self._active -= 1

    def _lookup_many(self, vids: Iterable[int]) -> Dict[int, LookupResult]:
        from seaweedfs_tpu.stats.metrics import MetaLookupWaitersCounter
        out: Dict[int, LookupResult] = {}
        waits: List[Tuple[int, _Flight]] = []
        lead_batch: Optional[List[int]] = None
        my_added = 0
        hits = neg = misses = waiters = 0
        now = time.monotonic()
        with self._lock:
            for vid in dict.fromkeys(vids):
                ent = self._cache.get(vid)
                if ent is not None and ent[1] > now:
                    out[vid] = ent[0]
                    if ent[0].error:
                        neg += 1
                        self.negative_hits += 1
                    else:
                        hits += 1
                        self.hits += 1
                    continue
                misses += 1
                self.misses += 1
                fl = self._flights.get(vid)
                if fl is None:
                    fl = self._flights[vid] = _Flight()
                    if self._forming is None:
                        # we open the window and lead its batch
                        self._forming = []
                        lead_batch = self._forming
                    self._forming.append(vid)
                    my_added += 1
                else:
                    waiters += 1
                waits.append((vid, fl))
        # metric emission strictly outside the lock (house rule: the
        # family lock must never nest under a subsystem lock)
        if hits:
            self._c_hit.inc(hits)
        if neg:
            self._c_neg.inc(neg)
        if misses:
            self._c_miss.inc(misses)
        if waiters:
            MetaLookupWaitersCounter.inc(waiters)
        if lead_batch is not None:
            try:
                if self.coalesce_s > 0:
                    # the coalescing window: misses on other threads
                    # join `_forming` while we sleep (never under the
                    # lock). A LONE caller skips it — with nobody else
                    # inside lookup_many and no vid joined from
                    # another thread, the sleep is pure latency (a
                    # sequential shell loop over 10k vids would pay
                    # 10k windows for zero fusion).
                    with self._lock:
                        lone = self._active <= 1 and \
                            len(lead_batch) == my_added
                    if not lone:
                        time.sleep(self.coalesce_s)
            finally:
                # take the batch even when the sleep dies on a
                # BaseException (interrupt): a window left FORMING
                # forever would make every future miss join a
                # leaderless batch that nobody ever resolves
                with self._lock:
                    batch = list(lead_batch)
                    if self._forming is lead_batch:
                        self._forming = None
            for i in range(0, len(batch), self.batch_max):
                self._resolve(batch[i:i + self.batch_max])
        for vid, fl in waits:
            if vid in out:
                continue
            if not fl.event.wait(timeout=FLIGHT_WAIT_S):
                # a leader that died on a non-Exception (interrupt,
                # SystemExit) can never resolve this flight — drop it
                # so later lookups open a fresh one instead of queueing
                # behind a corpse forever; if its WINDOW is also still
                # forming (the leader died before taking the batch),
                # close that too so the next miss elects a new leader
                with self._lock:
                    if self._flights.get(vid) is fl:
                        del self._flights[vid]
                        # only while OUR flight was still registered:
                        # a forming window holding this vid must be
                        # the dead leader's (a healthy new window
                        # would have needed a fresh flight)
                        if self._forming is not None and \
                                vid in self._forming:
                            self._forming = None
                out[vid] = LookupResult(
                    (), f"lookup of volume {vid} timed out waiting for "
                        "the single-flight leader")
                continue
            out[vid] = fl.result if fl.result is not None else \
                LookupResult((), f"volume {vid} lookup produced no result")
        return out

    def _resolve(self, vids: List[int]) -> None:
        """Leader half: ONE batched round trip for `vids`, publish the
        per-vid answers, release every waiter."""
        from seaweedfs_tpu.stats import trace
        from seaweedfs_tpu.stats.metrics import MetaLookupBatchHistogram
        MetaLookupBatchHistogram.observe(len(vids))
        sp = trace.span("meta.lookup", vids=len(vids)) \
            if trace.is_enabled() else trace.NOOP
        err: Optional[BaseException] = None
        results: Optional[Dict[int, LookupResult]] = None
        with sp:
            try:
                results = self._fetch_many(list(vids))
            except Exception as e:  # noqa: BLE001 - resolved per flight below
                err = e
        now = time.monotonic()
        release: List[_Flight] = []
        with self._lock:
            for vid in vids:
                if results is not None:
                    res = results.get(vid)
                    if res is None:
                        res = LookupResult((), f"volume {vid} not found")
                    ttl = self.negative_ttl_s if res.error else self.ttl_s
                    if ttl > 0:
                        self._cache[vid] = (res, now + ttl)
                else:
                    # transport failure: answer the waiters, cache
                    # NOTHING — the next call must retry the master
                    res = LookupResult((), f"lookup failed: {err!r}")
                fl = self._flights.pop(vid, None)
                if fl is not None:
                    fl.result = res
                    release.append(fl)
        for fl in release:
            fl.event.set()

    # -- invalidation ---------------------------------------------------------

    def invalidate(self, vid: int, reason: str = "read_failure") -> bool:
        """Drop one vid's cached answer (the caller observed it wrong —
        e.g. every returned location failed the actual read)."""
        with self._lock:
            dropped = self._cache.pop(vid, None) is not None
            if dropped:
                self.invalidations += 1
        if dropped:
            from seaweedfs_tpu.stats.metrics import \
                MetaLookupInvalidationsCounter
            MetaLookupInvalidationsCounter.labels(reason).inc()
        return dropped

    def stats(self) -> Dict:
        with self._lock:
            return {"entries": len(self._cache), "hits": self.hits,
                    "negative_hits": self.negative_hits,
                    "misses": self.misses,
                    "invalidations": self.invalidations}


# -- module seam (the -meta.lookup* flags) ------------------------------------
#
# `enabled` is the one check every wired call site pays when the cache
# is off; `configure()` is called by the server CLIs, the env vars arm
# spawned benches/tools the way SEAWEED_TRACE_SAMPLE does.

enabled = False
_ttl_s = DEFAULT_TTL_S
_negative_ttl_s = DEFAULT_NEGATIVE_TTL_S
_coalesce_s = DEFAULT_COALESCE_MS / 1000.0
_batch_max = DEFAULT_BATCH_MAX

_caches_lock = threading.Lock()
# (master_url, collection) -> shared per-process cache
_caches: Dict[Tuple[str, str], CoalescingLookupCache] = {}  # guarded_by(_caches_lock)


def configure(enable: bool = True, ttl_s: Optional[float] = None,
              negative_ttl_s: Optional[float] = None,
              coalesce_ms: Optional[float] = None,
              batch_max: Optional[int] = None) -> None:
    global enabled, _ttl_s, _negative_ttl_s, _coalesce_s, _batch_max
    if ttl_s is not None:
        _ttl_s = ttl_s
    if negative_ttl_s is not None:
        _negative_ttl_s = negative_ttl_s
    if coalesce_ms is not None:
        _coalesce_s = coalesce_ms / 1000.0
    if batch_max is not None:
        _batch_max = batch_max
    enabled = bool(enable) and _ttl_s > 0


def reset() -> None:
    """Tests: drop every cache and disable."""
    global enabled
    enabled = False
    with _caches_lock:
        _caches.clear()


def make_cache(fetch_many) -> CoalescingLookupCache:
    """A cache honoring the module tunables, over an injected
    transport (e.g. MasterClient's gRPC batched lookup)."""
    return CoalescingLookupCache(
        fetch_many, ttl_s=_ttl_s, negative_ttl_s=_negative_ttl_s,
        coalesce_s=_coalesce_s, batch_max=_batch_max)


def for_master(master_url: str,
               collection: str = "") -> CoalescingLookupCache:
    """The process-wide cache for one (master, collection), fetching
    over the batched HTTP ``/dir/lookup?volumeIds=`` surface (pooled —
    measurably cheaper per call than grpc-python on the same box, the
    operations.assign finding)."""
    key = (master_url, collection)
    with _caches_lock:
        c = _caches.get(key)
    if c is None:
        # constructed OUTSIDE _caches_lock: __init__ resolves metric
        # children (the family lock), which must never nest under a
        # subsystem lock; a racing double construction loses to
        # setdefault and is garbage-collected
        c = make_cache(
            lambda vids: http_fetch_many(master_url, vids, collection))
        with _caches_lock:
            c = _caches.setdefault(key, c)
    return c


def http_fetch_many(master_url: str, vids: List[int],
                    collection: str = "") -> Dict[int, LookupResult]:
    """One batched ``GET /dir/lookup?volumeIds=a,b,c`` round trip.
    (``volumeIds``, not ``volumeId`` — the legacy param's comma already
    belongs to the fid grammar ``<vid>,<key><cookie>``, so a batch
    there would misparse fids whose hex happens to be all digits.)"""
    from seaweedfs_tpu.util import http_client
    qs = "volumeIds=" + ",".join(str(v) for v in vids)
    if collection:
        import urllib.parse
        qs += "&collection=" + urllib.parse.quote(collection)
    r = http_client.request("GET", f"{master_url}/dir/lookup?{qs}")
    if r.status >= 300:
        # a 503 mid-leader-election is a TRANSPORT failure: raising
        # here answers waiters with the error and caches nothing —
        # swallowing it would negative-cache the whole batch as
        # not-found for negative_ttl_s after the master recovers
        raise IOError(f"lookup http {r.status} from {master_url}")
    out = json.loads(r.body)
    results: Dict[int, LookupResult] = {}
    entries = out.get("volumeIdLocations")
    if entries is None:
        if "volumeId" not in out or len(vids) > 1:
            # a top-level {"error": ...} body, or a single-vid legacy
            # answer to a MULTI-vid batch (non-batch-aware master):
            # either way we have no per-vid answers — transport-class
            # failure, cache nothing
            reason = out.get("error", "unrecognized response shape")
            raise IOError(f"lookup failed: {reason}")
        # single-vid legacy shape for the one vid we asked for
        entries = [out]
    for vl in entries:
        try:
            vid = int(str(vl.get("volumeId", "")).split(",")[0])
        except ValueError:
            continue
        if vl.get("error"):
            results[vid] = LookupResult((), vl["error"])
        else:
            results[vid] = LookupResult(tuple(
                Location(l["url"], l.get("publicUrl") or l["url"])
                for l in vl.get("locations", [])), "")
    return results


def invalidate(master_url: str, vid: int,
               reason: str = "read_failure") -> None:
    """Drop `vid` from every collection-view of `master_url`'s cache
    (read failures don't know which collection resolved the vid)."""
    with _caches_lock:
        caches = [c for (m, _coll), c in _caches.items()
                  if m == master_url]
    for c in caches:
        c.invalidate(vid, reason)


def _env_configure() -> None:
    """SEAWEED_META_LOOKUP_TTL_S arms the cache at import for spawned
    benches/tools (the SEAWEED_TRACE_SAMPLE pattern); the sibling env
    vars tune it."""
    raw = os.environ.get("SEAWEED_META_LOOKUP_TTL_S")
    if not raw:
        return
    try:
        ttl = float(raw)
    except ValueError:
        return

    # a malformed sibling tunable falls back to its default: this runs
    # at import time in every server and tool, and one typo'd env var
    # must degrade a knob, not crash the process
    def _num(name, default, cast):
        try:
            return cast(os.environ.get(name, default))
        except ValueError:
            return default

    configure(
        enable=ttl > 0, ttl_s=ttl,
        negative_ttl_s=_num("SEAWEED_META_NEGATIVE_TTL_S",
                            DEFAULT_NEGATIVE_TTL_S, float),
        coalesce_ms=_num("SEAWEED_META_COALESCE_MS",
                         DEFAULT_COALESCE_MS, float),
        batch_max=_num("SEAWEED_META_BATCH_MAX",
                       DEFAULT_BATCH_MAX, int))


_env_configure()
