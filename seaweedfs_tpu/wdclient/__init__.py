"""Cluster client library (reference weed/wdclient)."""

from seaweedfs_tpu.wdclient.masterclient import MasterClient
from seaweedfs_tpu.wdclient.vid_map import VidMap

__all__ = ["MasterClient", "VidMap"]
