"""AWS SQS notification queue over plain HTTP + SigV4 — no SDK.

Behavioral parity with the reference's aws-sdk-go publisher
(weed/notification/aws_sqs/aws_sqs_pub.go:17-100): resolve the queue
URL by name at startup (GetQueueUrl), then SendMessage per event with
the event key in a `key` message attribute and the EventNotification
in protobuf text format as the body. The wire protocol is the SQS
query API: form-encoded POSTs signed with SigV4 service="sqs".
"""

from __future__ import annotations

import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET
from typing import List, Tuple

from seaweedfs_tpu.notification import MessageQueue
from seaweedfs_tpu.util.aws_auth import sigv4_headers


class SqsError(Exception):
    pass


class AwsSqsQueue(MessageQueue):
    def __init__(self, sqs_queue_name: str = "",
                 aws_access_key_id: str = "",
                 aws_secret_access_key: str = "",
                 region: str = "us-east-1",
                 endpoint: str = "", queue_url: str = "",
                 timeout: float = 30.0, **_ignored):
        """`queue_url` skips discovery (also the local-emulator path);
        otherwise GetQueueUrl on `endpoint` (default: the public
        sqs.<region>.amazonaws.com) resolves `sqs_queue_name`."""
        self.access_key = aws_access_key_id
        self.secret_key = aws_secret_access_key
        self.region = region
        self.timeout = timeout
        if not endpoint:
            # the real AWS endpoint is TLS-only
            self.endpoint = f"https://sqs.{region}.amazonaws.com"
        elif "://" in endpoint:
            self.endpoint = endpoint.rstrip("/")
        else:
            # bare host:port means a local emulator; those speak http
            self.endpoint = f"http://{endpoint}"
        if queue_url:
            self.queue_url = queue_url
        else:
            if not sqs_queue_name:
                raise ValueError(
                    "aws_sqs needs sqs_queue_name or queue_url")
            self.queue_url = self._get_queue_url(sqs_queue_name)

    # -- SQS query-protocol plumbing -----------------------------------------

    def _call(self, url: str, params: List[Tuple[str, str]]) -> bytes:
        u = urllib.parse.urlparse(
            url if "://" in url else f"https://{url}")
        payload = urllib.parse.urlencode(params,
                                         quote_via=urllib.parse.quote
                                         ).encode()
        headers = sigv4_headers(
            "POST", u.netloc, u.path or "/", [],
            {"content-type": "application/x-www-form-urlencoded"},
            payload, self.access_key, self.secret_key, self.region,
            "sqs")
        req = urllib.request.Request(
            f"{u.scheme}://{u.netloc}{u.path or '/'}",
            data=payload, method="POST", headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return r.read()
        except urllib.error.HTTPError as e:
            raise SqsError(
                f"SQS HTTP {e.code}: "
                f"{e.read().decode('utf-8', 'replace')[:300]}") from None
        except OSError as e:   # URLError, timeouts, refused connections
            raise SqsError(f"SQS {u.netloc} unreachable: {e}") from None

    def _get_queue_url(self, name: str) -> str:
        body = self._call(self.endpoint, [
            ("Action", "GetQueueUrl"), ("QueueName", name),
            ("Version", "2012-11-05")])
        url = _find_text(body, "QueueUrl")
        if not url:
            raise SqsError(f"unable to find queue {name}")
        return url

    # -- MessageQueue SPI -----------------------------------------------------

    def send_message(self, key, event) -> None:
        from google.protobuf import text_format
        self._call(self.queue_url, [
            ("Action", "SendMessage"),
            ("MessageAttribute.1.Name", "key"),
            ("MessageAttribute.1.Value.DataType", "String"),
            ("MessageAttribute.1.Value.StringValue", key),
            ("MessageBody", text_format.MessageToString(event)),
            # the reference publisher delays every message 10s
            # (aws_sqs_pub.go SendMessageInput.DelaySeconds); keep
            # consumer-visible timing identical
            ("DelaySeconds", "10"),
            ("Version", "2012-11-05")])


def _find_text(xml_blob: bytes, tag: str) -> str:
    root = ET.fromstring(xml_blob)
    for el in root.iter():
        if el.tag.endswith(tag):
            return el.text or ""
    return ""
