"""Notification queues: publish filer events for external consumers
(reference: weed/notification — log/Kafka/SQS/PubSub backends behind
one interface; here: memory + file-log backends, with a registry for
environments that provide richer brokers)."""

from __future__ import annotations

import os
import threading
from typing import Callable, Dict, List, Optional, Tuple

from seaweedfs_tpu.pb import filer_pb2
from seaweedfs_tpu.util.log_buffer import LogEntry


class MessageQueue:
    """SPI: send_message(key, EventNotification)."""

    def send_message(self, key: str,
                     event: filer_pb2.EventNotification) -> None:
        raise NotImplementedError


class MemoryQueue(MessageQueue):
    """In-process queue with subscriber callbacks (test/dev backend)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.messages: List[Tuple[str, filer_pb2.EventNotification]] = []
        self._subscribers: List[Callable] = []

    def send_message(self, key, event):
        with self._lock:
            self.messages.append((key, event))
            subs = list(self._subscribers)
        for fn in subs:
            fn(key, event)

    def subscribe(self, fn: Callable) -> None:
        with self._lock:
            self._subscribers.append(fn)


class LogQueue(MessageQueue):
    """Append events to a local log file with the shared length-prefixed
    framing (reference notification/log — a debugging sink)."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._lock = threading.Lock()

    def send_message(self, key, event):
        rec = filer_pb2.SubscribeMetadataResponse(
            directory=key, event_notification=event)
        blob = LogEntry(0, 0, rec.SerializeToString()).pack()
        with self._lock, open(self.path, "ab") as f:
            f.write(blob)

    def read_all(self) -> List[Tuple[str, filer_pb2.EventNotification]]:
        if not os.path.exists(self.path):
            return []
        with open(self.path, "rb") as f:
            blob = f.read()
        out = []
        for e in LogEntry.unpack_stream(blob):
            rec = filer_pb2.SubscribeMetadataResponse()
            rec.ParseFromString(e.data)
            out.append((rec.directory, rec.event_notification))
        return out


def _gated(name: str, package: str) -> Callable[..., MessageQueue]:
    """Factory for broker backends whose client SDK is not in this
    image (reference ships kafka/sqs/pubsub backends behind the same
    interface): config naming them fails loudly with the remedy."""
    def factory(*a, **kw):
        raise RuntimeError(
            f"notification backend {name!r} needs the {package} client "
            f"library, which is not in this image; use 'log' (durable "
            f"file queue) or 'memory', or install {package}")
    return factory


def _aws_sqs_factory(**kw) -> MessageQueue:
    # lazy import: aws_sqs imports MessageQueue from this module
    from seaweedfs_tpu.notification.aws_sqs import AwsSqsQueue
    return AwsSqsQueue(**kw)


def _kafka_factory(**kw) -> MessageQueue:
    from seaweedfs_tpu.notification.kafka import KafkaQueue
    return KafkaQueue(**kw)


def _pubsub_factory(**kw) -> MessageQueue:
    from seaweedfs_tpu.notification.google_pub_sub import \
        GooglePubSubQueue
    return GooglePubSubQueue(**kw)


_REGISTRY: Dict[str, Callable[..., MessageQueue]] = {
    "memory": MemoryQueue,
    "log": LogQueue,
    "kafka": _kafka_factory,        # binary wire protocol, no SDK needed
    "aws_sqs": _aws_sqs_factory,    # SigV4 over HTTP, no SDK needed
    "google_pub_sub": _pubsub_factory,  # REST + RS256 JWT, no SDK needed
    "gocdk_pub_sub": _gated("gocdk_pub_sub", "a Go CDK bridge"),
}


def register(name: str, factory: Callable[..., MessageQueue]) -> None:
    _REGISTRY[name] = factory


def new_queue(name: str, **kwargs) -> MessageQueue:
    factory = _REGISTRY.get(name)
    if factory is None:
        raise ValueError(
            f"notification backend {name!r} not available in this "
            f"image; registered: {sorted(_REGISTRY)}")
    return factory(**kwargs)


# backends that talk to an external service get the async wrapper so
# their outages never stall the filer write path
_REMOTE = frozenset({"kafka", "aws_sqs", "google_pub_sub",
                     "gocdk_pub_sub"})


def from_config(conf) -> Optional[MessageQueue]:
    """Build the queue from a notification.toml Configuration: the
    first enabled [notification.X] section wins, its remaining keys
    become factory kwargs (reference notification.LoadConfiguration,
    weed/notification/configuration.go). Remote backends come back
    wrapped in AsyncQueue."""
    sections = (conf.get("notification") or {}) if conf else {}
    for name, props in sections.items():
        if not isinstance(props, dict) or not props.get("enabled"):
            continue
        kwargs = {k: v for k, v in props.items() if k != "enabled"}
        q = new_queue(name, **kwargs)
        return AsyncQueue(q) if name in _REMOTE else q
    return None


class AsyncQueue(MessageQueue):
    """Non-blocking wrapper for remote backends: send_message enqueues
    into a bounded buffer and a sender thread does the wire work, so a
    dead broker/endpoint stalls the publisher thread, not the filer
    write path (the reference gets this from sarama's AsyncProducer for
    kafka; here every remote backend rides the same mechanism). When
    the buffer is full the OLDEST event is dropped and counted."""

    MAX_PENDING = 1024

    def __init__(self, inner: MessageQueue):
        import collections
        self.inner = inner
        self._pending = collections.deque()
        self._cv = threading.Condition()
        self._inflight = 0
        self._closed = False
        self.dropped = 0
        self.failed = 0      # monotonic: sends the backend rejected
        self.last_error: Optional[Exception] = None   # None after success
        self.last_failure: Optional[Exception] = None  # never reset
        # lint: gate-ok(built only when a notification backend is configured) # lint: thread-ok(async sender is deliberately decoupled from the committing request)
        self._sender = threading.Thread(target=self._run,
                                        name="notify-sender", daemon=True)
        self._sender.start()

    def send_message(self, key, event) -> None:
        with self._cv:
            if self._closed:
                raise RuntimeError("notification queue is closed")
            if len(self._pending) >= self.MAX_PENDING:
                self._pending.popleft()
                self.dropped += 1
            self._pending.append((key, event))
            self._cv.notify()

    def flush(self, timeout: float = 10.0) -> bool:
        """Block until everything enqueued so far is delivered (or
        failed); False on timeout."""
        import time
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._pending or self._inflight:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(min(left, 0.05))
        return True

    def _run(self) -> None:
        from seaweedfs_tpu.util import wlog
        log = wlog.logger("notify")
        while True:
            with self._cv:
                while not self._pending and not self._closed:
                    self._cv.wait()
                if not self._pending and self._closed:
                    return
                key, event = self._pending.popleft()
                self._inflight += 1
            try:
                self.inner.send_message(key, event)
                with self._cv:
                    self.last_error = None
            except Exception as e:   # noqa: BLE001 — any backend error
                with self._cv:
                    self.last_error = e
                    self.last_failure = e
                    self.failed += 1
                log.warning("notification publish failed, event "
                            "dropped: %s", e)
            finally:
                with self._cv:
                    self._inflight -= 1
                    self._cv.notify_all()

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._sender.join(timeout=30.0)
        if hasattr(self.inner, "close"):
            self.inner.close()
