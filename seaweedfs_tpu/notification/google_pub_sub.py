"""Google Cloud Pub/Sub notification queue over REST — no SDK.

Behavioral parity with the reference's cloud.google.com/go publisher
(weed/notification/google_pub_sub/google_pub_sub.go:20-80): reads the
service-account JSON named by `google_application_credentials` (or the
GOOGLE_APPLICATION_CREDENTIALS env var), ensures the topic exists
(create-if-missing), and publishes one message per event with the key
in attributes and the serialized EventNotification as data.

Auth is the standard service-account OAuth2 flow implemented directly:
a self-signed RS256 JWT (util/rsa_sign.py) exchanged at token_uri for a
bearer token, cached until near expiry.
"""

from __future__ import annotations

import base64
import json
import os
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Optional

from seaweedfs_tpu.notification import MessageQueue
from seaweedfs_tpu.util import rsa_sign

PUBSUB_SCOPE = "https://www.googleapis.com/auth/pubsub"


class PubSubError(Exception):
    pass


class GooglePubSubQueue(MessageQueue):
    def __init__(self, google_application_credentials: str = "",
                 project_id: str = "", topic: str = "",
                 endpoint: str = "https://pubsub.googleapis.com",
                 timeout: float = 30.0, **_ignored):
        creds_path = google_application_credentials or \
            os.environ.get("GOOGLE_APPLICATION_CREDENTIALS", "")
        if not creds_path:
            raise ValueError(
                "google_pub_sub needs google_application_credentials "
                "(or the GOOGLE_APPLICATION_CREDENTIALS env var)")
        with open(creds_path) as f:
            creds = json.load(f)
        self.key = rsa_sign.parse_private_key_pem(creds["private_key"])
        self.client_email = creds["client_email"]
        self.token_uri = creds.get(
            "token_uri", "https://oauth2.googleapis.com/token")
        self.project_id = project_id or creds.get("project_id", "")
        if not self.project_id or not topic:
            raise ValueError("google_pub_sub needs project_id and topic")
        self.topic = topic
        self.endpoint = endpoint.rstrip("/")
        self.timeout = timeout
        self._token: Optional[str] = None
        self._token_expiry = 0.0
        self._ensure_topic()

    # -- OAuth2 service-account flow ------------------------------------------

    def _bearer(self) -> str:
        if self._token and time.time() < self._token_expiry - 60:
            return self._token
        now = int(time.time())
        assertion = rsa_sign.make_jwt(self.key, {
            "iss": self.client_email, "scope": PUBSUB_SCOPE,
            "aud": self.token_uri, "iat": now, "exp": now + 3600})
        body = urllib.parse.urlencode({
            "grant_type": "urn:ietf:params:oauth:grant-type:jwt-bearer",
            "assertion": assertion}).encode()
        doc = json.loads(self._http("POST", self.token_uri, body,
                                    {"Content-Type":
                                     "application/x-www-form-urlencoded"}))
        self._token = doc["access_token"]
        self._token_expiry = time.time() + float(
            doc.get("expires_in", 3600))
        return self._token

    def _http(self, method: str, url: str, body: Optional[bytes],
              headers: dict) -> bytes:
        req = urllib.request.Request(url, data=body, method=method,
                                     headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return r.read()
        except urllib.error.HTTPError as e:
            raise PubSubError(
                f"pubsub HTTP {e.code} on {method} {url}: "
                f"{e.read().decode('utf-8', 'replace')[:300]}") from None
        except OSError as e:
            raise PubSubError(f"pubsub {url} unreachable: {e}") from None

    def _api(self, method: str, path: str,
             doc: Optional[dict] = None) -> dict:
        body = json.dumps(doc).encode() if doc is not None else None
        raw = self._http(
            method, f"{self.endpoint}/v1/{path}", body,
            {"Authorization": f"Bearer {self._bearer()}",
             "Content-Type": "application/json"})
        return json.loads(raw) if raw else {}

    # -- topic lifecycle ------------------------------------------------------

    @property
    def _topic_path(self) -> str:
        return f"projects/{self.project_id}/topics/{self.topic}"

    def _ensure_topic(self) -> None:
        """Create-if-missing, like the reference's Exists/CreateTopic."""
        try:
            self._api("GET", self._topic_path)
        except PubSubError as e:
            if "HTTP 404" not in str(e):
                raise
            self._api("PUT", self._topic_path, {})

    # -- MessageQueue SPI -----------------------------------------------------

    def send_message(self, key, event) -> None:
        self._api("POST", f"{self._topic_path}:publish", {
            "messages": [{
                "data": base64.b64encode(
                    event.SerializeToString()).decode(),
                "attributes": {"key": key},
            }]})
