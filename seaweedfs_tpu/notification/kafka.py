"""Kafka notification queue speaking the binary wire protocol — no SDK.

Behavioral parity with the reference's sarama producer
(weed/notification/kafka/kafka_queue.go:15-64): events are produced to
one topic, keyed by the entry path, value = the serialized
EventNotification, partition chosen by hashing the key the way
sarama's default HashPartitioner does (FNV-1a 32-bit, toPositive, mod
numPartitions).

Protocol subset implemented here:
  - Metadata v1  (leader discovery per partition)
  - Produce  v3  (acks=1) carrying a RecordBatch v2 (magic 2): CRC32C
    over the batch body, zigzag-varint record framing
Both are supported by every broker since Kafka 0.11 and are the only
message format modern brokers (3.x+) still accept for writes.
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Dict, Optional, Tuple

from seaweedfs_tpu.notification import MessageQueue


class KafkaError(Exception):
    pass


# -- primitive codecs ---------------------------------------------------------


def _int8(v):
    return struct.pack(">b", v)


def _int16(v):
    return struct.pack(">h", v)


def _int32(v):
    return struct.pack(">i", v)


def _int64(v):
    return struct.pack(">q", v)


def _string(s: Optional[str]) -> bytes:
    if s is None:
        return _int16(-1)
    b = s.encode()
    return _int16(len(b)) + b


def _bytes(b: Optional[bytes]) -> bytes:
    if b is None:
        return _int32(-1)
    return _int32(len(b)) + b


def _varint(v: int) -> bytes:
    """Zigzag-encoded signed varint (record framing)."""
    z = (v << 1) ^ (v >> 63)
    out = bytearray()
    while True:
        b = z & 0x7F
        z >>= 7
        if z:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    shift = z = 0
    while True:
        b = buf[pos]
        pos += 1
        z |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    return (z >> 1) ^ -(z & 1), pos


# -- CRC32C (Castagnoli), the RecordBatch checksum ----------------------------

_CRC32C_TABLE = []


def _crc32c_init():
    poly = 0x82F63B78
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
        _CRC32C_TABLE.append(crc)


_crc32c_init()


def crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC32C_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


# -- sarama-compatible key partitioner ---------------------------------------


def fnv1a_32(data: bytes) -> int:
    h = 0x811C9DC5
    for b in data:
        h ^= b
        h = (h * 0x01000193) & 0xFFFFFFFF
    return h


def partition_for_key(key: bytes, num_partitions: int) -> int:
    """sarama NewHashPartitioner: FNV-1a 32 as int32, negated when
    negative, mod numPartitions."""
    h = fnv1a_32(key)
    if h & 0x80000000:            # int32 < 0 -> -h, like sarama
        h = (1 << 32) - h
    return h % num_partitions


# -- record batch (magic 2) ---------------------------------------------------


def encode_record_batch(key: bytes, value: bytes, timestamp_ms: int) -> bytes:
    record_body = (
        _int8(0)                      # record attributes
        + _varint(0)                  # timestamp delta
        + _varint(0)                  # offset delta
        + _varint(len(key)) + key
        + _varint(len(value)) + value
        + _varint(0)                  # headers count
    )
    record = _varint(len(record_body)) + record_body
    body = (
        _int16(0)                     # batch attributes (no compression)
        + _int32(0)                   # lastOffsetDelta
        + _int64(timestamp_ms)        # firstTimestamp
        + _int64(timestamp_ms)        # maxTimestamp
        + _int64(-1)                  # producerId
        + _int16(-1)                  # producerEpoch
        + _int32(-1)                  # baseSequence
        + _int32(1)                   # record count
        + record
    )
    header = (
        _int64(0)                     # baseOffset
        + _int32(4 + 1 + 4 + len(body))   # batchLength (after this field)
        + _int32(-1)                  # partitionLeaderEpoch
        + _int8(2)                    # magic
        + struct.pack(">I", crc32c(body))  # crc (unsigned, covers body)
    )
    return header + body


class KafkaQueue(MessageQueue):
    """Synchronous wire client; production configs get wrapped in
    notification.AsyncQueue by from_config so a down broker stalls the
    sender thread, not the filer write path."""

    def __init__(self, hosts=None, topic: str = "seaweedfs_filer",
                 client_id: str = "seaweedfs-tpu",
                 timeout: float = 10.0, **_ignored):
        if isinstance(hosts, str):
            hosts = [h.strip() for h in hosts.split(",") if h.strip()]
        if not hosts:
            raise ValueError("kafka needs hosts = [\"host:port\", ...]")
        self.hosts = hosts
        self.topic = topic
        self.client_id = client_id
        self.timeout = timeout
        self._corr = 0
        # one lock serializes all wire traffic: connections are shared
        # per broker and concurrent callers touch shared state
        self._lock = threading.Lock()
        self._conns: Dict[str, socket.socket] = {}
        # leader discovery up front, like sarama's producer
        self.partition_leaders: Dict[int, str] = {}
        self.num_partitions = 0   # TOTAL partitions (even leaderless)
        with self._lock:
            self._refresh_metadata()

    # -- framing --------------------------------------------------------------

    def _connect(self, host: str) -> socket.socket:
        sock = self._conns.get(host)
        if sock is not None:
            return sock
        h, _, p = host.partition(":")
        sock = socket.create_connection((h, int(p or 9092)),
                                        timeout=self.timeout)
        self._conns[host] = sock
        return sock

    def _drop(self, host: str) -> None:
        sock = self._conns.pop(host, None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _call(self, host: str, api_key: int, api_version: int,
              body: bytes) -> bytes:
        """One size-framed request/response round trip."""
        self._corr += 1
        corr = self._corr
        msg = (_int16(api_key) + _int16(api_version) + _int32(corr)
               + _string(self.client_id) + body)
        sock = self._connect(host)
        try:
            sock.sendall(_int32(len(msg)) + msg)
            raw = self._read_exact(sock, 4)
            (size,) = struct.unpack(">i", raw)
            resp = self._read_exact(sock, size)
        except OSError as e:
            self._drop(host)
            raise KafkaError(f"kafka {host}: {e}") from None
        (got_corr,) = struct.unpack(">i", resp[:4])
        if got_corr != corr:
            self._drop(host)
            raise KafkaError(f"kafka {host}: correlation mismatch")
        return resp[4:]

    @staticmethod
    def _read_exact(sock: socket.socket, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise OSError("connection closed")
            buf += chunk
        return buf

    # -- metadata -------------------------------------------------------------

    def _refresh_metadata(self, attempts: int = 3) -> None:
        """Bounded retry like sarama (Metadata.Retry.Max): a Metadata
        request against a fresh broker triggers topic auto-creation and
        the immediate response has no leaders yet — the next poll
        does."""
        import time
        err: Optional[Exception] = None
        for attempt in range(attempts):
            for host in self.hosts:
                try:
                    body = _int32(1) + _string(self.topic)  # [topic]
                    resp = self._call(host, 3, 1, body)     # Metadata v1
                    self._parse_metadata(resp)
                    if self.partition_leaders:
                        return
                except (KafkaError, OSError) as e:
                    err = e
            if attempt < attempts - 1:
                time.sleep(0.25 * (attempt + 1))
        raise KafkaError(
            f"no kafka broker reachable or topic {self.topic!r} has no "
            f"leaders (hosts={self.hosts}): {err}")

    def _parse_metadata(self, b: bytes) -> None:
        pos = 0
        (n_brokers,) = struct.unpack_from(">i", b, pos)
        pos += 4
        brokers: Dict[int, str] = {}
        for _ in range(n_brokers):
            (node_id,) = struct.unpack_from(">i", b, pos)
            pos += 4
            (hlen,) = struct.unpack_from(">h", b, pos)
            pos += 2
            host = b[pos:pos + hlen].decode()
            pos += hlen
            (port,) = struct.unpack_from(">i", b, pos)
            pos += 4
            (rlen,) = struct.unpack_from(">h", b, pos)  # rack (nullable)
            pos += 2 + max(rlen, 0)
            brokers[node_id] = f"{host}:{port}"
        pos += 4                                        # controller_id
        (n_topics,) = struct.unpack_from(">i", b, pos)
        pos += 4
        leaders: Dict[int, str] = {}
        total = 0
        for _ in range(n_topics):
            (topic_err,) = struct.unpack_from(">h", b, pos)
            pos += 2
            (tlen,) = struct.unpack_from(">h", b, pos)
            pos += 2
            name = b[pos:pos + tlen].decode()
            pos += tlen
            pos += 1                                    # is_internal bool
            (n_parts,) = struct.unpack_from(">i", b, pos)
            pos += 4
            if name == self.topic:
                total = n_parts
            for _ in range(n_parts):
                (perr, pid, leader) = struct.unpack_from(">hii", b, pos)
                pos += 10
                (n_replicas,) = struct.unpack_from(">i", b, pos)
                pos += 4 + 4 * n_replicas
                (n_isr,) = struct.unpack_from(">i", b, pos)
                pos += 4 + 4 * n_isr
                if name == self.topic and perr == 0 and leader in brokers:
                    leaders[pid] = brokers[leader]
        self.partition_leaders = leaders
        # the key->partition map must use the TOTAL partition count:
        # hashing over only the currently-leadered ones would remap
        # every key whenever one partition loses its leader
        self.num_partitions = total

    # -- produce --------------------------------------------------------------

    # produce error codes that a metadata refresh can fix
    _RETRIABLE = (5, 6)   # LEADER_NOT_AVAILABLE, NOT_LEADER_FOR_PARTITION

    def send_message(self, key, event) -> None:
        import time
        kb, value = key.encode(), event.SerializeToString()
        with self._lock:
            if not self.num_partitions:
                self._refresh_metadata()

            def build():
                # partition + request body derive from the CURRENT
                # metadata; after a refresh both must be recomputed
                # (sarama re-partitions on retry too) or a re-created/
                # expanded topic would see the key land off-map
                partition = partition_for_key(kb, self.num_partitions)
                batch = encode_record_batch(kb, value,
                                            int(time.time() * 1000))
                return partition, (
                    _string(None)     # transactional_id (Produce v3)
                    + _int16(1)       # acks = leader (WaitForLocal)
                    + _int32(int(self.timeout * 1000))
                    + _int32(1) + _string(self.topic)
                    + _int32(1) + _int32(partition)
                    + _bytes(batch)
                )

            partition, body = build()
            try:
                self._produce(partition, body)
            except KafkaError as e:
                # stale leader (transport error OR a retriable produce
                # error code): refresh once and retry on the new one
                if getattr(e, "code", None) is not None and \
                        e.code not in self._RETRIABLE:
                    raise
                self._refresh_metadata()
                partition, body = build()
                self._produce(partition, body)

    def _produce(self, partition: int, body: bytes) -> None:
        leader = self.partition_leaders.get(partition)
        if leader is None:
            raise KafkaError(
                f"partition {partition} of {self.topic!r} has no leader")
        resp = self._call(leader, 0, 3, body)           # Produce v3
        self._check_produce_response(resp)

    @staticmethod
    def _check_produce_response(b: bytes) -> None:
        pos = 4                                         # topic array len
        (tlen,) = struct.unpack_from(">h", b, pos)
        pos += 2 + tlen
        pos += 4                                        # partition array len
        (_pid, err) = struct.unpack_from(">ih", b, pos)
        if err != 0:
            e = KafkaError(f"produce failed: kafka error code {err}")
            e.code = err
            raise e

    def close(self) -> None:
        with self._lock:
            for host in list(self._conns):
                self._drop(host)
