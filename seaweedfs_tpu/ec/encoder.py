"""EC encode / rebuild / decode over volume files.

Equivalent behavior to reference weed/storage/erasure_coding/
ec_encoder.go + ec_decoder.go, re-structured for TPU batch compute:

The reference encodes serially in 256KB batches through a per-volume Go
loop. Here each 10-block row is encoded as a [10, chunk] uint8 matrix and
parity comes from one GF(2^8) linear map (seaweedfs_tpu/ops) — on TPU
a single MXU matmul per chunk, with `chunk` sized in the tens of MB so
dispatch latency amortizes. Data shards never pass through the RS path
at all: they are straight padded copies of .dat slices (the code is
systematic), halving the IO the reference's buffer loop does.
"""

from __future__ import annotations

import os
from collections import deque
from typing import Deque, List, Optional, Tuple

import numpy as np

from seaweedfs_tpu.ops.rs_code import ReedSolomon, DATA_SHARDS, TOTAL_SHARDS
from seaweedfs_tpu.storage import idx as idx_codec
from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.needle import actual_size

LARGE_BLOCK_SIZE = 1 << 30  # 1GB
SMALL_BLOCK_SIZE = 1 << 20  # 1MB
DEFAULT_CHUNK = 16 << 20      # RS dispatch granularity, host backends
DEFAULT_CHUNK_JAX = 128 << 20  # jax: larger batches amortize dispatch
                               # (measured 2026-07: 2.0x over 16MB/depth-1
                               # on the tunneled chip at depth 3)


def shard_file_name(base_name: str, shard_id: int) -> str:
    return f"{base_name}.ec{shard_id:02d}"


def _rs(backend: str) -> ReedSolomon:
    return ReedSolomon(backend=backend)


# --- encode -----------------------------------------------------------------

def default_chunk_for(backend: str) -> int:
    """Per-backend RS dispatch granularity: the jax path needs large
    batches to amortize dispatch/tunnel latency; host backends prefer
    cache-sized chunks."""
    return DEFAULT_CHUNK_JAX if backend == "jax" else DEFAULT_CHUNK


def write_ec_files(base_name: str, backend: str = "auto",
                   large_block: int = LARGE_BLOCK_SIZE,
                   small_block: int = SMALL_BLOCK_SIZE,
                   chunk: Optional[int] = None) -> None:
    """Generate .ec00-.ec13 from <base>.dat.

    Rows are consumed exactly like the reference encoder
    (ec_encoder.go:194-231): large rows while MORE than 10*large_block
    remains, then zero-padded small rows.
    """
    if chunk is None:
        chunk = default_chunk_for(backend)
    rs = _rs(backend)
    dat_path = base_name + ".dat"
    dat_size = os.path.getsize(dat_path)
    outputs = [open(shard_file_name(base_name, i), "wb")
               for i in range(TOTAL_SHARDS)]
    pipe = _EncodePipeline()
    try:
        with open(dat_path, "rb") as dat:
            remaining = dat_size
            processed = 0
            while remaining > large_block * DATA_SHARDS:
                _encode_large_row(rs, dat, processed, large_block, outputs,
                                  chunk, pipe)
                remaining -= large_block * DATA_SHARDS
                processed += large_block * DATA_SHARDS
            if remaining > 0:
                n_rows = -(-remaining // (small_block * DATA_SHARDS))
                _encode_small_rows(rs, dat, processed, small_block, n_rows,
                                   outputs, chunk, pipe)
        pipe.drain()
    finally:
        for f in outputs:
            f.close()


def _read_padded(f, offset: int, length: int) -> np.ndarray:
    """One buffer filled in place: readinto() avoids the
    frombuffer+concatenate double allocation on tail chunks, only the
    EOF tail is memset, and the result is writable (a read-only
    frombuffer view forces copies downstream)."""
    buf = np.empty(length, dtype=np.uint8)
    f.seek(offset)
    got = f.readinto(memoryview(buf))
    if got < length:
        buf[got:] = 0  # zero padding past EOF
    return buf


# How many encode dispatches may be in flight at once. Depth 2 is classic
# double buffering: while the device computes parity for chunk i, the host
# writes chunk i-1's shards and reads chunk i+1 from disk (SURVEY §7
# "overlap gRPC ingest, host staging, device_put and compute").
PIPELINE_DEPTH = 2


class _EncodePipeline:
    """Bounded in-flight queue of (data, pending-parity, writeback)."""

    def __init__(self, depth: int = PIPELINE_DEPTH):
        self._inflight: Deque[Tuple] = deque()
        self._depth = max(1, depth)

    def submit(self, handle, writeback) -> None:
        self._inflight.append((handle, writeback))
        while len(self._inflight) >= self._depth:
            self._retire_one()

    def _retire_one(self) -> None:
        handle, writeback = self._inflight.popleft()
        writeback(handle.result())

    def drain(self) -> None:
        while self._inflight:
            self._retire_one()


def _encode_large_row(rs: ReedSolomon, dat, row_offset: int, block_size: int,
                      outputs: List, chunk: int,
                      pipe: Optional[_EncodePipeline] = None) -> None:
    """One large row: shard i gets dat[row_offset + i*block : +block]
    (padded); parity comes chunk-at-a-time so a 1GB row never needs 10GB
    resident. Data shards are written immediately (the code is
    systematic); parity writes retire through the pipeline so device
    compute overlaps the next chunk's disk read."""
    own = pipe is None
    pipe = pipe or _EncodePipeline()
    for c in range(0, block_size, chunk):
        clen = min(chunk, block_size - c)
        data = np.empty((DATA_SHARDS, clen), dtype=np.uint8)
        for i in range(DATA_SHARDS):
            data[i] = _read_padded(dat, row_offset + i * block_size + c, clen)
        handle = rs.encode_async(data)
        for i in range(DATA_SHARDS):
            outputs[i].write(data[i].tobytes())

        def write_parity(parity, outputs=outputs):
            for p in range(parity.shape[0]):
                outputs[DATA_SHARDS + p].write(parity[p].tobytes())

        pipe.submit(handle, write_parity)
    if own:
        pipe.drain()


def _encode_small_rows(rs: ReedSolomon, dat, start_offset: int,
                       small_block: int, n_rows: int, outputs: List,
                       chunk: int,
                       pipe: Optional[_EncodePipeline] = None) -> None:
    """Tail small rows, batched: consecutive rows are contiguous in the
    .dat, so a span of B rows is just a reshape to [B, 10, small] and
    parity for all of them is ONE RS dispatch — this is what amortizes
    TPU dispatch latency (vs the reference's serial 256KB loop)."""
    own = pipe is None
    pipe = pipe or _EncodePipeline()
    rows_per_batch = max(1, chunk // (small_block * DATA_SHARDS))
    row_bytes = small_block * DATA_SHARDS
    for r0 in range(0, n_rows, rows_per_batch):
        rows = min(rows_per_batch, n_rows - r0)
        span = _read_padded(dat, start_offset + r0 * row_bytes,
                            rows * row_bytes)
        data = span.reshape(rows, DATA_SHARDS, small_block)
        handle = rs.encode_async(data)
        for i in range(DATA_SHARDS):
            outputs[i].write(np.ascontiguousarray(data[:, i, :]).tobytes())

        def write_parity(parity, outputs=outputs):
            for p in range(parity.shape[1]):
                outputs[DATA_SHARDS + p].write(
                    np.ascontiguousarray(parity[:, p, :]).tobytes())

        pipe.submit(handle, write_parity)
    if own:
        pipe.drain()


def write_sorted_file_from_idx(base_name: str, ext: str = ".ecx") -> None:
    """Replay <base>.idx, write the *live* needle set key-sorted as .ecx.

    Matches reference WriteSortedFileFromIdx (ec_encoder.go:27-54): the
    final state per key (tombstones applied) sorted ascending.
    """
    with open(base_name + ".idx", "rb") as f:
        arr = idx_codec.parse_index_bytes(f.read())
    final: dict[int, tuple[int, int]] = {}
    for i in range(len(arr)):
        key = int(arr["key"][i])
        size = int(arr["size"][i])
        if t.size_is_deleted(size):
            final.pop(key, None)
        else:
            final[key] = (int(arr["offset"][i]), size)
    with open(base_name + ext, "wb") as out:
        for key in sorted(final):
            offset, size = final[key]
            out.write(idx_codec.entry_to_bytes(key, offset, size))


# --- rebuild ----------------------------------------------------------------

def rebuild_ec_files(base_name: str, backend: str = "auto",
                     chunk: Optional[int] = None,
                     wanted: Optional[List[int]] = None) -> List[int]:
    """Regenerate missing .ecNN from >=10 present ones.

    `wanted` restricts which missing shards get rebuilt (decode-to-volume
    only needs the data shards). Returns the generated shard ids
    (reference generateMissingEcFiles, ec_encoder.go:88-118).
    """
    if chunk is None:
        chunk = default_chunk_for(backend)
    rs = _rs(backend)
    present = [i for i in range(TOTAL_SHARDS)
               if os.path.exists(shard_file_name(base_name, i))]
    missing = [i for i in (range(TOTAL_SHARDS) if wanted is None else wanted)
               if i not in present]
    if not missing:
        return []
    if len(present) < DATA_SHARDS:
        raise ValueError(
            f"cannot rebuild: only {len(present)} shards present")
    shard_size = os.path.getsize(shard_file_name(base_name, present[0]))
    ins = {i: open(shard_file_name(base_name, i), "rb") for i in present}
    outs = {i: open(shard_file_name(base_name, i), "wb") for i in missing}
    pipe = _EncodePipeline()
    try:
        for c in range(0, shard_size, chunk):
            clen = min(chunk, shard_size - c)
            src = np.empty((len(present[:DATA_SHARDS]), clen), dtype=np.uint8)
            for row, i in enumerate(present[:DATA_SHARDS]):
                src[row] = _read_padded(ins[i], c, clen)
            handle = rs.reconstruct_some_async(present, missing, src)

            def write_rebuilt(out, outs=outs):
                for row, i in enumerate(missing):
                    outs[i].write(out[row].tobytes())

            # retire in FIFO order: while the device reconstructs chunk
            # i, the host reads chunk i+1 (same overlap as encode)
            pipe.submit(handle, write_rebuilt)
        pipe.drain()
    finally:
        for f in ins.values():
            f.close()
        for f in outs.values():
            f.close()
    return missing


# --- decode back to a volume ------------------------------------------------

def _read_ec_volume_version(base_name: str) -> int:
    """The original superblock lives in the first bytes of .ec00."""
    with open(shard_file_name(base_name, 0), "rb") as f:
        header = f.read(8)
    if len(header) < 8:
        raise ValueError("ec00 shard too short for a superblock")
    return header[0]


def find_dat_file_size(base_name: str, index_base_name: Optional[str] = None) -> int:
    """Recover the original .dat size from the max .ecx entry end.

    (reference ec_decoder.go:45-70; trailing deletes past the max entry
    are deletions anyway.)
    """
    version = _read_ec_volume_version(base_name)
    index_base_name = index_base_name or base_name
    with open(index_base_name + ".ecx", "rb") as f:
        arr = idx_codec.parse_index_bytes(f.read())
    dat_size = 8  # at least the superblock
    for i in range(len(arr)):
        size = int(arr["size"][i])
        if t.size_is_deleted(size):
            continue
        end = int(arr["offset"][i]) + actual_size(size, version)
        dat_size = max(dat_size, end)
    return dat_size


def write_dat_file(base_name: str, dat_size: int,
                   large_block: int = LARGE_BLOCK_SIZE,
                   small_block: int = SMALL_BLOCK_SIZE,
                   chunk: Optional[int] = None,
                   backend: str = "auto") -> None:
    """Re-interleave .ec00-.ec09 rows back into <base>.dat
    (reference WriteDatFile, ec_decoder.go:153-195). The chunk default
    follows the backend like encode/rebuild do."""
    if chunk is None:
        chunk = default_chunk_for(backend)
    inputs = [open(shard_file_name(base_name, i), "rb")
              for i in range(DATA_SHARDS)]
    try:
        with open(base_name + ".dat", "wb") as dat:
            shard_off = 0
            remaining = dat_size
            while remaining > large_block * DATA_SHARDS:
                _decode_row(inputs, dat, shard_off, large_block, chunk)
                shard_off += large_block
                remaining -= large_block * DATA_SHARDS
            while remaining > 0:
                _decode_row(inputs, dat, shard_off, small_block, chunk)
                shard_off += small_block
                remaining -= small_block * DATA_SHARDS
            dat.truncate(dat_size)
    finally:
        for f in inputs:
            f.close()


def _decode_row(inputs: List, dat, shard_off: int, block_size: int,
                chunk: int) -> None:
    for i in range(DATA_SHARDS):
        for c in range(0, block_size, chunk):
            clen = min(chunk, block_size - c)
            buf = _read_padded(inputs[i], shard_off + c, clen)
            dat.write(buf.tobytes())


def rebuild_ecx_file(base_name: str) -> None:
    """Replay the .ecj journal into the sorted .ecx (tombstone in place),
    then drop the journal (reference RebuildEcxFile,
    ec_volume_delete.go:51-98)."""
    ecj_path = base_name + ".ecj"
    if not os.path.exists(ecj_path):
        return
    with open(base_name + ".ecx", "r+b") as ecx:
        arr = None
        with open(ecj_path, "rb") as j:
            journal = j.read()
        if journal:
            ecx.seek(0)
            arr = idx_codec.parse_index_bytes(ecx.read())
        for jo in range(0, len(journal) - len(journal) % 8, 8):
            key = int.from_bytes(journal[jo:jo + 8], "big")
            i = int(np.searchsorted(arr["key"], np.uint64(key)))
            if i < len(arr) and int(arr["key"][i]) == key:
                ecx.seek(i * t.NEEDLE_MAP_ENTRY_SIZE + t.NEEDLE_ID_SIZE + t.OFFSET_SIZE)
                ecx.write((t.TOMBSTONE_SIZE & 0xFFFFFFFF).to_bytes(4, "big"))
    os.remove(ecj_path)


def write_idx_file_from_ec_index(base_name: str) -> None:
    """.idx = .ecx copied + tombstone entries for every .ecj id
    (reference WriteIdxFileFromEcIndex, ec_decoder.go:18-43)."""
    with open(base_name + ".ecx", "rb") as f:
        ecx = f.read()
    with open(base_name + ".idx", "wb") as out:
        out.write(ecx)
        ecj_path = base_name + ".ecj"
        if os.path.exists(ecj_path):
            with open(ecj_path, "rb") as j:
                while True:
                    b = j.read(t.NEEDLE_ID_SIZE)
                    if len(b) < t.NEEDLE_ID_SIZE:
                        break
                    key = int.from_bytes(b, "big")
                    out.write(idx_codec.entry_to_bytes(key, 0, t.TOMBSTONE_SIZE))
