"""Needle-location math over the EC striping layout.

Maps a byte range of the original .dat onto intervals of the 14 shard
files. Behavioral parity with reference
weed/storage/erasure_coding/ec_locate.go:15-87.

Layout recap: the .dat is consumed row-major — while more than
10*largeBlock bytes remain, one "large row" assigns dat[row*10L + i*L ..]
to shard i; the tail is striped the same way in small blocks. Shard file
i therefore holds its large blocks first, then its small blocks.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

from seaweedfs_tpu.ops.rs_code import DATA_SHARDS


@dataclass(frozen=True)
class Interval:
    block_index: int          # index among blocks of this block-size class
    inner_offset: int         # offset within the block
    size: int                 # bytes in this interval
    is_large_block: bool
    large_block_rows: int     # how many large rows the volume has

    def to_shard_and_offset(self, large_block: int, small_block: int) -> Tuple[int, int]:
        """Map to (shard_id, offset within that shard file)."""
        off = self.inner_offset
        row = self.block_index // DATA_SHARDS
        if self.is_large_block:
            off += row * large_block
        else:
            off += self.large_block_rows * large_block + row * small_block
        return self.block_index % DATA_SHARDS, off


def _locate_offset(large_block: int, small_block: int, dat_size: int,
                   offset: int) -> Tuple[int, bool, int]:
    large_row = large_block * DATA_SHARDS
    n_large_rows = dat_size // large_row
    if offset < n_large_rows * large_row:
        return offset // large_block, True, offset % large_block
    offset -= n_large_rows * large_row
    return offset // small_block, False, offset % small_block


def locate_data(large_block: int, small_block: int, dat_size: int,
                offset: int, size: int) -> List[Interval]:
    """Split dat[offset:offset+size] into shard-file intervals."""
    block_index, is_large, inner = _locate_offset(
        large_block, small_block, dat_size, offset)
    # number of large rows, derivable from a shard file size
    # (+10*small ensures the small-row remainder rounds the same way the
    # encoder's strict-> loop does; see reference ec_locate.go:19)
    n_large_rows = (dat_size + DATA_SHARDS * small_block) // (large_block * DATA_SHARDS)

    intervals: List[Interval] = []
    while size > 0:
        block_len = large_block if is_large else small_block
        take = min(size, block_len - inner)
        intervals.append(Interval(
            block_index=block_index, inner_offset=inner, size=take,
            is_large_block=is_large, large_block_rows=n_large_rows))
        size -= take
        block_index += 1
        if is_large and block_index == n_large_rows * DATA_SHARDS:
            is_large = False
            block_index = 0
        inner = 0
    return intervals
