"""Erasure-coding pipeline: RS(10,4) striping of volumes across 14 shards.

Disk layout (parity with reference weed/storage/erasure_coding):
  .ec00...ec13  shard files: row-major striping of the .dat — 1GB blocks
                per shard per "large row" while >10GB remains, then 1MB
                "small rows" (zero-padded tail)
  .ecx          key-sorted 16-byte needle index (same entry codec as .idx)
  .ecj          journal of deleted needle ids (8B big-endian each)

The encode/rebuild/decode compute runs as a batched GF(2^8) bit-matmul on
TPU (seaweedfs_tpu/ops) — many 256KB stripes per dispatch — with CPU
fallbacks for small volumes.
"""

from seaweedfs_tpu.ec.locate import Interval, locate_data
from seaweedfs_tpu.ec.shard_bits import ShardBits
from seaweedfs_tpu.ec.encoder import (
    write_ec_files, write_sorted_file_from_idx, rebuild_ec_files,
    write_dat_file, write_idx_file_from_ec_index, find_dat_file_size,
    rebuild_ecx_file, shard_file_name, LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE,
)
from seaweedfs_tpu.ec.fleet import (
    fleet_write_ec_files, fleet_rebuild_ec_files,
)
from seaweedfs_tpu.ec.ec_volume import EcVolume, EcVolumeShard
