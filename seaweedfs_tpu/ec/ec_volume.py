"""EcVolume: runtime state of one erasure-coded volume on a server.

Holds mounted shard files, the key-sorted .ecx index, and the .ecj
delete journal. Needle reads resolve via binary search + interval math;
missing-shard intervals are recovered by callers through the RS decoder
(see read_needle / seaweedfs_tpu/volume_server integration).

Reference: weed/storage/erasure_coding/ec_volume.go, ec_shard.go,
ec_volume_delete.go.
"""

from __future__ import annotations

import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from seaweedfs_tpu.ec import locate as ec_locate
from seaweedfs_tpu.ec.encoder import (
    shard_file_name, LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE,
)
from seaweedfs_tpu.ec.shard_bits import ShardBits, DATA_SHARDS, TOTAL_SHARDS
from seaweedfs_tpu.ops.rs_code import ReedSolomon
from seaweedfs_tpu.stats.metrics import (
    ReadsDecodedBytesCounter, ReadsDegradedCounter, ReadsShortShardCounter)
from seaweedfs_tpu.storage import idx as idx_codec
from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.needle import Needle, NeedleError, actual_size
from seaweedfs_tpu.util import wlog

log = wlog.logger("ec")


class EcShardNotFound(NeedleError):
    pass


# Shared fetch pool for the in-place (non-fleet) recovery fallback:
# created lazily on the FIRST degraded read, so a healthy server never
# spawns these threads (the degraded-decode-disabled perf gate).
_recover_pool: Optional[ThreadPoolExecutor] = None
_recover_pool_lock = threading.Lock()


def _get_recover_pool() -> ThreadPoolExecutor:
    global _recover_pool
    if _recover_pool is None:
        with _recover_pool_lock:
            if _recover_pool is None:
                # lint: thread-ok(shared recover pool takes explicit work items; the read seam enforces deadlines)
                _recover_pool = ThreadPoolExecutor(
                    max_workers=8, thread_name_prefix="ec-recover")
    return _recover_pool


class EcVolumeShard:
    """One mounted .ecNN shard (reference ec_shard.go:16-95).

    A shard is either LOCAL (an open file) or REMOTE (the bytes live
    in a cloud backend, recorded by the <base>.ectier sidecar —
    storage/volume_tier.move_ec_shards_to_remote): reads route through
    ranged backend GETs, the shard stays mounted, and the heartbeat
    keeps advertising it, so the COLD tier is transparent to every
    consumer of read_at (needle reads, scrub verify, remote shard
    serving, RS reconstruction rows)."""

    def __init__(self, directory: str, collection: str, vid: int,
                 shard_id: int, remote=None):
        self.collection = collection
        self.volume_id = vid
        self.shard_id = shard_id
        name = f"{collection}_{vid}" if collection else str(vid)
        self.path = shard_file_name(os.path.join(directory, name), shard_id)
        self._lock = threading.Lock()
        # read_at's lock-free fast path reads this once and falls back
        # to the local file under the lock when a concurrent download
        # leg swapped the shard mid-read (PR 9 review contract)
        self._remote = None  # guarded_by(self._lock, writes)   (BackendStorage, key) when tiered
        if remote is not None:
            storage, key, size = remote
            self._remote = (storage, key)
            self._f = None
            self.size = size
        else:
            self._f = open(self.path, "rb")
            self.size = os.path.getsize(self.path)

    @property
    def is_remote(self) -> bool:
        return self._remote is not None

    def read_at(self, offset: int, length: int) -> bytes:
        remote = self._remote
        if remote is not None:
            storage, key = remote
            try:
                return storage.read_range(key, offset, length)
            except Exception:
                # the download leg may have swapped this shard local
                # (and deleted the remote object) between our snapshot
                # and the ranged GET: serve from the file if so, else
                # surface the backend error
                with self._lock:
                    if self._f is None:
                        raise
                    self._f.seek(offset)
                    return self._f.read(length)
        with self._lock:
            if self._f is None:      # swapped remote mid-read
                storage, key = self._remote
                return storage.read_range(key, offset, length)
            self._f.seek(offset)
            return self._f.read(length)

    def swap_to_remote(self, storage, key: str, size: int) -> None:
        """Serve from the backend from now on (the tier-upload handle
        swap; the caller deletes the local file afterwards)."""
        with self._lock:
            old, self._f = self._f, None
            self._remote = (storage, key)
            self.size = size
        if old is not None:
            old.close()

    def swap_to_local(self) -> None:
        """Back to the local file (tier download re-materialized it)."""
        f = open(self.path, "rb")
        size = os.path.getsize(self.path)
        with self._lock:
            self._f = f
            self._remote = None
            self.size = size

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None

    def destroy(self) -> None:
        self.close()
        if os.path.exists(self.path):
            os.remove(self.path)


class EcVolume:
    def __init__(self, directory: str, collection: str, vid: int,
                 large_block: int = LARGE_BLOCK_SIZE,
                 small_block: int = SMALL_BLOCK_SIZE):
        self.directory = directory
        self.collection = collection
        self.volume_id = vid
        self.large_block = large_block
        self.small_block = small_block
        name = f"{collection}_{vid}" if collection else str(vid)
        self.base_name = os.path.join(directory, name)
        if not os.path.exists(self.base_name + ".ecx"):
            raise FileNotFoundError(self.base_name + ".ecx")
        self._ecx = open(self.base_name + ".ecx", "r+b")
        self._ecj = open(self.base_name + ".ecj", "a+b")
        self._lock = threading.RLock()
        self.shards: Dict[int, EcVolumeShard] = {}
        # shards whose short local read was already logged (once per
        # shard, so recovery traffic is distinguishable from decay
        # without flooding the log on a hot truncated shard)
        self._short_logged: set = set()
        # remote shard location cache: shard id -> list of server urls
        self.shard_locations: Dict[int, List[str]] = {}
        self.shard_locations_refreshed_at = 0.0
        self._load_ecx()
        self.created_at = time.time()

    # -- index ---------------------------------------------------------------

    def _load_ecx(self) -> None:
        self._ecx.seek(0)
        arr = idx_codec.parse_index_bytes(self._ecx.read())
        self._keys = arr["key"].copy()
        self._offsets = arr["offset"].copy()
        # find_needle/file_count read lock-free (single-element numpy
        # stores are atomic under the GIL; a read racing a tombstone
        # sees either value, both valid); mutation takes the lock
        # lint: guard-ok(_load_ecx runs from __init__ only, before the volume is published)
        self._sizes = arr["size"].copy()  # guarded_by(self._lock, writes)

    def find_needle(self, needle_id: int) -> Tuple[int, int]:
        """Return (dat_offset, size); raises NeedleError if absent/deleted."""
        i = int(np.searchsorted(self._keys, np.uint64(needle_id)))
        if i >= len(self._keys) or self._keys[i] != needle_id:
            raise NeedleError(f"needle {needle_id:x} not in ecx")
        size = int(self._sizes[i])
        if t.size_is_deleted(size):
            raise NeedleError(f"needle {needle_id:x} deleted")
        return int(self._offsets[i]), size

    def delete_needle(self, needle_id: int) -> None:
        """Tombstone in the sorted .ecx in place + journal to .ecj
        (reference ec_volume_delete.go:13-49)."""
        with self._lock:
            i = int(np.searchsorted(self._keys, np.uint64(needle_id)))
            if i >= len(self._keys) or self._keys[i] != needle_id:
                return
            self._sizes[i] = t.TOMBSTONE_SIZE
            entry_off = i * t.NEEDLE_MAP_ENTRY_SIZE
            self._ecx.seek(entry_off + t.NEEDLE_ID_SIZE + t.OFFSET_SIZE)
            self._ecx.write((t.TOMBSTONE_SIZE & 0xFFFFFFFF).to_bytes(4, "big"))
            self._ecx.flush()
            self._ecj.seek(0, os.SEEK_END)
            self._ecj.write(needle_id.to_bytes(8, "big"))
            self._ecj.flush()

    # -- shards --------------------------------------------------------------

    def mount_shard(self, shard_id: int) -> EcVolumeShard:
        with self._lock:
            if shard_id in self.shards:
                return self.shards[shard_id]
            s = EcVolumeShard(self.directory, self.collection, self.volume_id,
                              shard_id, remote=self._remote_info(shard_id))
            self.shards[shard_id] = s
            return s

    def _remote_info(self, shard_id: int):
        """(storage, key, size) for a shard this server tiered to a
        cloud backend (<base>.ectier sidecar), else None — so a
        restart remounts COLD shards without their local files."""
        if os.path.exists(shard_file_name(self.base_name, shard_id)):
            return None             # local file wins
        from seaweedfs_tpu.storage import backend as bk
        info = bk.read_ec_tier_info(self.base_name)
        if info is None:
            return None
        rec = info["shards"].get(shard_id)
        if rec is None:
            return None
        return bk.get_backend(info["backend"]), rec["key"], rec["size"]

    def unmount_shard(self, shard_id: int) -> bool:
        with self._lock:
            s = self.shards.pop(shard_id, None)
            if s is None:
                return False
            s.close()
            return True

    @property
    def shard_bits(self) -> ShardBits:
        return ShardBits.of(*self.shards.keys())

    @property
    def shard_size(self) -> int:
        for s in self.shards.values():
            return s.size
        # no local shards: derive from any shard file present
        for i in range(TOTAL_SHARDS):
            p = shard_file_name(self.base_name, i)
            if os.path.exists(p):
                return os.path.getsize(p)
        return 0

    # -- needle read ---------------------------------------------------------

    def locate_needle(self, needle_id: int, version: int = 3):
        """(offset, size, intervals) for the WHOLE needle record."""
        offset, size = self.find_needle(needle_id)
        dat_size = DATA_SHARDS * self.shard_size
        intervals = ec_locate.locate_data(
            self.large_block, self.small_block, dat_size,
            offset, actual_size(size, version))
        return offset, size, intervals

    def read_needle(self, n: Needle, version: int = 3,
                    remote_reader: Optional[Callable] = None,
                    rs: Optional[ReedSolomon] = None,
                    decoder=None, span_cache=None) -> Needle:
        """Read+verify a needle from local shards, remote shards, or by
        live RS reconstruction of missing intervals.

        remote_reader(shard_id, shard_offset, length) -> bytes|None is
        supplied by the volume server for non-local shards. `decoder`
        (reads.DegradedReadFleet) routes reconstructions to the fused
        batch path; `span_cache` (cache.TieredReadCache) serves repeat
        degraded reads without re-solving.
        """
        blob = self.read_needle_blob(n.id, version, remote_reader, rs,
                                     decoder, span_cache)
        got = Needle.from_bytes(blob, version)
        if n.cookie and got.cookie != n.cookie:
            from seaweedfs_tpu.storage.needle import CookieMismatch
            raise CookieMismatch(
                f"needle {n.id:x}: cookie {n.cookie:08x} != {got.cookie:08x}")
        return got

    def read_needle_blob(self, needle_id: int, version: int = 3,
                         remote_reader: Optional[Callable] = None,
                         rs: Optional[ReedSolomon] = None,
                         decoder=None, span_cache=None) -> bytes:
        """The raw stored record bytes of one needle — the unit the
        tiered read cache stores (Needle.from_bytes CRC-checks it on
        every parse, so a torn cache entry can never serve)."""
        _, size, intervals = self.locate_needle(needle_id, version)
        pieces = []
        for iv in intervals:
            pieces.append(self._read_interval(iv, remote_reader, rs,
                                              decoder, span_cache))
        return b"".join(pieces)

    def _read_interval(self, iv: ec_locate.Interval,
                       remote_reader: Optional[Callable],
                       rs: Optional[ReedSolomon],
                       decoder=None, span_cache=None) -> bytes:
        shard_id, off = iv.to_shard_and_offset(self.large_block, self.small_block)
        s = self.shards.get(shard_id)
        if s is not None:
            err = None
            try:
                data = s.read_at(off, iv.size)
            except (OSError, ValueError) as e:
                # failing disk, or the shard closed by a concurrent
                # unmount: same demotion as a short read — reconstruct
                err, data = e, b""
            if len(data) == iv.size:
                return data
            # short read (e.g. shard truncated by a crashed rebuild)
            # or read error: treat the shard as missing and reconstruct
            # from the others — but COUNT it, and log once per shard,
            # so operators can tell silent-recovery traffic from decay.
            # The log distinguishes truncation from IO errors: they
            # point at different repairs (bad rebuild vs dying disk).
            ReadsShortShardCounter.labels(
                str(self.volume_id), str(shard_id)).inc()
            if shard_id not in self._short_logged:
                self._short_logged.add(shard_id)
                if err is not None:
                    log.warning(
                        "ec volume %d shard %d: local read error at %d "
                        "(%s); serving via reconstruction until repaired",
                        self.volume_id, shard_id, off, err)
                else:
                    log.warning(
                        "ec volume %d shard %d: short local read (%d < "
                        "%d at %d); serving via reconstruction until "
                        "repaired",
                        self.volume_id, shard_id, len(data), iv.size, off)
            return self._recover_interval(shard_id, off, iv.size,
                                          remote_reader, rs, decoder,
                                          span_cache)
        if remote_reader is not None:
            try:
                data = remote_reader(shard_id, off, iv.size)
            # lint: swallow-ok(failure demotes to RS reconstruction, counted by SeaweedFS_reads_degraded_total)
            except Exception:
                data = None
            if data is not None and len(data) == iv.size:
                return data
        return self._recover_interval(shard_id, off, iv.size, remote_reader,
                                      rs, decoder, span_cache)

    def _recover_interval(self, missing_shard: int, off: int, length: int,
                          remote_reader: Optional[Callable],
                          rs: Optional[ReedSolomon],
                          decoder=None, span_cache=None) -> bytes:
        """On-the-fly RS reconstruction of one interval
        (reference store_ec.go:322-376).

        A reconstructed span is served from / published to `span_cache`
        when one is wired, and the solve itself goes to the fused
        `decoder` fleet when enabled, else to the in-place parallel
        fetch + single-row solve fallback."""
        gen = None
        if span_cache is not None:
            key = span_cache.span_key(self.volume_id, missing_shard, off,
                                      length)
            hit = span_cache.get(key)
            if hit is not None:
                if len(hit) == length:
                    return hit
                # torn span file (disk-tier entry truncated by power
                # loss): drop it and reconstruct
                span_cache.drop(key)
            # snapshot before solving: a rebuild/scrub invalidation
            # racing this reconstruction must win (set refuses stale)
            gen = span_cache.generation(key)
        if decoder is not None:
            data = decoder.decode(self, missing_shard, off, length,
                                  remote_reader)
        else:
            data = self._recover_in_place(missing_shard, off, length,
                                          remote_reader, rs)
        if span_cache is not None:
            span_cache.set(key, data, gen=gen)
        return data

    def _recover_in_place(self, missing_shard: int, off: int, length: int,
                          remote_reader: Optional[Callable],
                          rs: Optional[ReedSolomon]) -> bytes:
        """The fleet-less fallback: fetch 10 source rows with the
        shared reader pool (local reads all in parallel, then the
        remote deficit in parallel) and solve the one-row
        reconstruction locally. Byte-identical to the historical
        serial loop — any 10 valid rows produce the same bytes."""
        rs = rs or ReedSolomon()
        pool = _get_recover_pool()
        rows: List[np.ndarray] = []
        ids: List[int] = []
        # snapshot: a concurrent unmount between membership test and
        # element access must degrade the row, not raise KeyError
        shards = dict(self.shards)
        local_futs = [
            (sid, pool.submit(shards[sid].read_at, off, length))
            for sid in range(TOTAL_SHARDS)
            if sid != missing_shard and sid in shards]
        for sid, fut in local_futs:
            try:
                b = fut.result()
            except (OSError, ValueError):  # failing disk / closed by
                b = b""                    # a concurrent unmount
            if len(b) == length and len(ids) < DATA_SHARDS:
                ids.append(sid)
                rows.append(np.frombuffer(b, dtype=np.uint8))
        if len(ids) < DATA_SHARDS and remote_reader is not None:
            remote_sids = [sid for sid in range(TOTAL_SHARDS)
                           if sid != missing_shard and sid not in ids]
            remote_futs = [(sid, pool.submit(remote_reader, sid, off,
                                             length))
                           for sid in remote_sids]
            for sid, fut in remote_futs:
                if len(ids) >= DATA_SHARDS:
                    break
                try:
                    b = fut.result()
                # lint: swallow-ok(a dead peer fails rows, not reads; deficit rows top up below)
                except Exception:
                    b = None
                if b is not None and len(b) == length:
                    ids.append(sid)
                    rows.append(np.frombuffer(b, dtype=np.uint8))
        if len(ids) < DATA_SHARDS:
            raise EcShardNotFound(
                f"vid {self.volume_id} shard {missing_shard}: only "
                f"{len(ids)} shards reachable, need {DATA_SHARDS}")
        # rows were appended local-first: restore canonical sid order so
        # the decode matrix (and its cache key) is deterministic
        order = np.argsort(ids)
        src = np.stack([rows[i] for i in order], axis=0)
        ids = [ids[i] for i in order]
        out = rs.reconstruct_some(ids, [missing_shard], src)
        ReadsDegradedCounter.inc()
        ReadsDecodedBytesCounter.inc(float(length))
        return out[0].tobytes()

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        with self._lock:
            for s in self.shards.values():
                s.close()
            self.shards.clear()
            self._ecx.close()
            self._ecj.close()

    def destroy(self) -> None:
        """Remove all local ec files for this volume."""
        with self._lock:
            for s in list(self.shards.values()):
                s.destroy()
            self.shards.clear()
            self._ecx.close()
            self._ecj.close()
            for ext in (".ecx", ".ecj"):
                p = self.base_name + ext
                if os.path.exists(p):
                    os.remove(p)

    def file_count(self) -> int:
        alive = ~np.isin(self._sizes, [t.TOMBSTONE_SIZE]) & (self._sizes >= 0)
        return int(alive.sum())
