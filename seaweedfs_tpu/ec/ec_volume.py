"""EcVolume: runtime state of one erasure-coded volume on a server.

Holds mounted shard files, the key-sorted .ecx index, and the .ecj
delete journal. Needle reads resolve via binary search + interval math;
missing-shard intervals are recovered by callers through the RS decoder
(see read_needle / seaweedfs_tpu/volume_server integration).

Reference: weed/storage/erasure_coding/ec_volume.go, ec_shard.go,
ec_volume_delete.go.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from seaweedfs_tpu.ec import locate as ec_locate
from seaweedfs_tpu.ec.encoder import (
    shard_file_name, LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE,
)
from seaweedfs_tpu.ec.shard_bits import ShardBits, DATA_SHARDS, TOTAL_SHARDS
from seaweedfs_tpu.ops.rs_code import ReedSolomon
from seaweedfs_tpu.storage import idx as idx_codec
from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.needle import Needle, NeedleError, actual_size


class EcShardNotFound(NeedleError):
    pass


class EcVolumeShard:
    """One mounted .ecNN file (reference ec_shard.go:16-95)."""

    def __init__(self, directory: str, collection: str, vid: int, shard_id: int):
        self.collection = collection
        self.volume_id = vid
        self.shard_id = shard_id
        name = f"{collection}_{vid}" if collection else str(vid)
        self.path = shard_file_name(os.path.join(directory, name), shard_id)
        self._f = open(self.path, "rb")
        self.size = os.path.getsize(self.path)
        self._lock = threading.Lock()

    def read_at(self, offset: int, length: int) -> bytes:
        with self._lock:
            self._f.seek(offset)
            return self._f.read(length)

    def close(self) -> None:
        self._f.close()

    def destroy(self) -> None:
        self.close()
        if os.path.exists(self.path):
            os.remove(self.path)


class EcVolume:
    def __init__(self, directory: str, collection: str, vid: int,
                 large_block: int = LARGE_BLOCK_SIZE,
                 small_block: int = SMALL_BLOCK_SIZE):
        self.directory = directory
        self.collection = collection
        self.volume_id = vid
        self.large_block = large_block
        self.small_block = small_block
        name = f"{collection}_{vid}" if collection else str(vid)
        self.base_name = os.path.join(directory, name)
        if not os.path.exists(self.base_name + ".ecx"):
            raise FileNotFoundError(self.base_name + ".ecx")
        self._ecx = open(self.base_name + ".ecx", "r+b")
        self._ecj = open(self.base_name + ".ecj", "a+b")
        self._lock = threading.RLock()
        self.shards: Dict[int, EcVolumeShard] = {}
        # remote shard location cache: shard id -> list of server urls
        self.shard_locations: Dict[int, List[str]] = {}
        self.shard_locations_refreshed_at = 0.0
        self._load_ecx()
        self.created_at = time.time()

    # -- index ---------------------------------------------------------------

    def _load_ecx(self) -> None:
        self._ecx.seek(0)
        arr = idx_codec.parse_index_bytes(self._ecx.read())
        self._keys = arr["key"].copy()
        self._offsets = arr["offset"].copy()
        self._sizes = arr["size"].copy()

    def find_needle(self, needle_id: int) -> Tuple[int, int]:
        """Return (dat_offset, size); raises NeedleError if absent/deleted."""
        i = int(np.searchsorted(self._keys, np.uint64(needle_id)))
        if i >= len(self._keys) or self._keys[i] != needle_id:
            raise NeedleError(f"needle {needle_id:x} not in ecx")
        size = int(self._sizes[i])
        if t.size_is_deleted(size):
            raise NeedleError(f"needle {needle_id:x} deleted")
        return int(self._offsets[i]), size

    def delete_needle(self, needle_id: int) -> None:
        """Tombstone in the sorted .ecx in place + journal to .ecj
        (reference ec_volume_delete.go:13-49)."""
        with self._lock:
            i = int(np.searchsorted(self._keys, np.uint64(needle_id)))
            if i >= len(self._keys) or self._keys[i] != needle_id:
                return
            self._sizes[i] = t.TOMBSTONE_SIZE
            entry_off = i * t.NEEDLE_MAP_ENTRY_SIZE
            self._ecx.seek(entry_off + t.NEEDLE_ID_SIZE + t.OFFSET_SIZE)
            self._ecx.write((t.TOMBSTONE_SIZE & 0xFFFFFFFF).to_bytes(4, "big"))
            self._ecx.flush()
            self._ecj.seek(0, os.SEEK_END)
            self._ecj.write(needle_id.to_bytes(8, "big"))
            self._ecj.flush()

    # -- shards --------------------------------------------------------------

    def mount_shard(self, shard_id: int) -> EcVolumeShard:
        with self._lock:
            if shard_id in self.shards:
                return self.shards[shard_id]
            s = EcVolumeShard(self.directory, self.collection, self.volume_id,
                              shard_id)
            self.shards[shard_id] = s
            return s

    def unmount_shard(self, shard_id: int) -> bool:
        with self._lock:
            s = self.shards.pop(shard_id, None)
            if s is None:
                return False
            s.close()
            return True

    @property
    def shard_bits(self) -> ShardBits:
        return ShardBits.of(*self.shards.keys())

    @property
    def shard_size(self) -> int:
        for s in self.shards.values():
            return s.size
        # no local shards: derive from any shard file present
        for i in range(TOTAL_SHARDS):
            p = shard_file_name(self.base_name, i)
            if os.path.exists(p):
                return os.path.getsize(p)
        return 0

    # -- needle read ---------------------------------------------------------

    def locate_needle(self, needle_id: int, version: int = 3):
        """(offset, size, intervals) for the WHOLE needle record."""
        offset, size = self.find_needle(needle_id)
        dat_size = DATA_SHARDS * self.shard_size
        intervals = ec_locate.locate_data(
            self.large_block, self.small_block, dat_size,
            offset, actual_size(size, version))
        return offset, size, intervals

    def read_needle(self, n: Needle, version: int = 3,
                    remote_reader: Optional[Callable] = None,
                    rs: Optional[ReedSolomon] = None) -> Needle:
        """Read+verify a needle from local shards, remote shards, or by
        live RS reconstruction of missing intervals.

        remote_reader(shard_id, shard_offset, length) -> bytes|None is
        supplied by the volume server for non-local shards.
        """
        _, size, intervals = self.locate_needle(n.id, version)
        pieces = []
        for iv in intervals:
            pieces.append(self._read_interval(iv, remote_reader, rs))
        blob = b"".join(pieces)
        got = Needle.from_bytes(blob, version)
        if n.cookie and got.cookie != n.cookie:
            from seaweedfs_tpu.storage.needle import CookieMismatch
            raise CookieMismatch(
                f"needle {n.id:x}: cookie {n.cookie:08x} != {got.cookie:08x}")
        return got

    def _read_interval(self, iv: ec_locate.Interval,
                       remote_reader: Optional[Callable],
                       rs: Optional[ReedSolomon]) -> bytes:
        shard_id, off = iv.to_shard_and_offset(self.large_block, self.small_block)
        s = self.shards.get(shard_id)
        if s is not None:
            data = s.read_at(off, iv.size)
            if len(data) == iv.size:
                return data
            # short read (e.g. shard truncated by a crashed rebuild):
            # treat the shard as missing and reconstruct from the others
            return self._recover_interval(shard_id, off, iv.size,
                                          remote_reader, rs)
        if remote_reader is not None:
            data = remote_reader(shard_id, off, iv.size)
            if data is not None:
                return data
        return self._recover_interval(shard_id, off, iv.size, remote_reader, rs)

    def _recover_interval(self, missing_shard: int, off: int, length: int,
                          remote_reader: Optional[Callable],
                          rs: Optional[ReedSolomon]) -> bytes:
        """On-the-fly RS reconstruction of one interval
        (reference store_ec.go:322-376)."""
        rs = rs or ReedSolomon()
        rows = []
        ids = []
        for sid in range(TOTAL_SHARDS):
            if sid == missing_shard:
                continue
            buf = None
            s = self.shards.get(sid)
            if s is not None:
                b = s.read_at(off, length)
                if len(b) == length:
                    buf = np.frombuffer(b, dtype=np.uint8)
            if buf is None and remote_reader is not None:
                b = remote_reader(sid, off, length)
                if b is not None and len(b) == length:
                    buf = np.frombuffer(b, dtype=np.uint8)
            if buf is not None:
                ids.append(sid)
                rows.append(buf)
            if len(ids) >= DATA_SHARDS:
                break
        if len(ids) < DATA_SHARDS:
            raise EcShardNotFound(
                f"vid {self.volume_id} shard {missing_shard}: only "
                f"{len(ids)} shards reachable, need {DATA_SHARDS}")
        src = np.stack(rows, axis=0)
        out = rs.reconstruct_some(ids, [missing_shard], src)
        return out[0].tobytes()

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        with self._lock:
            for s in self.shards.values():
                s.close()
            self.shards.clear()
            self._ecx.close()
            self._ecj.close()

    def destroy(self) -> None:
        """Remove all local ec files for this volume."""
        with self._lock:
            for s in list(self.shards.values()):
                s.destroy()
            self.shards.clear()
            self._ecx.close()
            self._ecj.close()
            for ext in (".ecx", ".ecj"):
                p = self.base_name + ext
                if os.path.exists(p):
                    os.remove(p)

    def file_count(self) -> int:
        alive = ~np.isin(self._sizes, [t.TOMBSTONE_SIZE]) & (self._sizes >= 0)
        return int(alive.sum())
