"""ShardBits: uint32 bitmask of mounted shard ids per (node, volume).

Reference: weed/storage/erasure_coding/ec_volume_info.go:61-113.
"""

from __future__ import annotations

from seaweedfs_tpu.ops.rs_code import DATA_SHARDS, TOTAL_SHARDS


class ShardBits(int):
    def add(self, shard_id: int) -> "ShardBits":
        return ShardBits(self | (1 << shard_id))

    def remove(self, shard_id: int) -> "ShardBits":
        return ShardBits(self & ~(1 << shard_id))

    def has(self, shard_id: int) -> bool:
        return bool(self & (1 << shard_id))

    @property
    def shard_ids(self) -> list[int]:
        return [i for i in range(TOTAL_SHARDS) if self.has(i)]

    @property
    def count(self) -> int:
        return bin(self & ((1 << TOTAL_SHARDS) - 1)).count("1")

    def plus(self, other: "ShardBits") -> "ShardBits":
        return ShardBits(self | other)

    def minus(self, other: "ShardBits") -> "ShardBits":
        return ShardBits(self & ~other)

    def minus_parity(self) -> "ShardBits":
        return ShardBits(self & ((1 << DATA_SHARDS) - 1))

    @classmethod
    def of(cls, *shard_ids: int) -> "ShardBits":
        b = cls(0)
        for s in shard_ids:
            b = b.add(s)
        return b
