"""Store-level EC operations: the volume server's EC surface.

Functional equivalents of the reference's store_ec.go /
store_ec_delete.go and the per-RPC handlers in
server/volume_grpc_erasure_coding.go:38-400 — generate, rebuild,
mount/unmount, shard reads, EC needle reads with live recovery, decode
back to a normal volume. All take the Store as first arg; the Store
stays EC-agnostic (the ec package plugs into DiskLocation.ec_volumes).
"""

from __future__ import annotations

import os
import struct
from typing import Callable, Dict, Iterable, List, Optional, Sequence

from seaweedfs_tpu.ec import encoder, fleet
from seaweedfs_tpu.ec.ec_volume import EcVolume, EcShardNotFound
from seaweedfs_tpu.ec.shard_bits import TOTAL_SHARDS
from seaweedfs_tpu.ops.rs_code import ReedSolomon
from seaweedfs_tpu.stats import trace
from seaweedfs_tpu.storage.needle import Needle, NeedleError
from seaweedfs_tpu.storage.store import Store


def _base_name(directory: str, collection: str, vid: int) -> str:
    name = f"{collection}_{vid}" if collection else str(vid)
    return os.path.join(directory, name)


def _find_ec_base(store: Store, vid: int,
                  collection: Optional[str] = None) -> Optional[str]:
    """Locate the <base>.ecx for a volume across disk locations.

    A mounted EcVolume is authoritative for the collection name; when
    collection is unknown the directories are scanned for any
    [collection_]vid.ecx match (the same discovery rule
    DiskLocation._load_ec_shards uses)."""
    ecv = store.find_ec_volume(vid)
    if ecv is not None and os.path.exists(ecv.base_name + ".ecx"):
        return ecv.base_name
    for loc in store.locations:
        if collection is not None:
            base = _base_name(loc.directory, collection, vid)
            if os.path.exists(base + ".ecx"):
                return base
            continue
        for name in os.listdir(loc.directory):
            if not name.endswith(".ecx"):
                continue
            stem = name[:-len(".ecx")]
            col, _, tail = stem.rpartition("_")
            if tail == str(vid) or (not col and stem == str(vid)):
                return os.path.join(loc.directory, stem)
    return None


def generate_ec_shards(store: Store, vid: int, backend: str = "auto") -> str:
    """VolumeEcShardsGenerate: .dat/.idx -> .ec00-13 + .ecx.

    The volume must exist locally; it is marked read-only first (the
    shell's ec.encode does this cluster-wide before calling in).
    Returns the base name the shard files were written under.
    """
    v = store.find_volume(vid)
    if v is None:
        raise NeedleError(f"volume {vid} not found for ec encode")
    v.read_only = True
    v.sync()
    base = v.file_name()
    with trace.span("store_ec.generate", vid=vid):
        encoder.write_ec_files(base, backend=backend)
        encoder.write_sorted_file_from_idx(base)
    return base


def generate_ec_shards_batch(store: Store, vids: Sequence[int],
                             backend: str = "auto",
                             mesh_cfg: Optional[dict] = None
                             ) -> Dict[int, str]:
    """VolumeEcShardsGenerate for MANY volumes in one fused pass.

    Every volume is frozen (read-only + sync) up front, then ONE
    scheduler packs chunks from all of them into shared RS dispatches:
    with `mesh_cfg` (the volume server's -ec.mesh* knobs) the pass
    rides the unified pod-scale mesh scheduler
    (parallel/mesh_fleet.pod_write_ec_files, which falls back to the
    per-device fleet ladder on any MeshError); without it, the host
    fleet scheduler (ec/fleet.py). Shard bytes are identical to
    calling generate_ec_shards per volume either way. Returns
    {vid: base_name}.
    """
    vols = []
    for vid in vids:  # validate the whole list BEFORE freezing any —
        v = store.find_volume(vid)  # a bad vid must not strand earlier
        if v is None:               # volumes read-only with no shards
            raise NeedleError(f"volume {vid} not found for ec encode")
        vols.append((vid, v))
    bases: Dict[int, str] = {}
    for vid, v in vols:
        v.read_only = True
        v.sync()
        bases[vid] = v.file_name()
    with trace.span("store_ec.generate_batch", volumes=len(bases)):
        mesh_fleet = fleet.mesh_fleet_or_none() \
            if mesh_cfg is not None else None
        if mesh_fleet is not None:
            mesh_fleet.pod_write_ec_files(list(bases.values()),
                                          backend=backend, **mesh_cfg)
        else:
            fleet.fleet_write_ec_files(list(bases.values()),
                                       backend=backend)
        with trace.span("store_ec.write_ecx"):
            for base in bases.values():
                encoder.write_sorted_file_from_idx(base)
    return bases


def rebuild_ec_shards(store: Store, vid: int, collection: Optional[str] = None,
                      backend: str = "auto") -> List[int]:
    """VolumeEcShardsRebuild: regenerate missing .ecNN from >=10 local
    ones. Returns rebuilt shard ids."""
    base = _find_ec_base(store, vid, collection)
    if base is None:
        raise EcShardNotFound(f"no local ec files for volume {vid}")
    with trace.span("store_ec.rebuild", vid=vid):
        return encoder.rebuild_ec_files(base, backend=backend)


def mount_ec_shards(store: Store, vid: int, collection: str,
                    shard_ids: Iterable[int]) -> EcVolume:
    """VolumeEcShardsMount: open shard files and register the EcVolume."""
    base = _find_ec_base(store, vid, collection)
    if base is None:
        raise EcShardNotFound(f"volume {vid}: no .ecx on any disk location")
    loc = next(l for l in store.locations
               if os.path.dirname(base) == l.directory)
    ecv = loc.ec_volumes.get(vid)
    if ecv is None:
        ecv = EcVolume(loc.directory, collection, vid)
        loc.ec_volumes[vid] = ecv
    for sid in shard_ids:
        ecv.mount_shard(sid)
    return ecv


def unmount_ec_shards(store: Store, vid: int,
                      shard_ids: Iterable[int]) -> None:
    """VolumeEcShardsUnmount; drops the EcVolume when no shards remain."""
    ecv = store.find_ec_volume(vid)
    if ecv is None:
        return
    for sid in shard_ids:
        ecv.unmount_shard(sid)
    if not ecv.shards:
        loc = store.location_of(vid)
        ecv.close()
        if loc is not None:
            loc.ec_volumes.pop(vid, None)


def delete_ec_shards(store: Store, vid: int, collection: Optional[str] = None,
                     shard_ids: Iterable[int] = ()) -> None:
    """VolumeEcShardsDelete: remove shard files; when none remain, the
    .ecx/.ecj go too (reference volume_grpc_erasure_coding.go:136-210)."""
    base = _find_ec_base(store, vid, collection)
    if base is None:
        return
    ecv = store.find_ec_volume(vid)
    for sid in shard_ids:
        if ecv is not None:
            ecv.unmount_shard(sid)
        p = encoder.shard_file_name(base, sid)
        if os.path.exists(p):
            os.remove(p)
    if not any(os.path.exists(encoder.shard_file_name(base, i))
               for i in range(TOTAL_SHARDS)):
        loc = next(l for l in store.locations
                   if os.path.dirname(base) == l.directory)
        if ecv is not None:
            ecv.close()
            loc.ec_volumes.pop(vid, None)
        for ext in (".ecx", ".ecj"):
            if os.path.exists(base + ext):
                os.remove(base + ext)


def read_ec_shard(store: Store, vid: int, shard_id: int, offset: int,
                  length: int) -> bytes:
    """VolumeEcShardRead: raw bytes of one local shard (serves remote
    peers' interval reads)."""
    ecv = store.find_ec_volume(vid)
    if ecv is None:
        raise EcShardNotFound(f"ec volume {vid} not mounted")
    shard = ecv.shards.get(shard_id)
    if shard is None:
        raise EcShardNotFound(f"ec volume {vid} shard {shard_id} not local")
    return shard.read_at(offset, length)


def read_ec_needle(store: Store, vid: int, n: Needle,
                   remote_reader: Optional[Callable] = None,
                   rs: Optional[ReedSolomon] = None,
                   cache=None, decoder=None,
                   version: int = 3) -> Needle:
    """ReadEcShardNeedle: cookie-checked needle read over shards, with
    remote fan-out and on-the-fly RS recovery (store_ec.go:122-262).

    With a `cache` (cache.TieredReadCache) the whole stored record
    rides the needle-keyed tier: repeat reads of a hot needle — healthy
    or degraded — cost one cache hit and a CRC-checked parse, and
    concurrent misses single-flight so one reconstruction serves them
    all. `decoder` (reads.DegradedReadFleet) fuses any reconstruction
    the read does need into batched RS dispatches.
    """
    ecv = store.find_ec_volume(vid)
    if ecv is None:
        raise EcShardNotFound(f"ec volume {vid} not mounted")
    if cache is None:
        return ecv.read_needle(n, version, remote_reader=remote_reader,
                               rs=rs, decoder=decoder)
    sp = trace.span("reads.ec_needle", vid=vid) \
        if trace.is_enabled() else trace.NOOP
    with sp:
        key = cache.needle_key(vid, n.id)
        blob = cache.get(key)
        if blob is None:
            with cache.single_flight(key) as leader:
                if not leader:
                    blob = cache.get(key)  # the leader's result
                if blob is None:
                    # gen snapshot BEFORE the read: if the key or its
                    # volume is invalidated while we reconstruct
                    # (delete, scrub repair), set() refuses the blob
                    gen = cache.generation(key)
                    blob = ecv.read_needle_blob(
                        n.id, version, remote_reader, rs, decoder,
                        span_cache=cache)
                    cache.set(key, blob, gen=gen)
        try:
            got = Needle.from_bytes(blob, version)
        except (NeedleError, ValueError, IndexError, struct.error):
            # poisoned cache data (a file torn by power loss before
            # restart): a bad NEEDLE entry arrives as a cache hit; a
            # bad SPAN entry poisons a freshly-assembled blob. Either
            # way: drop the needle key AND the volume's span entries,
            # then retry once straight from the shards (span cache
            # bypassed). A retry failure is true shard corruption and
            # propagates.
            cache.drop(key)
            cache.drop_spans(vid)
            gen = cache.generation(key)
            blob = ecv.read_needle_blob(n.id, version, remote_reader,
                                        rs, decoder, span_cache=None)
            cache.set(key, blob, gen=gen)
            got = Needle.from_bytes(blob, version)
    if n.cookie and got.cookie != n.cookie:
        from seaweedfs_tpu.storage.needle import CookieMismatch
        raise CookieMismatch(
            f"needle {n.id:x}: cookie {n.cookie:08x} != {got.cookie:08x}")
    return got


def delete_ec_needle(store: Store, vid: int, n: Needle,
                     cache=None) -> None:
    """Tombstone in .ecx + journal to .ecj (store_ec_delete.go);
    drops the needle's cached entries so a delete is never masked."""
    ecv = store.find_ec_volume(vid)
    if ecv is None:
        raise EcShardNotFound(f"ec volume {vid} not mounted")
    ecv.delete_needle(n.id)
    if cache is not None:
        cache.invalidate(vid, n.id, reason="delete")


def scrub_ec_volume(store: Store, vid: int, backend: str = "auto",
                    mbps: float = 0.0):
    """Targeted integrity scrub of ONE mounted EC volume: needle sweep,
    stripe verify, and (when damaged) quarantine + reconstruction —
    the store-level form of the daemon's whole-store pass, for ad-hoc
    operator checks. Returns the scrub PassResult."""
    from seaweedfs_tpu.scrub import ScrubDaemon
    if store.find_ec_volume(vid) is None:
        raise EcShardNotFound(f"ec volume {vid} not mounted")
    # export_lag=False: a throwaway targeted pass must not hijack the
    # process-global scan-lag gauge from the server's own daemon
    daemon = ScrubDaemon(store, backend=backend, mbps=mbps,
                         export_lag=False)
    return daemon.run_pass(volume_ids=[vid])


def ec_shards_to_volume(store: Store, vid: int, collection: str = "",
                        backend: str = "auto",
                        large_block: int = encoder.LARGE_BLOCK_SIZE,
                        small_block: int = encoder.SMALL_BLOCK_SIZE) -> None:
    """VolumeEcShardsToVolume: decode .ec00-09 (+.ecx/.ecj) back into a
    loadable .dat/.idx volume (reference
    volume_grpc_erasure_coding.go:360-400 + ec_decoder.go)."""
    if store.find_ec_volume(vid) is not None:
        raise EcShardNotFound(
            f"volume {vid}: unmount ec shards before decoding back "
            "(a mounted EcVolume would serve stale reads)")
    base = _find_ec_base(store, vid, collection or None)
    if base is None:
        raise EcShardNotFound(f"volume {vid}: no .ecx to decode from")
    loc = next(l for l in store.locations
               if os.path.dirname(base) == l.directory)
    stem = os.path.basename(base)
    collection = stem.rsplit("_", 1)[0] if "_" in stem else ""
    # only the data shards are read back; don't waste RS compute
    # regenerating missing parity
    encoder.rebuild_ec_files(base, backend=backend,
                             wanted=list(range(encoder.DATA_SHARDS)))
    dat_size = encoder.find_dat_file_size(base)
    encoder.write_dat_file(base, dat_size, backend=backend,
                           large_block=large_block, small_block=small_block)
    encoder.write_idx_file_from_ec_index(base)
    from seaweedfs_tpu.storage.volume import Volume
    with loc._lock:
        v = Volume(loc.directory, collection, vid, create_if_missing=False,
                   needle_map_kind=loc.needle_map_kind)
        loc.volumes[vid] = v
    store.new_volumes.append(store.volume_info(v))
