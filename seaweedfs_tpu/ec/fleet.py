"""Cross-volume batched EC scheduler — the fleet encoder.

`ec/encoder.py` encodes ONE volume at a time: every chunk is its own
RS dispatch and a single reader thread feeds the device, so a fleet of
volumes serializes on dispatch latency and on that one thread's disk
reads. This module lifts the batch dimension from rows-within-a-volume
to chunks-ACROSS-volumes (the ROADMAP "sharding, batching, async"
directive; the BASELINE "cluster-wide ec.encode" shape):

  pack      same-sized row-spans from many volumes fuse into one
            [B, 10, small] dispatch — the `_encode_small_rows` batch
            shape — so 64 small volumes cost a handful of dispatches
            instead of 64 serial ones.
  feed      a bounded reader pool prefetches spans ahead of the
            device. Spans are consumed in submission order (round-
            robin rounds over the volumes), so per-volume row order
            is preserved by construction while reads overlap compute.
  dispatch  the jax backend is async already; sync host backends
            (native/numpy) are lifted to the same handle contract by
            a small encode pool, so RS compute itself runs multi-core
            and overlaps the reader and writer threads.
  retire    a tagged completion queue — the FIFO discipline of
            `encoder._EncodePipeline`, generalized from one (handle,
            writeback) pair to per-volume tags — fans each dispatch's
            parity out to many volumes' .ecNN files. A single retire
            thread awaits dispatches strictly in submission order and
            hands every volume's writes to that volume's writer LANE
            (per-volume FIFO, parallel across volumes), so the ~9
            bytes written per 10 read don't serialize behind one
            thread the way the per-volume pipeline's do.

Volumes that need large-row striping (> 10 * large_block bytes) fall
back to the per-volume `write_ec_files` path; everything else is
byte-identical to it (uniform small rows — the same on-disk layout
contract `parallel.sharded_write_ec_files` relies on).

Sharding the fleet across a device mesh (one scheduler per device,
volumes dealt by size) lives in `parallel/mesh.py`:
`fleet_write_ec_files_sharded`.
"""

from __future__ import annotations

import functools
import os
import queue
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from seaweedfs_tpu.ec import encoder as _encoder
from seaweedfs_tpu.ec.encoder import (
    LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE, default_chunk_for, shard_file_name)
from seaweedfs_tpu.ops.rs_code import ReedSolomon, DATA_SHARDS, TOTAL_SHARDS
from seaweedfs_tpu.resilience import failpoint as _failpoint
from seaweedfs_tpu.stats import trace
from seaweedfs_tpu.stats.metrics import (
    FleetDispatchBatchHistogram, FleetDispatchedBytesCounter,
    FleetMeshFallbacksCounter, FleetReaderQueueGauge,
    FleetStageSecondsHistogram, FleetWriterBacklogGauge)


def mesh_fleet_or_none():
    """The pod-scale mesh scheduler module (parallel/mesh_fleet), or
    None on a jax-less host — parallel's package import needs jax at
    import time. A None return counts as a mesh fallback; the caller
    runs the host fleet path instead."""
    try:
        from seaweedfs_tpu.parallel import mesh_fleet
        return mesh_fleet
    except ImportError:
        FleetMeshFallbacksCounter.labels("unavailable").inc()
        return None

# Reader-pool width: enough to keep several volumes' sequential reads
# in flight without degrading each stream to fully random IO.
FLEET_READERS = 4

# Fused dispatches in flight at once — the writer-queue bound, same
# double-buffering role as encoder.PIPELINE_DEPTH. Peak host memory is
# ~(depth + 2) fused batches (queued + packing + retiring).
FLEET_DEPTH = 2

# Encode pool for synchronous host backends: ctypes/numpy release the
# GIL, so two in-flight fused encodes use two cores — the host-side
# analogue of the device's async dispatch queue.
FLEET_ENCODERS = max(2, min(4, os.cpu_count() or 2))

# Writer lanes: each volume's writes stay FIFO on one lane, but lanes
# run in parallel, so the fleet's file writes (the larger half of the
# IO: 14 bytes out per 10 in) spread across cores instead of
# serializing behind a single writer thread.
FLEET_WRITERS = max(2, min(4, os.cpu_count() or 2))

# Bound on queued writes per lane: with ~chunk-sized spans this caps
# writer-side buffering at a few spans per lane.
_LANE_QUEUE = 4


# Stage-latency children resolved once at import: labels() takes a
# lock per call, and a stage interval closes for every chunk-sized
# unit of work.
_STAGE_HIST = {s: FleetStageSecondsHistogram.labels(s)
               for s in ("read", "dispatch", "rs", "retire", "write",
                         "verify", "upload")}


class _StageTimer:
    """One pipeline-stage interval: always observed into the per-stage
    latency histogram, and additionally recorded as a trace span when
    tracing is enabled (parented across threads via a handoff token).
    Span allocation is gated on the trace flag so the disabled path
    costs one histogram observe per chunk-sized unit of work."""

    __slots__ = ("_hist", "_span", "_t0")

    def __init__(self, stage: str, parent: Optional[int] = None, **tags):
        self._hist = _STAGE_HIST[stage]
        self._span = trace.span("fleet." + stage, parent=parent, **tags) \
            if trace.is_enabled() else trace.NOOP

    def __enter__(self) -> "_StageTimer":
        self._span.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        self._hist.observe(time.perf_counter() - self._t0)
        return self._span.__exit__(*exc)

    def token(self) -> Optional[int]:
        """Handoff token of the underlying span (None when disabled)."""
        return self._span.token()


class TaggedPipeline:
    """Tagged completion queue: fused dispatches retire FIFO, writes
    fan out to per-volume writer lanes.

    One retire thread awaits dispatch handles strictly in submission
    order — the deque discipline of `encoder._EncodePipeline` — and
    routes each tagged span's parity write to `tag % lanes`. All of a
    volume's writes carry the volume's tag, so they land on ONE lane in
    enqueue order (per-volume FIFO by construction) while different
    volumes' writes proceed in parallel. Data-shard writes (`write`)
    need no handle and go straight to the lane from the packing thread;
    they interleave with parity writes on the lane but touch disjoint
    files (.ec00-09 vs .ec10-13), so only the per-file order matters —
    and each file's writes come from a single ordered source.
    """

    def __init__(self, depth: int = FLEET_DEPTH,
                 writers: int = FLEET_WRITERS):
        self._lanes: List["queue.Queue[Optional[Tuple]]"] = [
            queue.Queue(maxsize=_LANE_QUEUE)
            for _ in range(max(1, writers))]
        self._retireq: "queue.Queue[Optional[Tuple]]" = \
            queue.Queue(maxsize=max(1, depth))
        self._exc: Optional[BaseException] = None
        # per-lane backlog gauges resolved once: labels() locks per call
        self._lane_gauges = [FleetWriterBacklogGauge.labels(str(i))
                             for i in range(len(self._lanes))]
        self._writers = [
            # lint: gate-ok(TaggedPipeline is built per fleet pass: construction is first use) # lint: thread-ok(fleet writers carry explicit volume tags, not request context)
            threading.Thread(target=self._drain_lane, args=(q, i),
                             name=f"fleet-write-{i}", daemon=True)
            for i, q in enumerate(self._lanes)]
        # lint: gate-ok(TaggedPipeline is built per fleet pass: construction is first use) # lint: thread-ok(retire thread carries explicit tags, not request context)
        self._retirer = threading.Thread(
            target=self._retire_loop, name="fleet-retire", daemon=True)
        for t in self._writers:
            t.start()
        self._retirer.start()

    def _put_lane(self, tag: int, fn: Callable[[], None],
                  token: Optional[int],
                  timeout_s: Optional[float] = None) -> None:
        lane = tag % len(self._lanes)
        # inc/dec deltas, not set(qsize): several schedulers run
        # concurrently (mesh sharding, parallel generate RPCs) and
        # share these children, so the gauge must SUM their backlogs
        # rather than last-write-wins one scheduler's view
        self._lane_gauges[lane].inc()
        try:
            self._lanes[lane].put((fn, token), timeout=timeout_s)
        except queue.Full:
            self._lane_gauges[lane].dec()  # never entered the lane
            raise

    def write(self, tag: int, fn: Callable[[], None],
              timeout_s: Optional[float] = None) -> None:
        """Enqueue one ordered write on `tag`'s lane (no handle).
        With timeout_s, a lane that stays full that long raises
        queue.Full instead of blocking the caller behind a wedged
        writer — same stall contract as submit()."""
        self._raise_pending()
        self._put_lane(tag, fn, trace.handoff(), timeout_s)

    def submit(self, handle,
               tagged: Sequence[Tuple[int, Callable]],
               timeout_s: Optional[float] = None) -> None:
        """Queue a dispatch: when `handle` resolves (FIFO), span i's
        output goes to `tagged[i] = (tag, fn)` as `fn(outs[i])` on
        tag's lane. With timeout_s, waiting `timeout_s` for a free
        in-flight slot raises queue.Full — the mesh scheduler's
        dispatch-stall detection (parallel/mesh_fleet.py) — instead of
        blocking forever behind a wedged retire."""
        self._raise_pending()
        self._retireq.put((handle, list(tagged), trace.handoff()),
                          timeout=timeout_s)

    def _retire_loop(self) -> None:
        while True:
            item = self._retireq.get()
            if item is None:
                return
            if self._exc is not None:
                # failed: keep draining, write nothing more — but let
                # the handle release its resources (the mesh scheduler
                # tracks in-flight buckets per handle)
                abandon = getattr(item[0], "abandon", None)
                if abandon is not None:
                    try:
                        abandon()
                    # lint: swallow-ok(first error already latched; abandon is cleanup)
                    except Exception:
                        pass
                continue
            handle, tagged, token = item
            try:
                # the retire stage is where async dispatches actually
                # resolve — for the jax backend this wait IS the device
                # time (block_until_ready), for host backends the encode
                # pool's compute; the lane puts after it are writer-side
                # backpressure, also this stage's problem
                with _StageTimer("retire", parent=token,
                                 spans=len(tagged)) as st:
                    outs = handle.result()
                    for (tag, fn), out in zip(tagged, outs):
                        self._put_lane(tag, functools.partial(fn, out),
                                       st.token())
            except BaseException as e:  # surfaced on submit/drain
                if self._exc is None:
                    self._exc = e

    def _drain_lane(self, q: "queue.Queue[Optional[Tuple]]",
                    lane: int) -> None:
        while True:
            item = q.get()
            if item is None:
                return
            self._lane_gauges[lane].dec()
            if self._exc is not None:
                continue
            fn, token = item
            try:
                with _StageTimer("write", parent=token, lane=lane):
                    fn()
            except BaseException as e:
                if self._exc is None:
                    self._exc = e

    def _raise_pending(self) -> None:
        # _exc stays latched once set: clearing it here would re-enable
        # the retire/writer threads after they skipped a failed span,
        # letting later spans land past a hole in the shard files
        if self._exc is not None:
            raise self._exc

    def drain(self) -> None:
        """Flush every queued write, stop all threads, re-raise the
        first error (if any). The pipeline is spent afterwards."""
        self._retireq.put(None)
        self._retirer.join()
        for q in self._lanes:
            q.put(None)
        for t in self._writers:
            t.join()
        self._raise_pending()


class _Gathered:
    """Handle over several in-flight per-span encodes: .result() is the
    list of per-span outputs, ordered like the spans were packed."""

    def __init__(self, handles):
        self._handles = handles

    def result(self) -> List[np.ndarray]:
        return [h.result() for h in self._handles]


def _rs_staged(fn, arr: np.ndarray, parent: Optional[int]) -> np.ndarray:
    """One host-backend RS compute task, attributed to the 'rs' stage
    (the jax path's device time shows up in 'retire' instead, where
    handle.result() blocks)."""
    with _StageTimer("rs", parent=parent):
        return fn(arr)


class _Dispatcher:
    """Uniform async-handle dispatch over any RS backend.

    jax dispatches are inherently async (the device computes while the
    host stages IO), so a fused batch is concatenated once and issued
    as one dispatch — fewer, fuller device slabs. Host backends compute
    synchronously instead, so each span goes to a small encode pool as
    its own task (no concatenation copy; the GIL-free native/numpy
    kernels genuinely run on other cores) and the handles are gathered.
    Either way .result() yields per-span parity arrays.
    """

    def __init__(self, rs: ReedSolomon, device=None,
                 encoders: int = FLEET_ENCODERS):
        self._rs = rs
        self._device = device
        self._pool = None
        if rs.backend != "jax":
            # lint: thread-ok(fleet dispatch pool; work items are explicit, no ambient request state)
            self._pool = ThreadPoolExecutor(
                max_workers=max(1, encoders),
                thread_name_prefix="fleet-encode")

    def encode(self, arrays: List[np.ndarray]):
        if _failpoint._armed:
            _failpoint.hit("fleet.dispatch", op="encode")
        if self._pool is None:
            data = arrays[0] if len(arrays) == 1 else \
                np.concatenate(arrays, axis=0)
            rows = [a.shape[0] for a in arrays]
            handle = self._rs.encode_async(data, device=self._device)
            return _SplitHandle(handle, rows)
        token = trace.handoff()
        return _Gathered([self._pool.submit(_rs_staged, self._rs.encode,
                                            a, token)
                          for a in arrays])

    def reconstruct(self, present, missing, arrays: List[np.ndarray]):
        if _failpoint._armed:
            _failpoint.hit("fleet.dispatch", op="reconstruct")
        if self._pool is None:
            src = np.stack(arrays, axis=0)  # [B, 10, span]
            handle = self._rs.reconstruct_some_async(
                present, missing, src, device=self._device)
            return _UnstackHandle(handle)
        token = trace.handoff()
        return _Gathered([self._pool.submit(
            _rs_staged,
            functools.partial(self._rs.reconstruct_some, present, missing),
            a, token)
            for a in arrays])

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=True)


class _SplitHandle:
    """Adapt one fused async encode handle back to per-span outputs."""

    def __init__(self, handle, rows: List[int]):
        self._handle = handle
        self._rows = rows

    def result(self) -> List[np.ndarray]:
        out = self._handle.result()
        if len(self._rows) == 1:
            return [out]
        parts, row = [], 0
        for r in self._rows:
            parts.append(out[row:row + r])
            row += r
        return parts


class _UnstackHandle:
    """Adapt one fused [B, ...] reconstruct handle to per-item outputs."""

    def __init__(self, handle):
        self._handle = handle

    def result(self) -> List[np.ndarray]:
        out = self._handle.result()
        return [out[i] for i in range(out.shape[0])]


class _VolState:
    __slots__ = ("base", "dat_size", "n_rows", "tag")

    def __init__(self, base: str, dat_size: int, n_rows: int, tag: int = 0):
        self.base = base
        self.dat_size = dat_size
        self.n_rows = n_rows
        self.tag = tag  # writer-lane key: all this volume's writes
        #                 share it, so they stay FIFO on one lane


def _append_rows(base: str, shard_id: int,
                 rows: Sequence[np.ndarray]) -> None:
    """Append C-contiguous row slices to one shard file: the slices go
    straight to the (buffered) file object — no ascontiguousarray /
    tobytes staging copies on the write path."""
    with open(shard_file_name(base, shard_id), "ab") as f:
        for r in rows:
            f.write(r)


def _round_robin_spans(vols: List[_VolState], span_rows: int):
    """Yield (vol, row0, rows) in rounds over the volumes: round r
    hands out rows [r*span, (r+1)*span) of every volume still alive.
    Submission order == pack order == per-volume row order."""
    pending = [(v, 0) for v in vols if v.n_rows > 0]
    while pending:
        nxt = []
        for v, row0 in pending:
            rows = min(span_rows, v.n_rows - row0)
            yield v, row0, rows
            if row0 + rows < v.n_rows:
                nxt.append((v, row0 + rows))
        pending = nxt


def _read_span(base: str, row0: int, rows: int,
               row_bytes: int, small_block: int) -> np.ndarray:
    """Rows [row0, row0+rows) of one volume as [rows, 10, small],
    zero-padded past EOF — one sequential read per span (the same
    readinto primitive as encoder._read_padded)."""
    with open(base + ".dat", "rb") as f:
        buf = _encoder._read_padded(f, row0 * row_bytes, rows * row_bytes)
    return buf.reshape(rows, DATA_SHARDS, small_block)


def _read_span_staged(base: str, row0: int, rows: int, row_bytes: int,
                      small_block: int, parent: Optional[int]) -> np.ndarray:
    """_read_span on a reader-pool thread, attributed to the 'read'
    stage and parented to the scheduler's root span."""
    with _StageTimer("read", parent=parent, vol=os.path.basename(base)):
        return _read_span(base, row0, rows, row_bytes, small_block)


def _write_data_shards(base: str, arr: np.ndarray) -> None:
    for i in range(DATA_SHARDS):
        _append_rows(base, i, [arr[r, i] for r in range(arr.shape[0])])


def _write_parity_span(base: str, seg: np.ndarray) -> None:
    """One span's parity [rows, 4, small] -> append to .ec10-.ec13."""
    for p in range(seg.shape[1]):
        _append_rows(base, DATA_SHARDS + p,
                     [seg[r, p] for r in range(seg.shape[0])])


def fleet_write_ec_files(base_names: Sequence[str], backend: str = "auto",
                         large_block: int = LARGE_BLOCK_SIZE,
                         small_block: int = SMALL_BLOCK_SIZE,
                         chunk: Optional[int] = None,
                         readers: int = FLEET_READERS,
                         depth: int = FLEET_DEPTH,
                         encoders: int = FLEET_ENCODERS,
                         device=None) -> None:
    """Generate .ec00-.ec13 for MANY volumes, fusing chunks across
    volumes into shared RS dispatches.

    Byte-identical to running `write_ec_files` per volume: small-row
    volumes ride the fused scheduler; oversized ones (large-row
    striping) fall back to the per-volume path. `device` pins the jax
    dispatches of this scheduler to one chip (see
    parallel.fleet_write_ec_files_sharded).
    """
    if chunk is None:
        chunk = default_chunk_for(backend)
    fleet: List[str] = []
    for base in base_names:
        if os.path.getsize(base + ".dat") > DATA_SHARDS * large_block:
            _encoder.write_ec_files(base, backend=backend,
                                    large_block=large_block,
                                    small_block=small_block, chunk=chunk)
        else:
            fleet.append(base)
    if not fleet:
        return
    row_bytes = DATA_SHARDS * small_block
    vols = []
    # creating/truncating 14 output files per volume is real write-side
    # IO (measured ~10% of a small fleet's wall time), so it carries
    # the write stage's span/metric attribution
    with _StageTimer("write", setup=len(fleet)):
        for tag, base in enumerate(fleet):
            size = os.path.getsize(base + ".dat")
            vols.append(_VolState(base, size, -(-size // row_bytes), tag))
            for i in range(TOTAL_SHARDS):  # create/truncate all 14 outputs
                open(shard_file_name(base, i), "wb").close()
    alive = [v for v in vols if v.n_rows > 0]
    if not alive:
        return  # all empty: 14 empty shard files each, same as serial
    # One fused dispatch ≈ `chunk` bytes of data rows; span size is the
    # per-volume slice of it, so a full round across the fleet packs
    # into one dispatch (a single volume degrades to the serial shape).
    batch_rows = max(1, chunk // row_bytes)
    span_rows = max(1, batch_rows // len(alive))
    spans_per_batch = -(-batch_rows // span_rows)
    prefetch = max(readers, 2 * spans_per_batch)

    dispatcher = _Dispatcher(ReedSolomon(backend=backend), device=device,
                             encoders=encoders)
    # lint: thread-ok(per-pass reader pool; work items are explicit, no ambient request state)
    pool = ThreadPoolExecutor(max_workers=max(1, readers),
                              thread_name_prefix="fleet-read")
    pipe = TaggedPipeline(depth=depth)
    gen = _round_robin_spans(alive, span_rows)
    inflight: deque = deque()
    root = trace.span("fleet.encode", volumes=len(alive), backend=backend)
    root.__enter__()
    token = root.token()

    def fill() -> None:
        while len(inflight) < prefetch:
            nxt = next(gen, None)
            if nxt is None:
                break
            v, row0, rows = nxt
            inflight.append((v, rows, pool.submit(
                _read_span_staged, v.base, row0, rows, row_bytes,
                small_block, token)))
            # inc/dec deltas so concurrent schedulers SUM on the
            # shared gauge instead of overwriting each other's depth
            FleetReaderQueueGauge.inc()

    def flush(pack: List[Tuple[_VolState, int, np.ndarray]]) -> None:
        with _StageTimer("dispatch", batch=len(pack)):
            handle = dispatcher.encode([a for _, _, a in pack])
        FleetDispatchBatchHistogram.observe(len(pack))
        FleetDispatchedBytesCounter.inc(
            float(sum(a.nbytes for _, _, a in pack)))
        # data shards need no parity: straight to each volume's lane
        # (enqueued here, in pack order, so per-volume FIFO holds)
        for v, _, arr in pack:
            pipe.write(v.tag, functools.partial(
                _write_data_shards, v.base, arr))
        pipe.submit(handle, [
            (v.tag, functools.partial(_write_parity_span, v.base))
            for v, _, _ in pack])

    try:
        fill()
        pack: List[Tuple[_VolState, int, np.ndarray]] = []
        acc = 0
        while inflight:
            v, rows, fut = inflight.popleft()
            FleetReaderQueueGauge.dec()
            pack.append((v, rows, fut.result()))
            acc += rows
            fill()
            if acc >= batch_rows or not inflight:
                flush(pack)
                pack, acc = [], 0
    finally:
        FleetReaderQueueGauge.dec(len(inflight))  # error path leftovers
        pool.shutdown(wait=True)
        try:
            pipe.drain()  # may re-raise the latched pipeline error
        finally:
            dispatcher.close()
            root.__exit__(None, None, None)


# --- fleet rebuild -----------------------------------------------------------

def fleet_rebuild_ec_files(base_names: Sequence[str], backend: str = "auto",
                           chunk: Optional[int] = None,
                           wanted: Optional[List[int]] = None,
                           readers: int = FLEET_READERS,
                           depth: int = FLEET_DEPTH,
                           encoders: int = FLEET_ENCODERS,
                           device=None) -> Dict[str, List[int]]:
    """Cross-volume batched `rebuild_ec_files`.

    Volumes sharing a (present, missing) signature share one decode
    matrix, so their shard chunks fuse into single [B, 10, span]
    reconstruct dispatches — the rebuild-side twin of
    `fleet_write_ec_files`. Tail spans are zero-padded to the bucket
    width (GF maps send 0 to 0) and trimmed on writeback. Returns
    {base_name: rebuilt shard ids} (empty list where nothing was
    missing).
    """
    if chunk is None:
        chunk = default_chunk_for(backend)
    rebuilt: Dict[str, List[int]] = {}
    groups: Dict[Tuple[Tuple[int, ...], Tuple[int, ...]],
                 List[Tuple[str, int]]] = {}
    for base in base_names:
        present = [i for i in range(TOTAL_SHARDS)
                   if os.path.exists(shard_file_name(base, i))]
        missing = [i for i in
                   (range(TOTAL_SHARDS) if wanted is None else wanted)
                   if i not in present]
        rebuilt[base] = missing
        if not missing:
            continue
        if len(present) < DATA_SHARDS:
            raise ValueError(
                f"cannot rebuild {base}: only {len(present)} shards present")
        shard_size = os.path.getsize(shard_file_name(base, present[0]))
        groups.setdefault((tuple(present), tuple(missing)),
                          []).append((base, shard_size))
    for (present, missing), members in groups.items():
        _fleet_rebuild_group(list(present), list(missing), members, backend,
                             chunk, readers, depth, encoders, device)
    return rebuilt


def _write_rebuilt_span(base: str, missing: List[int], valid: int,
                        out: np.ndarray) -> None:
    """One span's rebuilt shards [len(missing), span] -> append the
    valid prefix of each row to its .ecNN file."""
    for row, sid in enumerate(missing):
        _append_rows(base, sid, [out[row, :valid]])


def _read_present_span(base: str, present: List[int], shard_size: int,
                       offset: int, span: int,
                       parent: Optional[int] = None) -> np.ndarray:
    """[10, span] slice at `offset` of the first 10 present shards,
    zero-padded past shard end."""
    with _StageTimer("read", parent=parent, vol=os.path.basename(base)):
        src = np.zeros((DATA_SHARDS, span), dtype=np.uint8)
        want = min(span, max(shard_size - offset, 0))
        if want > 0:
            for row, sid in enumerate(present[:DATA_SHARDS]):
                with open(shard_file_name(base, sid), "rb") as f:
                    f.seek(offset)
                    f.readinto(memoryview(src[row])[:want])
        return src


def _fleet_rebuild_group(present: List[int], missing: List[int],
                         members: List[Tuple[str, int]], backend: str,
                         chunk: int, readers: int, depth: int,
                         encoders: int, device) -> None:
    for base, _ in members:
        for sid in missing:
            open(shard_file_name(base, sid), "wb").close()
    # Uniform span width so spans from different volumes stack into one
    # [B, 10, span] dispatch of ~chunk bytes per shard row.
    span = max(1, chunk // len(members))
    vols = [(_VolState(base, size, -(-size // span), tag), size)
            for tag, (base, size) in enumerate(members)]

    def gen_spans():
        for v, row0, rows in _round_robin_spans([v for v, _ in vols], 1):
            yield v, row0 * span

    dispatcher = _Dispatcher(ReedSolomon(backend=backend), device=device,
                             encoders=encoders)
    # lint: thread-ok(per-pass reader pool; work items are explicit, no ambient request state)
    pool = ThreadPoolExecutor(max_workers=max(1, readers),
                              thread_name_prefix="fleet-read")
    pipe = TaggedPipeline(depth=depth)
    gen = gen_spans()
    inflight: deque = deque()
    per_batch = len(members)
    prefetch = max(readers, 2 * per_batch)
    root = trace.span("fleet.rebuild", volumes=len(members),
                      backend=backend)
    root.__enter__()
    token = root.token()

    def fill() -> None:
        while len(inflight) < prefetch:
            nxt = next(gen, None)
            if nxt is None:
                break
            v, offset = nxt
            inflight.append((v, offset, pool.submit(
                _read_present_span, v.base, present, v.dat_size,
                offset, span, token)))
            FleetReaderQueueGauge.inc()  # delta: concurrent-safe sum

    def flush(pack) -> None:
        with _StageTimer("dispatch", batch=len(pack)):
            handle = dispatcher.reconstruct(present, missing,
                                            [a for _, _, a in pack])
        FleetDispatchBatchHistogram.observe(len(pack))
        FleetDispatchedBytesCounter.inc(
            float(sum(a.nbytes for _, _, a in pack)))
        pipe.submit(handle, [
            (v.tag, functools.partial(_write_rebuilt_span, v.base,
                                      missing,
                                      min(span, v.dat_size - offset)))
            for v, offset, _ in pack])

    try:
        fill()
        pack = []
        while inflight:
            item = inflight.popleft()
            FleetReaderQueueGauge.dec()
            pack.append((item[0], item[1], item[2].result()))
            fill()
            if len(pack) >= per_batch or not inflight:
                flush(pack)
                pack = []
    finally:
        FleetReaderQueueGauge.dec(len(inflight))  # error path leftovers
        pool.shutdown(wait=True)
        try:
            pipe.drain()  # may re-raise the latched pipeline error
        finally:
            dispatcher.close()
            root.__exit__(None, None, None)


# --- fleet verify ------------------------------------------------------------

@dataclass
class VerifyResult:
    """Outcome of verifying one volume's EC files.

    parity_mismatch maps a parity shard id (10..13) to its count of
    bytes that differ from the re-encoded parity; first_mismatch holds
    the first differing shard offset per shard. `missing` lists shard
    files absent on disk — those are known damage (the rebuild path's
    job), not verification subjects. A volume with any data shard
    missing cannot be re-encoded and is reported with verified=False.
    """

    parity_mismatch: Dict[int, int] = field(default_factory=dict)
    first_mismatch: Dict[int, int] = field(default_factory=dict)
    missing: List[int] = field(default_factory=list)
    parity_checked: List[int] = field(default_factory=list)
    bytes_verified: int = 0
    spans: int = 0
    verified: bool = True

    @property
    def clean(self) -> bool:
        return self.verified and not self.parity_mismatch \
            and not self.missing


def fleet_verify_ec_files(base_names: Sequence[str], backend: str = "auto",
                          chunk: Optional[int] = None,
                          readers: int = FLEET_READERS,
                          depth: int = FLEET_DEPTH,
                          encoders: int = FLEET_ENCODERS,
                          device=None,
                          throttler=None) -> Dict[str, "VerifyResult"]:
    """Verify EC stripe consistency for MANY volumes in one fused pass.

    The scrub scanner's compute path: data shards are re-encoded
    through the same fleet dispatcher as `fleet_write_ec_files` —
    spans from all volumes fuse into shared [B, 10, span] RS
    dispatches — and the recomputed parity is compared byte-for-byte
    against the stored .ec10-13, so verification throughput rides the
    TPU/mesh encode path instead of a host loop. Nothing on disk is
    touched; mismatches are reported per parity shard for the repair
    planner to classify (a corrupt DATA shard surfaces here as all
    four parity shards disagreeing at the same offsets — see
    scrub/planner.py).

    `throttler` (util.throttler.Throttler) paces the read side so a
    background scrub stays inside its IO budget.
    """
    if chunk is None:
        chunk = default_chunk_for(backend)
    results: Dict[str, VerifyResult] = {}
    fleet: List[Tuple[str, int, List[int]]] = []  # (base, size, parity ids)
    for base in base_names:
        r = VerifyResult()
        results[base] = r
        present = [i for i in range(TOTAL_SHARDS)
                   if os.path.exists(shard_file_name(base, i))]
        r.missing = [i for i in range(TOTAL_SHARDS) if i not in present]
        data_present = [i for i in present if i < DATA_SHARDS]
        parity_present = [i for i in present if i >= DATA_SHARDS]
        if len(data_present) < DATA_SHARDS or not parity_present:
            # can't re-encode without every data shard (or compare
            # without any parity): known damage, rebuild's job
            r.verified = False
            continue
        r.parity_checked = parity_present
        shard_size = os.path.getsize(shard_file_name(base, 0))
        fleet.append((base, shard_size, parity_present))
    if not fleet:
        return results
    # span: the per-volume slice of one ~chunk-sized fused dispatch,
    # capped at the largest shard so small fleets don't read (and
    # RS-encode) chunk-sized slabs of zero padding per 100KB shard
    span = max(1, min(chunk // max(1, len(fleet)),
                      max(size for _, size, _ in fleet)))
    vols = [(_VolState(base, size, -(-size // span) if size else 0, tag),
             parity)
            for tag, (base, size, parity) in enumerate(fleet)]

    def gen_spans():
        for v, row0, _rows in _round_robin_spans([v for v, _ in vols], 1):
            yield v, row0 * span

    parity_by_tag = {v.tag: parity for v, parity in vols}
    dispatcher = _Dispatcher(ReedSolomon(backend=backend), device=device,
                             encoders=encoders)
    # lint: thread-ok(per-pass reader pool; work items are explicit, no ambient request state)
    pool = ThreadPoolExecutor(max_workers=max(1, readers),
                              thread_name_prefix="fleet-read")
    pipe = TaggedPipeline(depth=depth)
    gen = gen_spans()
    inflight: deque = deque()
    per_batch = len(fleet)
    prefetch = max(readers, 2 * per_batch)
    root = trace.span("fleet.verify", volumes=len(fleet), backend=backend)
    root.__enter__()
    token = root.token()
    data_present = list(range(DATA_SHARDS))

    def fill() -> None:
        while len(inflight) < prefetch:
            nxt = next(gen, None)
            if nxt is None:
                break
            v, offset = nxt
            if throttler is not None:
                # pace on the read side: one span costs 10 data reads
                # plus the parity reads the compare will issue
                throttler.maybe_slowdown(
                    (DATA_SHARDS + len(parity_by_tag[v.tag])) * span)
            inflight.append((v, offset, pool.submit(
                _read_present_span, v.base, data_present, v.dat_size,
                offset, span, token)))
            FleetReaderQueueGauge.inc()  # delta: concurrent-safe sum

    # parity fds cached per volume for the whole pass: each volume's
    # compares run FIFO on ITS writer lane (single reader per fd), and
    # per-span open/close would cost thousands of syscalls per volume
    # once large fleets shrink the span. Populated INSIDE the
    # try/finally below: an open() racing a concurrent shard delete
    # must still tear down the pools/span and close earlier fds.
    parity_fds: Dict[str, Dict[int, object]] = {}

    def compare(v: _VolState, offset: int, out: np.ndarray) -> None:
        """Runs on v's writer lane: recomputed parity [1, 4, span] (or
        [4, span] from the host pool) vs the stored parity slices."""
        with _StageTimer("verify", vol=os.path.basename(v.base)):
            parity = out[0] if out.ndim == 3 else out
            valid = min(span, v.dat_size - offset)
            r = results[v.base]
            for sid in parity_by_tag[v.tag]:
                f = parity_fds[v.base][sid]
                f.seek(offset)
                stored = f.read(valid)
                stored_arr = np.frombuffer(stored, dtype=np.uint8)
                row = parity[sid - DATA_SHARDS][:len(stored_arr)]
                diff = np.nonzero(row != stored_arr)[0]
                if len(diff):
                    r.parity_mismatch[sid] = \
                        r.parity_mismatch.get(sid, 0) + len(diff)
                    # spans retire in offset order on this volume's
                    # lane, so the first recorded hit is the lowest
                    r.first_mismatch.setdefault(sid, offset + int(diff[0]))
                if len(stored_arr) < valid:
                    # a truncated parity shard is missing bytes the
                    # data shards say should exist: every absent byte
                    # is a mismatch, not a free pass
                    r.parity_mismatch[sid] = \
                        r.parity_mismatch.get(sid, 0) + \
                        (valid - len(stored_arr))
                    r.first_mismatch.setdefault(
                        sid, offset + len(stored_arr))
            r.bytes_verified += DATA_SHARDS * valid
            r.spans += 1

    def flush(pack) -> None:
        with _StageTimer("dispatch", batch=len(pack)):
            handle = dispatcher.encode(
                [a[np.newaxis] for _, _, a in pack])
        FleetDispatchBatchHistogram.observe(len(pack))
        FleetDispatchedBytesCounter.inc(
            float(sum(a.nbytes for _, _, a in pack)))
        pipe.submit(handle, [
            (v.tag, functools.partial(compare, v, offset))
            for v, offset, _ in pack])

    try:
        for v, parity in vols:
            fds = parity_fds[v.base] = {}
            for sid in parity:  # incremental: no fd lost to a partial
                fds[sid] = open(shard_file_name(v.base, sid), "rb")
        fill()
        pack = []
        while inflight:
            item = inflight.popleft()
            FleetReaderQueueGauge.dec()
            pack.append((item[0], item[1], item[2].result()))
            fill()
            if len(pack) >= per_batch or not inflight:
                flush(pack)
                pack = []
    finally:
        FleetReaderQueueGauge.dec(len(inflight))  # error path leftovers
        pool.shutdown(wait=True)
        try:
            pipe.drain()  # may re-raise the latched pipeline error
        finally:
            dispatcher.close()
            for fds in parity_fds.values():
                for f in fds.values():
                    f.close()
            root.__exit__(None, None, None)
    return results
