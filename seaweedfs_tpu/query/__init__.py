"""Query engine: S3-Select-style filter/projection over stored JSON
(reference weed/query/json/query_json.go + server/volume_grpc_query.go)."""

from seaweedfs_tpu.query.json_query import (  # noqa: F401
    Query, filter_json, get_path, query_json_line, query_json_lines,
)
