"""JSON document filter/projection (reference weed/query/json/
query_json.go:17-130, which uses gjson paths; here: stdlib json +
dotted-path lookup).

Documents are newline-delimited JSON (the layout the reference's
volume-server Query RPC scans, volume_grpc_query.go:52). A query is
``Query(field, op, value)``; supported operands mirror filterJson:
``=  !=  <  <=  >  >=  %``  (``%`` is a glob-ish LIKE using fnmatch,
standing in for gjson's pattern match). Numeric comparisons apply when
both sides parse as numbers, string comparison otherwise; an empty op
means "field exists".
"""

from __future__ import annotations

import fnmatch
import json
from typing import Any, Iterator, List, NamedTuple, Optional, Tuple


class Query(NamedTuple):
    field: str
    op: str = ""
    value: str = ""


_MISSING = object()


def get_path(doc: Any, dotted: str):
    """Dotted-path lookup with numeric segments indexing arrays:
    "a.b", "items.0.name". Returns _MISSING when absent."""
    node = doc
    if not dotted:
        return node
    for part in dotted.split("."):
        if isinstance(node, dict):
            if part not in node:
                return _MISSING
            node = node[part]
        elif isinstance(node, list):
            try:
                node = node[int(part)]
            except (ValueError, IndexError):
                return _MISSING
        else:
            return _MISSING
    return node


def _as_number(v) -> Optional[float]:
    if isinstance(v, bool):
        return None
    if isinstance(v, (int, float)):
        return float(v)
    if isinstance(v, str):
        try:
            return float(v)
        except ValueError:
            return None
    return None


def filter_json(doc: Any, query: Query) -> bool:
    """One document against one predicate (reference filterJson)."""
    value = get_path(doc, query.field)
    if value is _MISSING:
        return False
    if not query.op:
        return True  # existence check
    lnum, rnum = _as_number(value), _as_number(query.value)
    if lnum is not None and rnum is not None:
        left, right = lnum, rnum
    else:
        left = value if isinstance(value, str) else json.dumps(value)
        right = query.value
    if query.op == "=":
        return left == right
    if query.op == "!=":
        return left != right
    if query.op == "<":
        return left < right
    if query.op == "<=":
        return left <= right
    if query.op == ">":
        return left > right
    if query.op == ">=":
        return left >= right
    if query.op == "%":
        return fnmatch.fnmatchcase(str(left), str(right))
    raise ValueError(f"unknown operand {query.op!r}")


def query_json_line(line: str, projections: List[str],
                    query: Query) -> Tuple[bool, Optional[dict]]:
    """Filter + project one JSON line (reference QueryJson). With no
    projections the whole document passes through."""
    try:
        doc = json.loads(line)
    except json.JSONDecodeError:
        return False, None
    if not filter_json(doc, query):
        return False, None
    if not projections:
        return True, doc
    out = {}
    for p in projections:
        v = get_path(doc, p)
        if v is not _MISSING:
            out[p] = v
    return True, out


def query_json_lines(data: bytes, projections: List[str],
                     query: Query) -> Iterator[dict]:
    """Scan newline-delimited JSON bytes; yield projected records."""
    for raw in data.splitlines():
        line = raw.decode("utf-8", "replace").strip()
        if not line:
            continue
        passed, rec = query_json_line(line, projections, query)
        if passed:
            yield rec
