"""On-the-fly image resize (reference weed/images/resizing.go:17-52).

Same contract as the reference handler: ``width``/``height`` query
params with ``mode`` in {"" (fit within, preserving aspect), "fit"
(letterbox to exact WxH), "fill" (cover + center-crop to exact WxH)}.
Unsupported/undecodable content falls through untouched, exactly like
the reference returns the original bytes on decode failure.
"""

from __future__ import annotations

import io
from typing import Tuple

_FORMATS = {"image/jpeg": "JPEG", "image/png": "PNG", "image/gif": "GIF",
            "image/webp": "WEBP"}


def resized(data: bytes, mime: str, width: int = 0, height: int = 0,
            mode: str = "") -> Tuple[bytes, int, int]:
    """Return (bytes, w, h); original data when no resize applies."""
    if (width <= 0 and height <= 0) or mime not in _FORMATS:
        return data, 0, 0
    try:
        from PIL import Image
    except ImportError:  # image support not in this deployment
        return data, 0, 0
    try:
        img = Image.open(io.BytesIO(data))
        img.load()
    # lint: swallow-ok(unparseable image served as stored, undimensioned)
    except Exception:
        return data, 0, 0
    ow, oh = img.size
    w, h = width or ow, height or oh

    def transform(frame):
        if mode == "fit":
            # letterbox: scale to fit inside, pad to exact WxH
            scaled = frame.copy()
            scaled.thumbnail((w, h))
            canvas = Image.new(frame.mode, (w, h))
            canvas.paste(scaled, ((w - scaled.width) // 2,
                                  (h - scaled.height) // 2))
            return canvas
        if mode == "fill":
            # cover: scale so both dims reach the target, center-crop
            fw, fh = frame.size
            scale = max(w / fw, h / fh)
            scaled = frame.resize((max(1, round(fw * scale)),
                                   max(1, round(fh * scale))))
            left = (scaled.width - w) // 2
            top = (scaled.height - h) // 2
            return scaled.crop((left, top, left + w, top + h))
        # default: fit within the box preserving aspect ratio
        out = frame.copy()
        out.thumbnail((w, h))
        return out

    out = transform(img)
    buf = io.BytesIO()
    fmt = _FORMATS[mime]
    if fmt == "JPEG" and out.mode not in ("RGB", "L"):
        out = out.convert("RGB")
    if fmt == "GIF" and getattr(img, "n_frames", 1) > 1:
        # animated GIF: apply the SAME transform to every frame, keep
        # the animation (the reference resizes frame-by-frame too)
        from PIL import ImageSequence
        frames = [transform(frame.copy())
                  for frame in ImageSequence.Iterator(img)]
        frames[0].save(buf, format="GIF", save_all=True,
                       append_images=frames[1:],
                       duration=img.info.get("duration", 100),
                       loop=img.info.get("loop", 0))
        return buf.getvalue(), frames[0].width, frames[0].height
    out.save(buf, format=fmt)
    return buf.getvalue(), out.width, out.height
