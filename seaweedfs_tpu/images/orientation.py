"""EXIF orientation normalization (reference weed/images/orientation.go).

JPEGs carrying an EXIF Orientation tag are rewritten upright before
serving/resizing, so downstream consumers never see rotated pixels.
Anything undecodable passes through untouched.
"""

from __future__ import annotations

import io

# EXIF orientation -> (rotate degrees CCW, mirror horizontally first)
_ORIENT = {
    2: (0, True),
    3: (180, False),
    4: (180, True),
    5: (270, True),
    6: (270, False),
    7: (90, True),
    8: (90, False),
}


def fix_orientation(data: bytes, mime: str = "image/jpeg") -> bytes:
    if mime != "image/jpeg":
        return data
    try:
        from PIL import Image
    except ImportError:
        return data
    try:
        img = Image.open(io.BytesIO(data))
        exif = img.getexif()
        orientation = exif.get(274, 1)  # 274 = Orientation tag
        if orientation not in _ORIENT:
            return data
        degrees, mirror = _ORIENT[orientation]
        out = img
        if mirror:
            from PIL import ImageOps
            out = ImageOps.mirror(out)
        if degrees:
            out = out.rotate(degrees, expand=True)
        exif[274] = 1  # now upright
        buf = io.BytesIO()
        out.save(buf, format="JPEG", exif=exif.tobytes())
        return buf.getvalue()
    except Exception:
        return data
