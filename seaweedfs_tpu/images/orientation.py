"""EXIF orientation normalization (reference weed/images/orientation.go).

JPEGs carrying an EXIF Orientation tag are rewritten upright before
serving/resizing, so downstream consumers never see rotated pixels.
Anything undecodable passes through untouched.
"""

from __future__ import annotations

import io


def fix_orientation(data: bytes, mime: str = "image/jpeg") -> bytes:
    if mime != "image/jpeg":
        return data
    try:
        from PIL import Image, ImageOps
    except ImportError:
        return data
    try:
        img = Image.open(io.BytesIO(data))
        orientation = img.getexif().get(274, 1)  # 274 = Orientation
        if orientation not in range(2, 9):
            return data  # upright or corrupt tag: never re-encode
        # exif_transpose implements the full 8-state orientation table
        # (incl. the transpose/transverse cases 5 and 7) and clears the
        # tag on the result
        out = ImageOps.exif_transpose(img)
        buf = io.BytesIO()
        out.save(buf, format="JPEG", exif=out.getexif().tobytes())
        return buf.getvalue()
    # lint: swallow-ok(unparseable/untransposable image served as stored)
    except Exception:
        return data
