"""Image post-processing on the volume read path
(reference weed/images/resizing.go + orientation.go, hooked at
server/volume_server_handlers_read.go:219-243)."""

from seaweedfs_tpu.images.resizing import resized  # noqa: F401
from seaweedfs_tpu.images.orientation import fix_orientation  # noqa: F401
