"""LifecycleEngine: the master-side leader-only daemon around policy.py.

One pass, every `-lifecycle.intervalSeconds`:

  1. build VolumeViews from the topology (normal volumes = HOT tier,
     EC volumes = WARM tier) joined with the heartbeat heat map
     (Topology.cluster_heat);
  2. reconcile the engine's state records against what the cluster
     actually looks like (operators and failovers move volumes too);
  3. run the pure planner under the cluster-wide in-flight cap;
  4. execute — or, under `-lifecycle.dryRun`, log and ledger every
     decision without acting.

Execution rides the admin shell rather than re-implementing the
crash-safe orderings: encodes GROUP into one `ec.encode
-volumeId=a,b,c` per pass (the server fuses the whole group's chunks
into shared RS dispatches — the PR 1 fleet), decodes run `ec.decode`
(VolumeEcShardsToVolume + shard cleanup), and COLD moves ride
`volume.tier.upload` / `volume.tier.download`. Transitions execute
serially on the engine thread; `max_inflight` therefore bounds how
much of the cluster can be mid-transition (writes frozen, shards in
motion) per pass, and a byte-budget Throttler paces transition
admission by volume size (`-lifecycle.throttleMBps`), so a cold
cluster never converts itself at full disk speed.

Zero-cost-disabled contract: a master without `-lifecycle` constructs
no engine at all (MasterServer.lifecycle is None). A constructed
engine spawns nothing until start(), and its loop acts only while
this master is the raft leader.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from seaweedfs_tpu.lifecycle.policy import (COLD, HOT, STATES, WARM,
                                            LifecycleConfig, Transition,
                                            VolState, VolumeView,
                                            plan_transitions,
                                            reconcile_states)
from seaweedfs_tpu.stats import trace
from seaweedfs_tpu.util import wlog
from seaweedfs_tpu.util.throttler import Throttler

log = wlog.logger("lifecycle")

DECISION_RING = 64      # recent decisions kept for /status + dry-run
RETRY_BACKOFF_PASSES = 4   # passes a failed vid sits out before retry


class LifecycleEngine:
    def __init__(self, master, cfg: LifecycleConfig):
        self.master = master
        self.cfg = cfg.validate()
        self.states: Dict[int, VolState] = {}
        self.paused = False
        self._thread: Optional[threading.Thread] = None
        self._wake = threading.Event()
        self._stopping = False
        self._lock = threading.Lock()      # states/forced/decisions
        self._forced: List[Transition] = []  # guarded_by(self._lock)
        self._decisions: List[dict] = []  # guarded_by(self._lock)   ring, newest last
        self._failed_until: Dict[int, int] = {}  # vid -> pass number
        # last-known HOT size per vid: heartbeats carry no size for EC
        # shards, so WARM/COLD views (and therefore the byte budget and
        # bytes-moved ledger for decode/offload/download) remember the
        # volume's size from its HOT era
        self._sizes: Dict[int, int] = {}
        self._pass_no = 0
        self._throttler = Throttler(cfg.throttle_mbps,
                                    burst_s=cfg.interval_s)
        self.transitions_ok = 0
        self.transitions_err = 0

    # -- lifecycle of the lifecycle -----------------------------------------

    def start(self) -> None:
        # lint: thread-ok(leader-only policy cron daemon; no request context)
        self._thread = threading.Thread(
            target=self._loop, name="master-lifecycle", daemon=True)
        self._thread.start()
        log.info("lifecycle engine started (interval=%.0fs dry_run=%s "
                 "cool<=%g warm>=%g cap=%d)",
                 self.cfg.interval_s, self.cfg.dry_run,
                 self.cfg.cool_threshold, self.cfg.warm_threshold,
                 self.cfg.max_inflight)

    def stop(self) -> None:
        self._stopping = True
        self._wake.set()

    def run_pass_now(self) -> None:
        """Test/ops hook: trigger one policy pass immediately."""
        self._wake.set()

    # -- control plane --------------------------------------------------------

    def pause(self) -> None:
        self.paused = True

    def resume(self) -> None:
        self.paused = False

    def force(self, vid: int, target: str) -> str:
        """Queue one operator-forced transition (volume.lifecycle
        -force): bypasses thresholds and dwell, still executes on the
        engine thread under the same serialized executor, and still
        honors dry-run (a dry-run engine reports what it WOULD do)."""
        target = target.lower()
        if target not in STATES:
            raise ValueError(f"unknown target state {target!r} "
                             f"(want one of {', '.join(STATES)})")
        with self._lock:
            st = self.states.get(vid)
        if st is None:
            raise ValueError(f"volume {vid} is not tracked (no "
                             "heartbeat holder yet?)")
        kind = {(HOT, WARM): "encode", (WARM, HOT): "decode",
                (WARM, COLD): "offload", (COLD, WARM): "download",
                (COLD, HOT): "download"}.get((st.state, target))
        if kind is None:
            raise ValueError(
                f"volume {vid}: no single transition {st.state} -> "
                f"{target}")
        if kind == "offload" and not self.cfg.cold_backend:
            raise ValueError(
                "COLD is disabled: no -lifecycle.coldBackend configured")
        t = Transition(vid, kind, WARM if kind == "download" else target,
                       self._sizes.get(vid, 0), "",
                       f"forced by operator ({st.state} -> {target})")
        with self._lock:
            self._forced.append(t)
        self._wake.set()
        return kind

    def status(self) -> dict:
        with self._lock:
            counts = {s: 0 for s in STATES}
            for st in self.states.values():
                counts[st.state] = counts.get(st.state, 0) + 1
            return {
                "enabled": True,
                "dry_run": self.cfg.dry_run,
                "paused": self.paused,
                "is_leader": self.master.raft.is_leader,
                "interval_s": self.cfg.interval_s,
                "passes": self._pass_no,
                "states": counts,
                "queued_forced": len(self._forced),
                "transitions_ok": self.transitions_ok,
                "transitions_err": self.transitions_err,
                "decisions": list(self._decisions),
            }

    # -- the pass -------------------------------------------------------------

    def _loop(self) -> None:
        while not self._stopping:
            self._wake.wait(timeout=self.cfg.interval_s)
            self._wake.clear()
            if self._stopping:
                return
            if not self.master.raft.is_leader:
                continue
            try:
                # encode/offload passes run as the _internal QoS
                # tenant: low fair-share weight on the stores' pools,
                # exempt from admission shed (no-op context when off)
                from seaweedfs_tpu import qos
                with qos.internal_context():
                    self._run_pass()
            except Exception:
                log.exception("lifecycle pass crashed")

    def _run_pass(self) -> None:
        from seaweedfs_tpu.stats.metrics import (
            LifecyclePassSecondsHistogram, LifecycleQueueDepthGauge,
            LifecycleVolumeStatesGauge)
        t0 = time.perf_counter()
        self._pass_no += 1
        now = time.monotonic()
        sp = trace.span("lifecycle.pass", n=self._pass_no) \
            if trace.is_enabled() else trace.NOOP
        with sp:
            views = self._build_views()
            with self._lock:
                self.states = reconcile_states(views, self.states, now)
                forced, self._forced = self._forced, []
                # backoff hygiene: expired entries and vids that left
                # the cluster must not accumulate on a long-lived master
                self._failed_until = {
                    vid: until
                    for vid, until in self._failed_until.items()
                    if until > self._pass_no and vid in views}
                backoff = set(self._failed_until)
            eligible = {vid: v for vid, v in views.items()
                        if vid not in backoff}
            # pause stops the POLICY only: states keep reconciling (so
            # status stays live) and operator-forced transitions still
            # execute — an explicit force is never held hostage
            plan = [] if self.paused else plan_transitions(
                eligible, self.states, self.cfg, now,
                in_flight=len(forced))
            # a forced vid must not ALSO be planned by policy in the
            # same pass (a duplicate would fuse "ec.encode -volumeId=
            # 5,5" and double-record the outcome)
            forced_vids = {t.vid for t in forced}
            plan = [t for t in plan if t.vid not in forced_vids]
            for s in STATES:
                LifecycleVolumeStatesGauge.labels(s).set(float(
                    sum(1 for st in self.states.values()
                        if st.state == s)))
            todo = forced + plan
            LifecycleQueueDepthGauge.set(float(len(todo)))
            if todo:
                self._execute(todo, views)
            LifecycleQueueDepthGauge.set(0.0)
        LifecyclePassSecondsHistogram.observe(time.perf_counter() - t0)

    def _build_views(self) -> Dict[int, VolumeView]:
        """Observed cluster state -> planner views. EC vids report as
        WARM; everything with a normal replica reports HOT (a vid mid-
        conversion holding both counts as HOT until the originals are
        retired — exactly when ec.encode finishes)."""
        topo = self.master.topo
        heat = topo.cluster_heat()
        wall = time.time()
        views: Dict[int, VolumeView] = {}
        for node in topo.nodes():
            for vid, info in node.volumes.items():
                prev = views.get(vid)
                h = heat.get(vid, {})
                age = wall - info.modified_at_second \
                    if info.modified_at_second else 1e18
                if prev is not None and prev.tier == HOT:
                    views[vid] = prev._replace(
                        size=max(prev.size, info.size),
                        file_count=max(prev.file_count, info.file_count),
                        modified_age_s=min(prev.modified_age_s, age))
                else:
                    views[vid] = VolumeView(
                        vid=vid, tier=HOT, size=info.size,
                        file_count=info.file_count,
                        reads_window=h.get("reads_window", 0.0),
                        ewma=h.get("ewma", 0.0),
                        modified_age_s=age,
                        collection=info.collection)
        for vid, vw in views.items():
            if vw.size > 0:
                self._sizes[vid] = vw.size
        for vid in list(topo.ec_locations):
            if vid in views:
                continue       # normal replica wins (mid-conversion)
            h = heat.get(vid, {})
            views[vid] = VolumeView(
                vid=vid, tier=WARM, size=self._sizes.get(vid, 0),
                reads_window=h.get("reads_window", 0.0),
                ewma=h.get("ewma", 0.0),
                collection=self.master.topo.ec_collections.get(vid, ""))
        # size memory tracks the live view set (no unbounded growth)
        for vid in list(self._sizes):
            if vid not in views:
                self._sizes.pop(vid, None)
        return views

    def _typical_size(self) -> int:
        """Median known volume size: the pacing stand-in for volumes
        whose size the heartbeat can't tell us (EC shards carry no
        byte count on the wire)."""
        known = sorted(self._sizes.values())
        return known[len(known) // 2] if known else 0

    # -- execution ------------------------------------------------------------

    def _record(self, t: Transition, outcome: str, detail: str = "") -> None:
        from seaweedfs_tpu.stats.metrics import (
            LifecycleBytesMovedCounter, LifecycleTransitionsCounter)
        LifecycleTransitionsCounter.labels(t.kind, outcome).inc()
        if outcome == "ok" and t.size:
            LifecycleBytesMovedCounter.labels(t.kind).inc(float(t.size))
        with self._lock:
            self._decisions.append({
                "ts": time.time(), "vid": t.vid, "kind": t.kind,
                "target": t.target, "reason": t.reason,
                "outcome": outcome,
                **({"detail": detail[:200]} if detail else {})})
            del self._decisions[:-DECISION_RING]

    def _execute(self, todo: List[Transition],
                 views: Dict[int, VolumeView]) -> None:
        from seaweedfs_tpu.shell import Shell
        if self.cfg.dry_run:
            for t in todo:
                log.info("lifecycle DRY RUN: volume %d %s -> %s (%s)",
                         t.vid, t.kind, t.target, t.reason)
                self._record(t, "dry_run")
            return
        sh = Shell(self.master.url)
        # encodes group into ONE fused ec.encode per pass: the server
        # packs the whole group's chunks into shared RS dispatches
        encodes = [t for t in todo if t.kind == "encode"]
        rest = [t for t in todo if t.kind != "encode"]
        if encodes:
            self._run_group(
                sh, encodes,
                "ec.encode -volumeId=" +
                ",".join(str(t.vid) for t in encodes))
        for t in rest:
            cmd = {
                "decode": f"ec.decode -volumeId={t.vid}",
                "offload": f"volume.tier.upload -volumeId={t.vid} "
                           f"-dest={self.cfg.cold_backend}",
                "download": f"volume.tier.download -volumeId={t.vid}",
            }[t.kind]
            self._run_group(sh, [t], cmd)

    def _run_group(self, sh, group: List[Transition], cmd: str) -> None:
        from seaweedfs_tpu.shell import CommandError
        now = time.monotonic()
        for t in group:
            # admission pacing: the byte budget is spent BEFORE the
            # move, so a burst of cold volumes converts at the
            # configured MB/s, not at disk speed. Heartbeats carry no
            # size for EC shards, so a WARM/COLD volume whose HOT era
            # predates this master (restart) paces at the median of
            # the sizes we DO know rather than slipping through free.
            self._throttler.maybe_slowdown(t.size or self._typical_size())
        sp = trace.span("lifecycle.transition", kind=group[0].kind,
                        volumes=len(group)) \
            if trace.is_enabled() else trace.NOOP
        with sp:
            try:
                out = sh.run_command(cmd)
            except CommandError as e:
                log.warning("lifecycle %s failed: %s", cmd, e)
                with self._lock:
                    for t in group:
                        self._failed_until[t.vid] = \
                            self._pass_no + RETRY_BACKOFF_PASSES
                    self.transitions_err += len(group)
                for t in group:
                    self._record(t, "error", str(e))
                return
        dt = time.monotonic() - now
        log.info("lifecycle: %s done in %.1fs (%d volume(s))",
                 cmd.split()[0], dt, len(group))
        if out.strip():
            log.info("lifecycle %s:\n%s", cmd.split()[0], out.strip())
        with self._lock:
            for t in group:
                self.states[t.vid] = VolState(t.target, time.monotonic())
                self._failed_until.pop(t.vid, None)
            self.transitions_ok += len(group)
        for t in group:
            self._record(t, "ok")
