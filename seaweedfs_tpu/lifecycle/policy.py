"""The lifecycle state machine, pure over fabricated cluster views.

States
------
HOT   a replicated normal volume: full-speed reads, writable.
WARM  erasure-coded RS(10,4): 1.4x storage instead of Nx, reads a
      touch slower, reconstruction on shard loss (the f4 shape).
COLD  bulk bytes (sealed .dat or EC shards) offloaded to a cloud
      backend through storage/volume_tier; reads become ranged GETs.

Transitions (kind names are the metric labels)
----------------------------------------------
  HOT  -> WARM   "encode"    fused `ec.encode -volumeId=a,b,c`
  WARM -> HOT    "decode"    `ec.decode` (VolumeEcShardsToVolume)
  WARM -> COLD   "offload"   `volume.tier.upload` (EC shards)
  COLD -> WARM   "download"  `volume.tier.download`

Anti-flap contract
------------------
* Hysteresis: a volume cools only when BOTH its instantaneous window
  reads and its decayed EWMA rate sit at or below `cool_threshold`;
  it heats back up only when window reads reach `warm_threshold`
  (validated > cool_threshold). The band between the two thresholds
  is dead: no transition in either direction.
* Dwell: each state has a minimum residence time; a volume that just
  transitioned cannot transition again until its dwell elapses, no
  matter what the thresholds say. A fresh HOT volume's dwell also
  doubles as the write-quiet guard (its modified-age must clear the
  hot dwell before an encode — never EC a volume still being filled).
* Cap: at most `max_inflight` transitions may be planned/running
  cluster-wide at once. Heat-ups (download/decode) outrank cool-downs
  in the plan order — un-cooling is user-facing latency, cooling is
  housekeeping.

Everything here is pure: `reconcile_states` + `plan_transitions` take
plain views/state dicts and a timestamp, return decisions, and touch
no cluster — the house planning-function pattern (plan_scrub_stagger,
plan_volume_balance), so the whole lattice is unit-testable on
fabricated views.
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Optional

HOT = "hot"
WARM = "warm"
COLD = "cold"
STATES = (HOT, WARM, COLD)


class LifecycleConfig(NamedTuple):
    """The `-lifecycle.*` master knobs (defaults match the CLI)."""
    dry_run: bool = False
    interval_s: float = 60.0
    cool_threshold: float = 0.0     # window reads <= this => cool candidate
    warm_threshold: float = 50.0    # window reads >= this => heat back up
    hot_dwell_s: float = 600.0
    warm_dwell_s: float = 600.0
    cold_dwell_s: float = 3600.0
    freeze_s: float = 0.0           # WARM idle this long => COLD (0 = never)
    cold_backend: str = ""          # tier backend name ("" = COLD disabled)
    max_inflight: int = 2
    throttle_mbps: float = 0.0

    def validate(self) -> "LifecycleConfig":
        if self.warm_threshold <= self.cool_threshold:
            raise ValueError(
                f"-lifecycle.warmThreshold ({self.warm_threshold}) must "
                f"exceed -lifecycle.coolThreshold ({self.cool_threshold}) "
                "— without the hysteresis band a volume at the boundary "
                "would flap encode/decode every pass")
        if self.interval_s <= 0:
            raise ValueError("-lifecycle.intervalSeconds must be > 0")
        if self.max_inflight < 1:
            raise ValueError("-lifecycle.maxInflight must be >= 1")
        return self


class VolumeView(NamedTuple):
    """One volume as the planner sees it (fabricated in unit tests,
    built from topology + the heartbeat heat map by the engine)."""
    vid: int
    tier: str                   # observed tier: HOT (normal) or WARM (EC)
    size: int = 0
    file_count: int = 0
    reads_window: float = 0.0   # cluster-summed window reads
    ewma: float = 0.0           # cluster-summed decayed rate
    modified_age_s: float = 1e18   # seconds since last write
    collection: str = ""


class VolState(NamedTuple):
    state: str
    since: float                # monotonic timestamp of state entry


class Transition(NamedTuple):
    vid: int
    kind: str                   # encode | decode | offload | download
    target: str                 # the state the volume lands in
    size: int
    collection: str
    reason: str


# what each kind moves between
KIND_TO_TARGET = {"encode": WARM, "decode": HOT,
                  "offload": COLD, "download": WARM}


def reconcile_states(views: Dict[int, VolumeView],
                     states: Dict[int, VolState],
                     now: float) -> Dict[int, VolState]:
    """Fold the observed topology into the engine's state records.

    The heartbeat view is authoritative for HOT-vs-WARM (an operator's
    manual ec.encode, a master failover, a crashed transition — all
    converge here); COLD is engine memory layered on top, because a
    tier-offloaded volume is indistinguishable from WARM in the
    heartbeat. A COLD record therefore survives only while the
    observed tier still matches WARM's wire shape; after a master
    restart COLD volumes re-enter as WARM and the idle-freeze rule
    re-offloads them — which is why `volume.tier.upload` must be
    idempotent (already-tiered holders skip cleanly). Vids that left
    the cluster drop out; new vids enter in their observed tier with
    dwell starting now."""
    out: Dict[int, VolState] = {}
    for vid, view in views.items():
        prev = states.get(vid)
        if prev is None:
            out[vid] = VolState(view.tier, now)
        elif prev.state == COLD and view.tier == WARM:
            out[vid] = prev            # COLD rides on the WARM wire shape
        elif prev.state != view.tier:
            out[vid] = VolState(view.tier, now)   # external transition
        else:
            out[vid] = prev
        # sanity: a view tier the machine doesn't know resets to HOT
        if out[vid].state not in STATES:
            out[vid] = VolState(HOT, now)
    return out


def _dwell(cfg: LifecycleConfig, state: str) -> float:
    return {HOT: cfg.hot_dwell_s, WARM: cfg.warm_dwell_s,
            COLD: cfg.cold_dwell_s}[state]


def _classify(view: VolumeView, st: VolState, cfg: LifecycleConfig,
              now: float) -> Optional[Transition]:
    """The per-volume decision. Returns None when the volume should
    stay put (in the hysteresis band, inside its dwell, or simply
    content where it is)."""
    dwelt = now - st.since
    if dwelt < _dwell(cfg, st.state):
        return None
    cold_enough = (view.reads_window <= cfg.cool_threshold
                   and view.ewma <= cfg.cool_threshold)
    hot_enough = view.reads_window >= cfg.warm_threshold
    if st.state == HOT:
        # quiet guard: never EC a volume still taking writes, and
        # never bother with an empty one (a freshly-grown volume's
        # .dat is just a superblock — file_count is the honest signal)
        if cold_enough and view.file_count > 0 \
                and view.modified_age_s >= cfg.hot_dwell_s:
            return Transition(
                view.vid, "encode", WARM, view.size, view.collection,
                f"reads_window={view.reads_window:.0f} "
                f"ewma={view.ewma:.2f} <= cool={cfg.cool_threshold:g} "
                f"for dwell>={cfg.hot_dwell_s:g}s")
    elif st.state == WARM:
        if hot_enough:
            return Transition(
                view.vid, "decode", HOT, view.size, view.collection,
                f"reads_window={view.reads_window:.0f} >= "
                f"warm={cfg.warm_threshold:g}")
        if cfg.cold_backend and cfg.freeze_s > 0 \
                and dwelt >= cfg.freeze_s and cold_enough:
            return Transition(
                view.vid, "offload", COLD, view.size, view.collection,
                f"warm+idle {dwelt:.0f}s >= freeze={cfg.freeze_s:g}s")
    elif st.state == COLD:
        if hot_enough:
            return Transition(
                view.vid, "download", WARM, view.size, view.collection,
                f"reads_window={view.reads_window:.0f} >= "
                f"warm={cfg.warm_threshold:g}")
    return None


# plan order: heat-ups are user-facing latency and go first; inside a
# class, hottest (download/decode) or coldest (encode/offload) first
_KIND_RANK = {"download": 0, "decode": 1, "encode": 2, "offload": 3}


def plan_transitions(views: Dict[int, VolumeView],
                     states: Dict[int, VolState],
                     cfg: LifecycleConfig, now: float,
                     in_flight: int = 0) -> List[Transition]:
    """One policy pass: classify every volume, order, and cut to the
    cluster-wide cap. `in_flight` is the count of transitions already
    running (forced or carried over); the plan never pushes the total
    past cfg.max_inflight."""
    planned: List[Transition] = []
    for vid, view in views.items():
        st = states.get(vid)
        if st is None:
            continue
        t = _classify(view, st, cfg, now)
        if t is not None:
            planned.append(t)
    planned.sort(key=lambda t: (
        _KIND_RANK[t.kind],
        -views[t.vid].reads_window if t.kind in ("download", "decode")
        else views[t.vid].reads_window,
        t.vid))
    room = max(0, cfg.max_inflight - in_flight)
    return planned[:room]
