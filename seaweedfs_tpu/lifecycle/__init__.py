"""Heat-driven lifecycle: the policy engine that moves volumes
hot<->warm<->cold on its own (ROADMAP item 3, the decider half of the
heat plane PR 7 shipped).

The f4/Haystack blueprint (SURVEY) is a *lifecycle*: blobs migrate
between a replicated hot store and an erasure-coded warm store as
their access rate decays, automatically. Every mechanism already
exists in this tree — `-heat.track` read telemetry, the fused EC
encode/decode fleets, `storage/volume_tier` cloud offload,
`VolumeEcShardsToVolume` un-cooling, the master's leader-only crons —
and this package is the part that *decides*:

  policy.py   the pure state machine: HOT (replicated) -> WARM (EC)
              -> COLD (tier-offloaded) and back up, with hysteresis
              (separate cool/warm thresholds), per-state minimum dwell
              times, and a cluster-wide in-flight transition cap.
              Pure over fabricated views (the house planning-function
              pattern) — unit-testable without a cluster.
  engine.py   the master-side leader-only daemon: builds views from
              the heartbeat heat map, runs the planner, and executes
              transitions through the admin shell (`ec.encode
              -volumeId=a,b,c` grouped per pass so cools ride ONE
              fused fleet dispatch, `ec.decode`, `volume.tier.*`),
              byte-budget-paced via util/throttler. `-lifecycle.dryRun`
              reports every decision without acting.

Cost discipline (house rule, gated by
tests/test_perf_gates.py::test_lifecycle_disabled_overhead): a master
without `-lifecycle` holds NO engine — zero threads, heartbeats
byte-identical to the pre-lifecycle wire format, and the read path's
only heat branch is the `-heat.track` None check that predates this
package.
"""

from seaweedfs_tpu.lifecycle.policy import (COLD, HOT, WARM,
                                            LifecycleConfig, Transition,
                                            VolumeView, plan_transitions,
                                            reconcile_states)
from seaweedfs_tpu.lifecycle.engine import LifecycleEngine

__all__ = ["LifecycleConfig", "LifecycleEngine", "Transition",
           "VolumeView", "plan_transitions", "reconcile_states",
           "HOT", "WARM", "COLD"]
