"""Server roles: master, volume, filer (reference weed/server)."""
