"""WebDAV server over the filer (reference: weed/server/webdav_server.go,
which wraps golang.org/x/net/webdav; here the DAV verbs are implemented
directly on the filer gRPC/HTTP surface).

Supports the class-2 verb set clients actually use: OPTIONS, PROPFIND
(Depth 0/1), MKCOL, GET/HEAD, PUT, DELETE, MOVE, COPY, and fake
LOCK/UNLOCK (like most non-locking servers, enough for macOS/Windows
clients to mount read-write).
"""

from __future__ import annotations

import threading
import time
import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET
from seaweedfs_tpu.util.http_server import (FastHandler, ServeConfig,
                                            make_http_server)
from typing import List, Optional

import grpc

from seaweedfs_tpu.filer import filechunks
from seaweedfs_tpu.filer import http_client as filer_http
from seaweedfs_tpu.filer.filerstore import join_path, normalize_path, split_path
from seaweedfs_tpu.pb import filer_pb2, filer_stub

DAV_NS = "DAV:"


class WebDavServer:
    def __init__(self, filer_url: str, ip: str = "127.0.0.1",
                 port: int = 7333, root: str = "/",
                 serve: Optional[ServeConfig] = None):
        self.filer_url = filer_url
        self.ip = ip
        self.port = port
        self.root = normalize_path(root)
        self.serve = serve or ServeConfig()
        self._http_server = None
        self._http_thread = None

    @property
    def url(self) -> str:
        return f"{self.ip}:{self.port}"

    def start(self) -> None:
        self._http_server = make_http_server(
            (self.ip, self.port), _make_handler(self),
            role="webdav", serve=self.serve)
        # lint: thread-ok(listener thread; ingress wrappers mint request context)
        self._http_thread = threading.Thread(
            target=self._http_server.serve_forever,
            name=f"webdav-{self.port}", daemon=True)
        self._http_thread.start()

    def stop(self) -> None:
        if self._http_server:
            self._http_server.shutdown()
            self._http_server.server_close()

    # -- filer plumbing -------------------------------------------------------

    @property
    def stub(self):
        return filer_stub(self.filer_url)

    def full_path(self, dav_path: str) -> str:
        return normalize_path(join_path(self.root, dav_path.lstrip("/")))

    def find(self, dav_path: str) -> Optional[filer_pb2.Entry]:
        p = self.full_path(dav_path)
        if p == "/":
            return filer_pb2.Entry(name="/", is_directory=True)
        d, n = split_path(p)
        try:
            return self.stub.LookupDirectoryEntry(
                filer_pb2.LookupDirectoryEntryRequest(
                    directory=d, name=n)).entry
        except grpc.RpcError:
            return None

    def children(self, dav_path: str) -> List[filer_pb2.Entry]:
        try:
            return [r.entry for r in self.stub.ListEntries(
                filer_pb2.ListEntriesRequest(
                    directory=self.full_path(dav_path), limit=10000))]
        except grpc.RpcError:
            return []


def _prop_response(href: str, entry: filer_pb2.Entry) -> ET.Element:
    resp = ET.Element(f"{{{DAV_NS}}}response")
    ET.SubElement(resp, f"{{{DAV_NS}}}href").text = urllib.parse.quote(href)
    propstat = ET.SubElement(resp, f"{{{DAV_NS}}}propstat")
    prop = ET.SubElement(propstat, f"{{{DAV_NS}}}prop")
    ET.SubElement(prop, f"{{{DAV_NS}}}displayname").text = entry.name
    rt = ET.SubElement(prop, f"{{{DAV_NS}}}resourcetype")
    if entry.is_directory:
        ET.SubElement(rt, f"{{{DAV_NS}}}collection")
    else:
        size = filechunks.total_size(entry.chunks)
        ET.SubElement(prop,
                      f"{{{DAV_NS}}}getcontentlength").text = str(size)
        if entry.attributes.mime:
            ET.SubElement(prop, f"{{{DAV_NS}}}getcontenttype").text = \
                entry.attributes.mime
    ET.SubElement(prop, f"{{{DAV_NS}}}getlastmodified").text = \
        time.strftime("%a, %d %b %Y %H:%M:%S GMT",
                      time.gmtime(entry.attributes.mtime or 0))
    ET.SubElement(propstat, f"{{{DAV_NS}}}status").text = \
        "HTTP/1.1 200 OK"
    return resp


def _make_handler(dav: WebDavServer):
    class Handler(FastHandler):
        protocol_version = "HTTP/1.1"
        disable_nagle_algorithm = True  # small replies must not wait on delayed ACKs

        def log_message(self, fmt, *args):
            pass

        def _reply(self, code: int, body: bytes = b"",
                   headers: Optional[dict] = None) -> None:
            self.send_response(code)
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            if self.command != "HEAD" and body:
                self.wfile.write(body)

        def _body(self) -> bytes:
            # framing-aware (Content-Length or chunked), identical on
            # both server models
            return self.read_body()

        def _path(self) -> str:
            return urllib.parse.unquote(
                urllib.parse.urlparse(self.path).path) or "/"

        # -- verbs ------------------------------------------------------------

        def do_OPTIONS(self):
            self._reply(200, headers={
                "DAV": "1,2",
                "Allow": "OPTIONS, PROPFIND, MKCOL, GET, HEAD, PUT, "
                         "DELETE, MOVE, COPY, LOCK, UNLOCK",
                "MS-Author-Via": "DAV"})

        def do_PROPFIND(self):
            self._body()
            path = self._path()
            entry = dav.find(path)
            if entry is None:
                self._reply(404)
                return
            depth = self.headers.get("Depth", "1")
            ms = ET.Element(f"{{{DAV_NS}}}multistatus")
            ms.append(_prop_response(path, entry))
            if entry.is_directory and depth != "0":
                base = path if path.endswith("/") else path + "/"
                for c in dav.children(path):
                    href = base + c.name + ("/" if c.is_directory else "")
                    ms.append(_prop_response(href, c))
            ET.register_namespace("D", DAV_NS)
            body = b'<?xml version="1.0" encoding="utf-8"?>' + \
                ET.tostring(ms)
            self._reply(207, body,
                        headers={"Content-Type":
                                 'application/xml; charset="utf-8"'})

        def do_MKCOL(self):
            path = self._path()
            d, n = split_path(dav.full_path(path))
            if dav.find(path) is not None:
                self._reply(405)
                return
            dav.stub.CreateEntry(filer_pb2.CreateEntryRequest(
                directory=d,
                entry=filer_pb2.Entry(name=n, is_directory=True)))
            self._reply(201)

        def do_GET(self):
            path = self._path()
            entry = dav.find(path)
            if entry is None:
                self._reply(404)
                return
            if entry.is_directory:
                self._reply(405)
                return
            try:
                code, data, headers = filer_http.get(
                    dav.filer_url, dav.full_path(path),
                    self.headers.get("Range"))
            except urllib.error.HTTPError as e:
                self._reply(e.code)
                return
            extra = {h: headers[h] for h in
                     ("Content-Range", "Content-Type", "ETag")
                     if h in headers}
            self._reply(code, data, headers=extra)

        def do_HEAD(self):
            # metadata only — never pull the body for a HEAD
            path = self._path()
            entry = dav.find(path)
            if entry is None:
                self._reply(404)
                return
            if entry.is_directory:
                self._reply(405)
                return
            self.send_response(200)
            self.send_header("Content-Length",
                             str(filechunks.total_size(entry.chunks)))
            self.send_header("Content-Type", entry.attributes.mime
                             or "application/octet-stream")
            self.end_headers()

        def do_PUT(self):
            path = self._path()
            data = self._body()
            try:
                filer_http.put(dav.filer_url, dav.full_path(path), data,
                               mime=self.headers.get("Content-Type") or "")
            except urllib.error.HTTPError as e:
                self._reply(e.code)
                return
            self._reply(201)

        def do_DELETE(self):
            path = self._path()
            if dav.find(path) is None:
                self._reply(404)
                return
            d, n = split_path(dav.full_path(path))
            dav.stub.DeleteEntry(filer_pb2.DeleteEntryRequest(
                directory=d, name=n, is_delete_data=True,
                is_recursive=True, ignore_recursive_error=True))
            self._reply(204)

        def _destination(self) -> Optional[str]:
            dst = self.headers.get("Destination", "")
            if not dst:
                return None
            u = urllib.parse.urlparse(dst)
            return urllib.parse.unquote(u.path) or "/"

        def do_MOVE(self):
            src, dst = self._path(), self._destination()
            if dst is None:
                self._reply(400)
                return
            if dav.find(src) is None:
                self._reply(404)
                return
            overwrote = dav.find(dst) is not None
            if overwrote and self.headers.get("Overwrite", "T") == "F":
                self._reply(412)
                return
            sd, sn = split_path(dav.full_path(src))
            dd, dn = split_path(dav.full_path(dst))
            dav.stub.AtomicRenameEntry(filer_pb2.AtomicRenameEntryRequest(
                old_directory=sd, old_name=sn,
                new_directory=dd, new_name=dn))
            self._reply(204 if overwrote else 201)

        def do_COPY(self):
            src, dst = self._path(), self._destination()
            if dst is None:
                self._reply(400)
                return
            entry = dav.find(src)
            if entry is None:
                self._reply(404)
                return
            if entry.is_directory:
                self._reply(501)  # collection COPY not supported
                return
            overwrote = dav.find(dst) is not None
            if overwrote and self.headers.get("Overwrite", "T") == "F":
                self._reply(412)
                return
            _, data, _ = filer_http.get(dav.filer_url,
                                        dav.full_path(src))
            filer_http.put(dav.filer_url, dav.full_path(dst), data,
                           mime=entry.attributes.mime or "")
            self._reply(204 if overwrote else 201)

        def do_LOCK(self):
            # fake lock token, like read-write servers without real
            # locking; body echoes an activelock so clients proceed
            self._body()
            token = f"opaquelocktoken:{time.time_ns():x}"
            body = (
                '<?xml version="1.0" encoding="utf-8"?>'
                '<D:prop xmlns:D="DAV:"><D:lockdiscovery><D:activelock>'
                '<D:locktype><D:write/></D:locktype>'
                '<D:lockscope><D:exclusive/></D:lockscope>'
                f'<D:locktoken><D:href>{token}</D:href></D:locktoken>'
                '</D:activelock></D:lockdiscovery></D:prop>').encode()
            self._reply(200, body, headers={
                "Lock-Token": f"<{token}>",
                "Content-Type": 'application/xml; charset="utf-8"'})

        def do_UNLOCK(self):
            self._reply(204)

    from seaweedfs_tpu.stats.metrics import instrument_http_handler
    return instrument_http_handler(Handler, "webdav")
