"""Raft consensus for multi-master HA.

The reference embeds the chrislusf/raft library
(weed/server/raft_server.go:21-160): one leader among an odd number of
masters, elected by vote, replicating a small control-plane log (max
volume id, file-id sequence snapshots) and redirecting writes to the
leader. This module is a compact, self-contained Raft with the same
role here:

- roles follower/candidate/leader, randomized election timeouts,
  leader heartbeats (AppendEntries) over the master's own gRPC server,
  replicated to all peers in parallel so one hung peer cannot starve
  the live ones of heartbeats;
- persistent state under the master's -mdir (reference: raft log dir =
  -mdir, command/master.go:118), split per Raft's durability rules:
  `raft.meta.json` (term + vote, fsync'd BEFORE any vote/term reply
  leaves the node — the double-vote window a crash must never reopen),
  `raft.wal` (append-only entry log: JSON records, fsync per append
  batch, replayed on load; torn tails are cut), and `raft.snap.json`
  (state-machine snapshot + log base, written at compaction once the
  log exceeds LOG_CAP, after which the WAL is rewritten to the tail).
  Followers that fall behind the compacted base receive the snapshot
  piggybacked on AppendEntries;
- ``propose()`` replicates a command to a quorum before applying it to
  the state machine on every node (commands: max volume id bumps and
  sequence watermarks — the same state the reference snapshots).

A single-node configuration (no peers) short-circuits to permanent
leadership so the single-master deployment keeps zero overhead.
"""

from __future__ import annotations

import concurrent.futures
import json
import os
import random
import threading
import time
from typing import Callable, Dict, List, Optional

import grpc

from seaweedfs_tpu.pb import raft_pb2, raft_stub
from seaweedfs_tpu.util import wlog

log = wlog.logger("raft")

FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"


class NotLeader(Exception):
    def __init__(self, leader: Optional[str]):
        super().__init__(f"not the raft leader; leader is {leader or '?'}")
        self.leader = leader


class RaftNode:
    """One master's raft participant.

    apply(command: dict, term: int) is invoked, in log order, exactly
    once per committed entry on every live node (and again on restart
    replay — commands must be idempotent, which max/watermark bumps
    are); the entry's term lets the state machine tell the sitting
    leader's own proposals from replayed prior-term entries.
    snapshot_fn() returns the full state-machine state as a JSON-able
    dict; restore_fn(state) reinstalls it (used for log compaction and
    for catching up far-behind followers).
    """

    LOG_CAP = 1024  # compact the log into a snapshot beyond this

    def __init__(self, my_url: str, peer_urls: List[str],
                 meta_dir: Optional[str],
                 apply: Callable[[dict, int], None],
                 snapshot_fn: Optional[Callable[[], dict]] = None,
                 restore_fn: Optional[Callable[[dict], None]] = None,
                 election_timeout: float = 0.5,
                 heartbeat_interval: float = 0.1):
        self.my_url = my_url
        self.peers = [p for p in peer_urls if p and p != my_url]
        self.meta_dir = meta_dir
        self.apply = apply
        self.snapshot_fn = snapshot_fn or (lambda: {})
        self.restore_fn = restore_fn or (lambda state: None)
        self.election_timeout = election_timeout
        self.heartbeat_interval = heartbeat_interval

        self._lock = threading.RLock()
        self.state = FOLLOWER if self.peers else LEADER
        self.current_term = 0
        self.voted_for: Optional[str] = None
        # log[0] is the compaction sentinel: (base index, base term);
        # real entries follow. Initially (0, 0) = empty log.
        self.log: List[dict] = [{"index": 0, "term": 0, "command": None}]
        self.snapshot_state: dict = {}
        self.commit_index = 0
        self.last_applied = 0
        self.leader_url: Optional[str] = self.my_url if not self.peers \
            else None
        self._next_index: Dict[str, int] = {}
        self._match_index: Dict[str, int] = {}
        self._last_heard = time.monotonic()
        self._commit_cv = threading.Condition(self._lock)
        # the ticker polls lock-free; stop() writes under the lock
        self._stopped = False  # guarded_by(self._lock, writes)
        self._threads: List[threading.Thread] = []
        self._inflight: set = set()  # peers with a replicate RPC in flight
        # lint: thread-ok(consensus RPC fan-out pool; raft owns its own timeouts)
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=max(1, len(self.peers)),
            thread_name_prefix="raft-repl") if self.peers else None
        self._wal_file = None  # guarded_by(self._lock)
        self._wal_epoch = 0
        self._load_state()

    # -- log index helpers (base-relative) ------------------------------------

    def _base(self) -> int:
        return self.log[0]["index"]

    def _last_index(self) -> int:
        return self.log[-1]["index"]

    def _get(self, index: int) -> dict:
        return self.log[index - self._base()]

    # -- persistence ---------------------------------------------------------
    #
    # Three files under -mdir (see module docstring): meta (term+vote,
    # fsync'd before any reply that depends on it), an append-only WAL
    # of entry/truncate records, and the compaction snapshot.

    def _path(self, name: str) -> Optional[str]:
        return os.path.join(self.meta_dir, name) if self.meta_dir else None

    @staticmethod
    def _fsync_replace(path: str, payload: str) -> None:
        """Write-fsync-rename-fsyncdir: the file is durably either the
        old or the new content, never torn."""
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        dirfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
        try:
            os.fsync(dirfd)
        finally:
            os.close(dirfd)

    def _save_meta(self) -> None:
        """Persist term + vote. MUST complete before the vote/term is
        acted on: a crash after granting a vote but before persisting
        it lets the node vote twice in the term (Raft §5.2 persistence
        rules) — exactly what the fsync closes."""
        p = self._path("raft.meta.json")
        if not p:
            return
        os.makedirs(self.meta_dir, exist_ok=True)
        self._fsync_replace(p, json.dumps(
            {"term": self.current_term, "voted_for": self.voted_for}))

    def _wal_path(self, epoch: Optional[int] = None) -> Optional[str]:  # requires(self._lock)
        """The WAL is generation-stamped: the snapshot records which
        epoch it pairs with, so a crash between writing the snapshot
        and cleaning the previous WAL can never replay STALE entries
        against the new base (pre-truncate suffixes would resurrect
        and evict their committed replacements — review round 3)."""
        e = self._wal_epoch if epoch is None else epoch
        return self._path(f"raft.wal.{e}")

    def _wal_handle(self):  # requires(self._lock)
        if self._wal_file is None and self.meta_dir:
            os.makedirs(self.meta_dir, exist_ok=True)
            self._wal_file = open(self._wal_path(), "ab")
        return self._wal_file

    def _wal_record(self, rec: dict) -> None:
        f = self._wal_handle()
        if f is None:
            return
        f.write(json.dumps(rec).encode() + b"\n")
        f.flush()
        os.fsync(f.fileno())

    def _wal_append(self, entries: List[dict]) -> None:
        f = self._wal_handle()
        if f is None:
            return
        for e in entries:
            f.write(json.dumps({"op": "append", "entry": e}).encode()
                    + b"\n")
        f.flush()
        os.fsync(f.fileno())

    def _wal_truncate_mark(self, from_index: int) -> None:
        self._wal_record({"op": "truncate", "from": from_index})

    def _save_snapshot(self) -> None:  # requires(self._lock)
        """Write (new-epoch WAL tail, then snapshot naming it, then
        remove the old WAL). The snapshot write is the commit point:
        crash before it keeps the old (snap, WAL) pair intact; crash
        after it loads the new pair — never a mix."""
        p = self._path("raft.snap.json")
        if not p:
            return
        os.makedirs(self.meta_dir, exist_ok=True)
        old_epoch = self._wal_epoch
        new_epoch = old_epoch + 1
        if self._wal_file is not None:
            self._wal_file.close()
            self._wal_file = None  # guarded_by(self._lock)
        payload = "".join(
            json.dumps({"op": "append", "entry": e}) + "\n"
            for e in self.log[1:])
        self._fsync_replace(self._wal_path(new_epoch), payload)
        self._fsync_replace(p, json.dumps(
            {"base_index": self._base(), "base_term": self.log[0]["term"],
             "snapshot": self.snapshot_state,
             "commit_index": self.commit_index,
             "wal_epoch": new_epoch}))
        self._wal_epoch = new_epoch
        old = self._wal_path(old_epoch)
        if os.path.exists(old):
            os.remove(old)

    def _load_state(self) -> None:  # requires(self._lock)
        if not self.meta_dir:
            return
        legacy = self._path("raft.json")
        if os.path.exists(legacy):
            # the legacy file alone gates migration: its removal is the
            # commit point, so a crash mid-migration just re-runs it
            # (idempotent — it overwrites all three new files)
            self._load_legacy(legacy)
            return
        snap_p = self._path("raft.snap.json")
        if os.path.exists(snap_p):
            with open(snap_p) as f:
                st = json.load(f)
            self.log = [{"index": st["base_index"],
                         "term": st["base_term"], "command": None}]
            self.snapshot_state = st.get("snapshot") or {}
            self.commit_index = st.get("commit_index", 0)
            self._wal_epoch = st.get("wal_epoch", 0)
        # drop WAL generations other than the snapshot's (a crash can
        # strand the next epoch's pre-commit file)
        if self.meta_dir and os.path.isdir(self.meta_dir):
            for name in os.listdir(self.meta_dir):
                if name.startswith("raft.wal.") and \
                        name != f"raft.wal.{self._wal_epoch}":
                    os.remove(os.path.join(self.meta_dir, name))
        wal_p = self._wal_path()
        if os.path.exists(wal_p):
            good = 0   # byte offset of the last intact record
            with open(wal_p, "rb") as f:
                for line in f:
                    if not line.endswith(b"\n"):
                        # record+newline go down in one fsynced write,
                        # so a newline-less tail was never acked — and
                        # keeping it would glue the next append onto
                        # its line, losing BOTH on the following replay
                        break
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        break  # torn tail from a crash mid-append
                    good += len(line)
                    if rec["op"] == "append":
                        e = rec["entry"]
                        if e["index"] <= self._last_index():
                            continue  # idempotent replay
                        self.log.append(e)
                    elif rec["op"] == "truncate":
                        cut = rec["from"] - self._base()
                        if 1 <= cut <= len(self.log):
                            del self.log[cut:]
            if good != os.path.getsize(wal_p):
                # cut the torn bytes NOW, before reopening for append —
                # otherwise later appends land beyond garbage that every
                # future replay stops at
                with open(wal_p, "r+b") as f:
                    f.truncate(good)
        meta_p = self._path("raft.meta.json")
        if os.path.exists(meta_p):
            with open(meta_p) as f:
                st = json.load(f)
            self.current_term = st.get("term", 0)
            self.voted_for = st.get("voted_for")
        self._finish_load()

    def _load_legacy(self, path: str) -> None:
        """Upgrade path from the round-2 single-file raft.json."""
        with open(path) as f:
            st = json.load(f)
        self.current_term = st.get("term", 0)
        self.voted_for = st.get("voted_for")
        self.log = st.get("log") or self.log
        self.snapshot_state = st.get("snapshot") or {}
        self.commit_index = st.get("commit_index", 0)
        self._save_meta()
        self._save_snapshot()  # also rewrites the WAL with the tail
        os.remove(path)
        self._finish_load()

    def _finish_load(self) -> None:
        base = self._base()
        if self.snapshot_state or base:
            self.restore_fn(self.snapshot_state)
        self.last_applied = base
        self.commit_index = max(self.commit_index, base)
        self.commit_index = min(self.commit_index, self._last_index())
        if not self.peers:
            # single-node: everything durably logged WAS committed (no
            # quorum to re-learn it from after a restart)
            self.commit_index = self._last_index()
        # replay committed entries beyond the snapshot base
        self._apply_committed()

    def _maybe_compact(self) -> None:
        """Fold applied entries into the snapshot once the log is long
        (caller holds the lock). Keeps the WAL and replay cost bounded."""
        if len(self.log) <= self.LOG_CAP or \
                self.last_applied <= self._base():
            return
        cut = self.last_applied
        sentinel = dict(self._get(cut))
        sentinel["command"] = None
        self.snapshot_state = self.snapshot_fn()
        self.log = [sentinel] + self.log[cut - self._base() + 1:]
        self._save_snapshot()
        log.info("%s: compacted raft log to base %d (%d entries kept)",
                 self.my_url, cut, len(self.log) - 1)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if not self.peers:
            return  # single master: no timers needed
        # lint: thread-ok(election/heartbeat daemon; no request context)
        t = threading.Thread(target=self._ticker, name="raft-ticker",
                             daemon=True)
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            self._commit_cv.notify_all()
        for t in self._threads:
            t.join(timeout=2)
        if self._pool is not None:
            self._pool.shutdown(wait=False)
        with self._lock:
            if self._wal_file is not None:
                self._wal_file.close()
                self._wal_file = None  # guarded_by(self._lock)

    # -- role accessors ------------------------------------------------------

    @property
    def is_leader(self) -> bool:
        return self.state == LEADER

    def leader(self) -> Optional[str]:
        return self.leader_url

    # -- timers --------------------------------------------------------------

    def _ticker(self) -> None:
        while not self._stopped:
            with self._lock:
                state = self.state
            if state == LEADER:
                self._broadcast_heartbeat()
                time.sleep(self.heartbeat_interval)
            else:
                timeout = self.election_timeout * (1 + random.random())
                time.sleep(0.02)
                with self._lock:
                    heard = self._last_heard
                if time.monotonic() - heard > timeout:
                    self._run_election()

    # -- election ------------------------------------------------------------

    def _run_election(self) -> None:
        with self._lock:
            self.state = CANDIDATE
            self.current_term += 1
            term = self.current_term
            self.voted_for = self.my_url
            self.leader_url = None
            self._last_heard = time.monotonic()
            last = self.log[-1]
            self._save_meta()
        log.info("%s: starting election for term %d", self.my_url, term)

        def ask(peer):
            try:
                return raft_stub(peer).RequestVote(
                    raft_pb2.VoteRequest(
                        term=term, candidate_id=self.my_url,
                        last_log_index=last["index"],
                        last_log_term=last["term"]),
                    timeout=self.election_timeout)
            except grpc.RpcError:
                return None

        votes = 1
        for resp in self._pool.map(ask, self.peers):
            if resp is None:
                continue
            with self._lock:
                if resp.term > self.current_term:
                    self._become_follower(resp.term, None)
                    return
            if resp.vote_granted:
                votes += 1
        quorum = (len(self.peers) + 1) // 2 + 1
        with self._lock:
            if self.state != CANDIDATE or self.current_term != term:
                return
            if votes >= quorum:
                self.state = LEADER
                self.leader_url = self.my_url
                nxt = self._last_index() + 1
                self._next_index = {p: nxt for p in self.peers}
                self._match_index = {p: 0 for p in self.peers}
                # no-op entry in the new term: Raft only commits
                # prior-term entries indirectly, via a committed entry
                # of the current term (Raft §5.4.2) — without this, a
                # fresh leader would sit on uncommitted predecessors
                entry = {"index": nxt, "term": term, "command": None}
                self.log.append(entry)
                self._wal_append([entry])
                log.info("%s: won election for term %d (%d/%d votes)",
                         self.my_url, term, votes, len(self.peers) + 1)
        if self.is_leader:
            self._broadcast_heartbeat()

    def _become_follower(self, term: int, leader: Optional[str]) -> None:  # requires(self._lock)
        # caller holds self._lock
        if term > self.current_term:
            self.current_term = term
            self.voted_for = None
            self._save_meta()
        if self.state != FOLLOWER:
            log.info("%s: stepping down to follower (term %d, leader %s)",
                     self.my_url, term, leader)
        self.state = FOLLOWER
        if leader:
            self.leader_url = leader
        self._last_heard = time.monotonic()

    # -- replication (leader side) -------------------------------------------

    def _broadcast_heartbeat(self) -> None:
        """Fire-and-track replication to every peer.

        Never blocks on peer RPCs: a black-holed peer used to stretch
        the heartbeat cycle past the followers' election timeouts and
        flap the leadership (round-2 advisory). Instead each peer has
        at most one RPC in flight — a slow peer is simply skipped this
        tick while healthy peers keep their cadence — and commit
        advancement runs from each RPC's completion callback."""
        for p in self.peers:
            with self._lock:
                if p in self._inflight:
                    continue
                self._inflight.add(p)
            fut = self._pool.submit(self._replicate_to, p)
            fut.add_done_callback(
                lambda _f, peer=p: self._replication_done(peer))

    def _replication_done(self, peer: str) -> None:
        with self._lock:
            self._inflight.discard(peer)
        try:
            self._advance_commit()
        except Exception:
            log.exception("advance_commit failed after replicating to %s",
                          peer)

    def _replicate_to(self, peer: str) -> None:
        with self._lock:
            if self.state != LEADER:
                return
            term = self.current_term
            base = self._base()
            nxt = self._next_index.get(peer, self._last_index() + 1)
            snapshot = None
            if nxt <= base:
                # follower is behind the compacted log: piggyback the
                # snapshot (fused InstallSnapshot) and resend everything
                snapshot = (base, self.log[0]["term"],
                            json.dumps(self.snapshot_state))
                nxt = base + 1
            prev = self._get(nxt - 1)
            entries = self.log[nxt - base:]
            commit = self.commit_index
        pb_entries = [raft_pb2.LogEntry(
            index=e["index"], term=e["term"],
            command=json.dumps(e["command"]).encode())
            for e in entries]
        req = raft_pb2.AppendEntriesRequest(
            term=term, leader_id=self.my_url,
            prev_log_index=prev["index"], prev_log_term=prev["term"],
            entries=pb_entries, leader_commit=commit)
        if snapshot is not None:
            req.has_snapshot = True
            req.snapshot_index = snapshot[0]
            req.snapshot_term = snapshot[1]
            req.snapshot_state = snapshot[2].encode()
        try:
            resp = raft_stub(peer).AppendEntries(
                req, timeout=self.election_timeout)
        except grpc.RpcError:
            return
        with self._lock:
            if resp.term > self.current_term:
                self._become_follower(resp.term, None)
                return
            if self.state != LEADER:
                return
            if resp.success:
                self._match_index[peer] = resp.match_index
                self._next_index[peer] = resp.match_index + 1
            else:
                self._next_index[peer] = max(1, nxt - 1)

    def _advance_commit(self) -> None:
        with self._lock:
            if self.state != LEADER:
                return
            quorum = (len(self.peers) + 1) // 2 + 1
            for idx in range(self._last_index(), self.commit_index, -1):
                if idx <= self._base():
                    break
                votes = 1 + sum(1 for p in self.peers
                                if self._match_index.get(p, 0) >= idx)
                if votes >= quorum and \
                        self._get(idx)["term"] == self.current_term:
                    self.commit_index = idx
                    self._apply_committed()
                    self._maybe_compact()
                    self._commit_cv.notify_all()
                    break

    def _apply_committed(self) -> None:
        # caller holds self._lock (or init)
        while self.last_applied < self.commit_index:
            self.last_applied += 1
            entry = self._get(self.last_applied)
            if entry["command"] is not None:
                try:
                    self.apply(entry["command"], entry["term"])
                except Exception:
                    log.exception("raft apply failed for %r",
                                  entry["command"])

    # -- public: propose a command -------------------------------------------

    def propose(self, command: dict, timeout: float = 5.0) -> None:
        """Append to the log and block until the entry commits (quorum
        replicated + applied). Raises NotLeader from followers."""
        if not self.peers:
            # single-node: commit immediately
            with self._lock:
                idx = self._last_index() + 1
                entry = {"index": idx, "term": self.current_term,
                         "command": command}
                self.log.append(entry)
                self._wal_append([entry])  # durable before acking commit
                self.commit_index = idx
                self._apply_committed()
                self._maybe_compact()
            return
        with self._lock:
            if self.state != LEADER:
                raise NotLeader(self.leader_url)
            idx = self._last_index() + 1
            entry = {"index": idx, "term": self.current_term,
                     "command": command}
            self.log.append(entry)
            self._wal_append([entry])
        # push to followers now rather than waiting for the next tick
        self._broadcast_heartbeat()
        deadline = time.monotonic() + timeout
        with self._lock:
            while self.commit_index < idx:
                if self._stopped:
                    raise RuntimeError(
                        "raft node stopped before the command committed")
                if self.state != LEADER:
                    raise NotLeader(self.leader_url)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"raft commit of index {idx} timed out")
                self._commit_cv.wait(timeout=min(remaining, 0.05))

    # -- gRPC service (Raft) ---------------------------------------------------

    def RequestVote(self, request, context):
        with self._lock:
            if request.term < self.current_term:
                return raft_pb2.VoteResponse(term=self.current_term,
                                             vote_granted=False)
            if request.term > self.current_term:
                self._become_follower(request.term, None)
            last = self.log[-1]
            up_to_date = (request.last_log_term, request.last_log_index) >= \
                (last["term"], last["index"])
            grant = up_to_date and self.voted_for in (None,
                                                      request.candidate_id)
            if grant:
                self.voted_for = request.candidate_id
                self._last_heard = time.monotonic()
                # fsync'd BEFORE the reply leaves: a crash may not
                # forget a granted vote (double-vote window)
                self._save_meta()
            return raft_pb2.VoteResponse(term=self.current_term,
                                         vote_granted=grant)

    def AppendEntries(self, request, context):
        with self._lock:
            if request.term < self.current_term:
                return raft_pb2.AppendEntriesResponse(
                    term=self.current_term, success=False, match_index=0)
            self._become_follower(request.term, request.leader_id)
            if request.has_snapshot and \
                    request.snapshot_index > self.commit_index:
                # install the piggybacked snapshot: we're behind the
                # leader's compacted base
                self.snapshot_state = json.loads(
                    request.snapshot_state.decode() or "{}")
                self.restore_fn(self.snapshot_state)
                self.log = [{"index": request.snapshot_index,
                             "term": request.snapshot_term,
                             "command": None}]
                self.commit_index = request.snapshot_index
                self.last_applied = request.snapshot_index
                self._save_snapshot()  # also resets the WAL to the base
            base = self._base()
            # log consistency check
            if request.prev_log_index > self._last_index():
                return raft_pb2.AppendEntriesResponse(
                    term=self.current_term, success=False, match_index=0)
            if request.prev_log_index >= base and \
                    self._get(request.prev_log_index)["term"] != \
                    request.prev_log_term:
                return raft_pb2.AppendEntriesResponse(
                    term=self.current_term, success=False, match_index=0)
            # append / overwrite conflicting suffix (skip entries the
            # snapshot already covers)
            appended: List[dict] = []
            for e in request.entries:
                if e.index <= base:
                    continue
                entry = {"index": e.index, "term": e.term,
                         "command": json.loads(e.command.decode())
                         if e.command else None}
                if e.index <= self._last_index():
                    if self._get(e.index)["term"] != e.term:
                        del self.log[e.index - base:]
                        self._wal_truncate_mark(e.index)
                        self.log.append(entry)
                        appended.append(entry)
                else:
                    self.log.append(entry)
                    appended.append(entry)
            if appended:
                # durable before the success reply: the leader counts
                # this node toward quorum as soon as it answers
                self._wal_append(appended)
            # match what the LEADER sent, not whatever tail this node
            # happens to hold: a stale suffix beyond the leader's last
            # entry must not count toward the leader's quorum math
            match = request.prev_log_index + len(request.entries)
            if request.leader_commit > self.commit_index:
                self.commit_index = min(request.leader_commit,
                                        self._last_index())
                self._apply_committed()
                self._maybe_compact()
            return raft_pb2.AppendEntriesResponse(
                term=self.current_term, success=True, match_index=match)
