"""Volume server: the dataplane node.

HTTP serves the public blob path (GET/POST/DELETE /<vid>,<fid>); gRPC
serves the admin plane (allocate, vacuum, copy, the EC lifecycle); a
background thread streams heartbeats to the master leader.

Reference: weed/server/volume_server.go, volume_server_handlers_*.go,
volume_grpc_*.go, volume_grpc_client_to_master.go.
"""

from __future__ import annotations

import gzip
import json
import os
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Tuple
from urllib.parse import parse_qs

import grpc

from seaweedfs_tpu import rpc
from seaweedfs_tpu.resilience import breaker as _breaker
from seaweedfs_tpu.resilience import deadline as _deadline
from seaweedfs_tpu.resilience import failpoint as _failpoint
from seaweedfs_tpu.util import http_client, wlog
from seaweedfs_tpu.util.http_server import (FastHandler, ServeConfig,
                                            make_http_server)
from seaweedfs_tpu.util.throttler import Throttler
from seaweedfs_tpu.ec import store_ec
from seaweedfs_tpu.ec.ec_volume import EcShardNotFound
from seaweedfs_tpu.ec.encoder import shard_file_name
from seaweedfs_tpu.ec.shard_bits import DATA_SHARDS, TOTAL_SHARDS
from seaweedfs_tpu.operation.file_id import parse_fid
from seaweedfs_tpu.pb import (master_pb2, master_stub, volume_server_pb2,
                              volume_stub)
from seaweedfs_tpu.server import convert
from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage import vacuum as vacuum_mod
from seaweedfs_tpu.storage import volume_backup, volume_tier
from seaweedfs_tpu.scrub import ScrubDaemon
from seaweedfs_tpu.storage.backend import BackendError
from seaweedfs_tpu.storage.needle import (FLAG_IS_CHUNK_MANIFEST,
                                          FLAG_IS_COMPRESSED,
                                          CookieMismatch,
                                          DataCorruptionError, Needle,
                                          NeedleError)
from seaweedfs_tpu.storage.store import Store
from seaweedfs_tpu.storage.superblock import TTL
from seaweedfs_tpu.storage.volume import VolumeError

log = wlog.logger("volume")

COPY_CHUNK = 1 << 20
# EC shard-location freshness is tiered by how complete the cached view
# is (reference storage/store_ec.go:221-231): a sparse view (fewer than
# DATA_SHARDS known) re-asks the master after 11s, a readable-but-
# incomplete view after 7m, a complete view only after 37m
EC_REFRESH_SPARSE_S = 11.0
EC_REFRESH_PARTIAL_S = 7 * 60.0
EC_REFRESH_FULL_S = 37 * 60.0
# Replica-location freshness: replica sets move on volume.fix.replication
# / rebalance, so the window stays short; any replica POST failure
# forgets the vid immediately (same invalidate-on-failure discipline as
# _ec_locations)
REPLICA_REFRESH_S = 30.0


class VolumeServer:
    def __init__(self, master_url: str, directories: List[str],
                 ip: str = "127.0.0.1", port: int = 8080,
                 public_url: str = "", data_center: str = "",
                 rack: str = "", max_volume_counts: Optional[List[int]] = None,
                 pulse_seconds: float = 5.0, ec_encoder: str = "auto",
                 compaction_mbps: float = 0.0,
                 storage_backends: Optional[dict] = None,
                 needle_map_kind: str = "memory",
                 scrub_mbps: float = 0.0,
                 scrub_interval_s: float = 0.0,
                 cache_size_mb: int = 0,
                 cache_dir: Optional[str] = None,
                 degraded_fleet: bool = True,
                 degraded_batch_ms: float = 2.0,
                 replicate_parallel: int = 8,
                 hedge_reads: bool = False,
                 hedge_delay_ms: float = 10.0,
                 heat_track: bool = False,
                 heat_window_s: float = 60.0,
                 ec_mesh: bool = False,
                 ec_mesh_min_volumes: int = 0,
                 ec_mesh_bucket_mb: int = 32,
                 ec_mesh_timeout_s: float = 30.0,
                 serve: Optional[ServeConfig] = None):
        if storage_backends:
            # cloud-tier targets, e.g. {"s3.default": {...}} (reference
            # master.toml [storage.backend.s3.default])
            from seaweedfs_tpu.storage import backend as _bk
            _bk.load_configuration(storage_backends)
        self.master_url = master_url
        # the master this server last heartbeated successfully (the
        # leader); master_url may be a comma-separated candidate list,
        # so lookups must dial this, never the raw flag value
        self.current_master = master_url.split(",")[0].strip()
        self.ip = ip
        self.port = port
        self.data_center = data_center
        self.rack = rack
        self.pulse_seconds = pulse_seconds
        self.ec_encoder = ec_encoder
        # -ec.mesh* knobs for the unified pod-scale scheduler
        # (parallel/mesh_fleet). None — not merely empty — when
        # disabled, so the default path never imports the mesh module
        # or queries jax devices
        # (test_perf_gates.test_mesh_disabled_overhead)
        self.ec_mesh_cfg = None
        if ec_mesh:
            self.ec_mesh_cfg = {
                "min_volumes": ec_mesh_min_volumes,
                "bucket_mb": ec_mesh_bucket_mb,
                "timeout_s": ec_mesh_timeout_s,
            }
        self.compaction_mbps = compaction_mbps
        self.store = Store(directories, max_volume_counts, ip=ip, port=port,
                           public_url=public_url,
                           needle_map_kind=needle_map_kind)
        # tiered read cache (-cache.sizeMB/-cache.dir): absent — not
        # merely empty — unless sized, so the disabled read path never
        # pays a lookup (test_perf_gates.test_cache_disabled_overhead)
        self.read_cache = None
        if cache_size_mb > 0:
            from seaweedfs_tpu.cache import TieredReadCache
            self.read_cache = TieredReadCache(
                cache_size_mb << 20,
                disk_dir=os.path.join(cache_dir, f"rc{port}")
                if cache_dir else None)
        # degraded-read decode fleet: fuses concurrent on-the-fly RS
        # reconstructions into [B, 10, span] dispatches. Constructing
        # it spawns nothing; threads appear on the first degraded read
        # (test_perf_gates.test_degraded_decode_disabled_overhead).
        self.degraded = None
        if degraded_fleet:
            from seaweedfs_tpu.reads import DegradedReadFleet
            self.degraded = DegradedReadFleet(
                backend=ec_encoder,
                batch_window_s=degraded_batch_ms / 1000.0,
                use_mesh=ec_mesh)
        # background integrity scrub: costs nothing (no thread, no IO)
        # until started — by RPC, by the master's staggered scheduler,
        # or at boot when -scrub.intervalSeconds is set
        self.scrub = ScrubDaemon(
            self.store, mbps=scrub_mbps, backend=ec_encoder,
            interval_s=scrub_interval_s,
            replica_fetch=self._fetch_needle_from_replica,
            on_repair=self._invalidate_volume_cache,
            mesh_cfg=self.ec_mesh_cfg)
        self.scrub_interval_s = scrub_interval_s
        self.volume_size_limit = 30 << 30
        self.compact_states: Dict[int, vacuum_mod.CompactState] = {}
        self._ec_locations: Dict[int, Tuple[float, Dict[int, List[str]]]] = {}
        # replica fan-out (-replicate.parallel): all replica POSTs for
        # one write go out concurrently on this shared pool. The pool
        # spawns no threads until the first multi-replica fan-out
        # (single-replica placements run inline), and replica URLs are
        # cached per vid instead of asking the master on EVERY
        # replicated write
        from seaweedfs_tpu.util.fanout import FanOutPool
        self._replicate_pool = FanOutPool(
            max(1, replicate_parallel), f"replicate-{port}")
        self._replica_urls: Dict[int, Tuple[float, List[str]]] = {}
        # hedged remote shard reads (-resilience.hedge): absent unless
        # enabled; a constructed Hedger spawns nothing until its first
        # multi-candidate fetch (resilience house rule)
        self.hedger = None
        if hedge_reads:
            from seaweedfs_tpu.resilience import Hedger
            self.hedger = Hedger(
                delay_floor_s=max(hedge_delay_ms, 0.1) / 1000.0,
                name=f"hedge-volume-{port}")
        # read-path heat telemetry (-heat.track): absent — not merely
        # idle — unless enabled, so the disabled read path pays one
        # None check (the lifecycle subsystem's measurement half)
        from seaweedfs_tpu.stats.heat import make_tracker
        self.heat = make_tracker(heat_track, window_s=heat_window_s)
        # -serve.* config: the async selector core (and its zero-copy
        # sendfile GET path) only exists when asked for — the default
        # server never imports util/async_server
        # (test_perf_gates.test_serve_async_disabled_overhead)
        self.serve = serve or ServeConfig()
        self._grpc_server = None
        self._http_server = None
        self._http_thread = None
        self._hb_thread = None
        self._hb_call = None
        self._hb_wake = threading.Event()
        self._stopping = False

    # -- lifecycle -----------------------------------------------------------

    @property
    def url(self) -> str:
        return f"{self.ip}:{self.port}"

    def start(self) -> None:
        handler = rpc.generic_handler(
            volume_server_pb2, "VolumeServer", self)
        self._grpc_server = rpc.make_server(
            f"{self.ip}:{self.port + rpc.GRPC_PORT_OFFSET}", [handler])
        self._http_server = make_http_server(
            (self.ip, self.port), _make_http_handler(self),
            role="volume", serve=self.serve)
        # lint: thread-ok(listener thread; ingress wrappers mint request context)
        self._http_thread = threading.Thread(
            target=self._http_server.serve_forever,
            name=f"volume-http-{self.port}", daemon=True)
        self._http_thread.start()
        # lint: thread-ok(listener thread; ingress wrappers mint request context)
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, name=f"heartbeat-{self.port}",
            daemon=True)
        self._hb_thread.start()
        if self.scrub_interval_s > 0:
            self.scrub.start()
        log.info("volume server %s:%d started (grpc :%d, dirs %s)",
                 self.ip, self.port, self.port + rpc.GRPC_PORT_OFFSET,
                 [loc.directory for loc in self.store.locations])

    def stop(self) -> None:
        log.info("volume server %s:%d stopping", self.ip, self.port)
        self._stopping = True
        if self.heat is not None:
            self.heat.close()
        if self.degraded is not None:
            self.degraded.stop()
        self.scrub.stop()
        self._hb_wake.set()
        if self._hb_call is not None:
            self._hb_call.cancel()
        if self._http_server:
            self._http_server.shutdown()
            self._http_server.server_close()
        if self._grpc_server:
            self._grpc_server.stop(grace=0.2)
        # drain in-flight replica fan-outs before the store closes
        # (util/grace shutdown contract)
        self._replicate_pool.stop()
        self.store.close()

    # -- heartbeat ------------------------------------------------------------

    def _heartbeat_gen(self):
        while not self._stopping:
            hb = self.store.collect_heartbeat()
            if self.heat is not None:
                # heat summary rides the heartbeat: the master's
                # topology aggregates every server's window reads +
                # decayed EWMA into the cluster heat map the lifecycle
                # policy engine decides from. Absent (not empty) when
                # -heat.track is off, so the disabled wire format is
                # byte-identical to pre-lifecycle heartbeats.
                hb["volume_heats"] = self.heat.summary()
            yield convert.heartbeat_to_pb(hb, self.data_center, self.rack)
            self._hb_wake.wait(timeout=self.pulse_seconds)
            self._hb_wake.clear()

    def _heartbeat_loop(self) -> None:
        """Keep one bidi heartbeat stream to the master LEADER.

        master_url may list several masters (comma-separated); a
        follower answers with the leader's address and the loop redials
        it (reference volume_grpc_client_to_master.go:50-95 follows
        HeartbeatResponse.leader the same way).
        """
        candidates = [m.strip() for m in self.master_url.split(",")
                      if m.strip()]
        target = candidates[0]
        rotate = 0
        while not self._stopping:
            redirect = None
            try:
                stub = master_stub(target)
                self._hb_call = stub.SendHeartbeat(self._heartbeat_gen())
                connected = False
                for resp in self._hb_call:
                    if resp.leader and resp.leader != target:
                        redirect = resp.leader
                        log.info("master %s redirects heartbeat to "
                                 "leader %s", target, redirect)
                        self._hb_call.cancel()
                        break
                    if not connected:
                        connected = True
                        self.current_master = target
                        log.info("heartbeat stream to master %s established",
                                 target)
                    if resp.volume_size_limit:
                        self.volume_size_limit = resp.volume_size_limit
                    if self._stopping:
                        return
            except grpc.RpcError as e:
                if self._stopping:
                    return
                log.warning("heartbeat stream to master %s broken (%s); "
                            "reconnecting", target,
                            getattr(e, "code", lambda: e)())
                time.sleep(min(self.pulse_seconds, 1.0))
            if self._stopping:
                return
            if redirect:
                target = redirect
            else:
                # rotate through the configured masters on plain breaks
                # — with a pause, so a leaderless election window
                # doesn't turn into a tight redial spin
                rotate += 1
                target = candidates[rotate % len(candidates)]
                self._hb_wake.wait(timeout=min(self.pulse_seconds, 1.0))
                self._hb_wake.clear()

    def trigger_heartbeat(self) -> None:
        """Push a delta heartbeat now instead of waiting out the pulse."""
        self._hb_wake.set()

    # -- gRPC: volume lifecycle ------------------------------------------------

    def AllocateVolume(self, request, context):
        self.store.add_volume(request.volume_id, request.collection,
                              replica_placement=request.replication or "000",
                              ttl=request.ttl)
        self.trigger_heartbeat()
        return volume_server_pb2.AllocateVolumeResponse()

    def VolumeDelete(self, request, context):
        self.store.delete_volume(request.volume_id)
        self._forget_heat(request.volume_id)
        self.trigger_heartbeat()
        return volume_server_pb2.VolumeDeleteResponse()

    def VolumeMarkReadonly(self, request, context):
        if not self.store.mark_volume_readonly(request.volume_id):
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"volume {request.volume_id} not found")
        self.trigger_heartbeat()
        return volume_server_pb2.VolumeMarkReadonlyResponse()

    def VolumeMarkWritable(self, request, context):
        try:
            found = self.store.mark_volume_writable(request.volume_id)
        except VolumeError as e:  # cloud-tiered volumes stay sealed
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
        if not found:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"volume {request.volume_id} not found")
        self.trigger_heartbeat()
        return volume_server_pb2.VolumeMarkWritableResponse()

    def VolumeMount(self, request, context):
        vid = request.volume_id
        if self.store.find_volume(vid) is None:
            found = False
            for loc in self.store.locations:
                for name in os.listdir(loc.directory):
                    if not name.endswith(".dat"):
                        continue
                    stem = name[:-len(".dat")]
                    col, _, tail = stem.rpartition("_")
                    if tail == str(vid) or (not col and stem == str(vid)):
                        loc.add_volume(vid, col)
                        found = True
                        break
                if found:
                    break
            if not found:
                context.abort(grpc.StatusCode.NOT_FOUND,
                              f"no .dat for volume {vid} on any disk")
        self.trigger_heartbeat()
        return volume_server_pb2.VolumeMountResponse()

    def VolumeUnmount(self, request, context):
        vid = request.volume_id
        for loc in self.store.locations:
            v = loc.volumes.get(vid)
            if v is not None:
                v.close()
                loc.volumes.pop(vid, None)
        self._forget_heat(vid)
        self.trigger_heartbeat()
        return volume_server_pb2.VolumeUnmountResponse()

    def DeleteCollection(self, request, context):
        for loc in self.store.locations:
            for vid, v in list(loc.volumes.items()):
                if v.collection == request.collection:
                    loc.delete_volume(vid)
                    self._forget_heat(vid)
            for vid, ecv in list(loc.ec_volumes.items()):
                if ecv.collection == request.collection:
                    ecv.destroy()
                    loc.ec_volumes.pop(vid, None)
                    self._forget_heat(vid)
        self.trigger_heartbeat()
        return volume_server_pb2.DeleteCollectionResponse()

    def _forget_heat(self, vid: int) -> None:
        """Heat hygiene on volume departure/conversion: without this a
        dead vid's SeaweedFS_volume_heat{vid} child and counters
        linger forever (unbounded label growth)."""
        if self.heat is not None:
            self.heat.forget(vid)

    def ReadVolumeFileStatus(self, request, context):
        v = self.store.find_volume(request.volume_id)
        if v is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"volume {request.volume_id} not found")
        base = v.file_name()
        return volume_server_pb2.ReadVolumeFileStatusResponse(
            volume_id=v.id,
            idx_file_size=os.path.getsize(base + ".idx"),
            dat_file_size=os.path.getsize(base + ".dat"),
            idx_file_timestamp_seconds=int(os.path.getmtime(base + ".idx")),
            dat_file_timestamp_seconds=int(os.path.getmtime(base + ".dat")),
            file_count=v.file_count,
            compaction_revision=v.super_block.compaction_revision,
            collection=v.collection)

    # -- gRPC: vacuum ----------------------------------------------------------

    def VacuumVolumeCheck(self, request, context):
        v = self.store.find_volume(request.volume_id)
        if v is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"volume {request.volume_id} not found")
        return volume_server_pb2.VacuumVolumeCheckResponse(
            garbage_ratio=v.garbage_ratio())

    def VacuumVolumeCompact(self, request, context):
        v = self.store.find_volume(request.volume_id)
        if v is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"volume {request.volume_id} not found")
        self.compact_states[v.id] = vacuum_mod.compact(
            v, preallocate=request.preallocate,
            compaction_mbps=self.compaction_mbps)
        return volume_server_pb2.VacuumVolumeCompactResponse()

    def VacuumVolumeCommit(self, request, context):
        v = self.store.find_volume(request.volume_id)
        state = self.compact_states.pop(request.volume_id, None)
        if v is None or state is None:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                          f"volume {request.volume_id}: no pending compaction")
        vacuum_mod.commit_compact(v, state)
        return volume_server_pb2.VacuumVolumeCommitResponse(
            is_read_only=v.read_only)

    def VacuumVolumeCleanup(self, request, context):
        v = self.store.find_volume(request.volume_id)
        self.compact_states.pop(request.volume_id, None)
        if v is not None:
            for ext in (".cpd", ".cpx"):
                p = v.file_name() + ext
                if os.path.exists(p):
                    os.remove(p)
        return volume_server_pb2.VacuumVolumeCleanupResponse()

    # -- gRPC: batch delete ----------------------------------------------------

    def BatchDelete(self, request, context):
        results = []
        for fid in request.file_ids:
            try:
                f = parse_fid(fid)
            except ValueError as e:
                results.append(volume_server_pb2.DeleteResult(
                    file_id=fid, status=400, error=str(e)))
                continue
            n = Needle(id=f.key, cookie=f.cookie)
            try:
                if not request.skip_cookie_check:
                    got = self._read_needle(f.volume_id, n)
                    if got.cookie != f.cookie:
                        raise CookieMismatch(f"cookie mismatch on {fid}")
                    if got.is_chunk_manifest:
                        # cascading here could recurse through this very
                        # RPC; refuse like the reference
                        # (volume_grpc_batch_delete.go:62-69)
                        results.append(volume_server_pb2.DeleteResult(
                            file_id=fid, status=406,
                            error="ChunkManifest: not allowed in batch "
                                  "delete mode."))
                        continue
                # replicated like the HTTP DELETE path, so the needle
                # disappears from every replica, not just this server
                size = self.replicated_delete(f.volume_id, n)
                results.append(volume_server_pb2.DeleteResult(
                    file_id=fid, status=202, size=size))
            except CookieMismatch as e:
                results.append(volume_server_pb2.DeleteResult(
                    file_id=fid, status=403, error=str(e)))
            except (NeedleError, EcShardNotFound) as e:
                results.append(volume_server_pb2.DeleteResult(
                    file_id=fid, status=404, error=str(e)))
        return volume_server_pb2.BatchDeleteResponse(results=results)

    # -- gRPC: query (S3 Select-ish) -------------------------------------------

    def Query(self, request, context):
        """Scan stored JSON documents: filter + project, one stripe per
        file id (reference server/volume_grpc_query.go:12-76)."""
        from seaweedfs_tpu.query import Query as JQuery, query_json_lines
        q = JQuery(field=request.filter.field,
                   op=request.filter.operand,
                   value=request.filter.value)
        for fid in request.from_file_ids:
            try:
                f = parse_fid(fid)
            except ValueError as e:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
            n = Needle(id=f.key, cookie=f.cookie)
            try:
                got = self._read_needle(f.volume_id, n)
            except (NeedleError, EcShardNotFound, CookieMismatch) as e:
                context.abort(grpc.StatusCode.NOT_FOUND,
                              f"{fid}: {e}")
            data = got.data
            if got.is_compressed:
                data = gzip.decompress(data)
            records = b"".join(
                json.dumps(rec).encode() + b"\n"
                for rec in query_json_lines(
                    data, list(request.selections), q))
            yield volume_server_pb2.QueriedStripe(records=records)

    # -- gRPC: replica copy ----------------------------------------------------

    def CopyFile(self, request, context):
        path = self._file_path_for_copy(request)
        if path is None or not os.path.exists(path):
            if request.ignore_source_file_not_found:
                return
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"no file for vid={request.volume_id} "
                          f"ext={request.ext}")
        stop = request.stop_offset or os.path.getsize(path)
        throttler = Throttler(self.compaction_mbps)
        with open(path, "rb") as f:
            sent = 0
            while sent < stop:
                chunk = f.read(min(COPY_CHUNK, stop - sent))
                if not chunk:
                    break
                sent += len(chunk)
                throttler.maybe_slowdown(len(chunk))
                yield volume_server_pb2.CopyFileResponse(file_content=chunk)

    def _file_path_for_copy(self, request) -> Optional[str]:
        vid, ext = request.volume_id, request.ext
        if request.is_ec_volume:
            base = store_ec._find_ec_base(self.store, vid,
                                          request.collection or None)
            return base + ext if base else None
        v = self.store.find_volume(vid)
        return v.file_name() + ext if v else None

    def VolumeCopy(self, request, context):
        """Pull a whole volume (.dat + .idx) from source_data_node and
        mount it (reference server/volume_grpc_copy.go)."""
        vid = request.volume_id
        if self.store.find_volume(vid) is not None:
            context.abort(grpc.StatusCode.ALREADY_EXISTS,
                          f"volume {vid} already exists")
        src = volume_stub(request.source_data_node)
        status = src.ReadVolumeFileStatus(
            volume_server_pb2.ReadVolumeFileStatusRequest(volume_id=vid))
        loc = next((l for l in self.store.locations if l.has_free_slot()),
                   None)
        if loc is None:
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, "no free slot")
        base = store_ec._base_name(loc.directory, status.collection, vid)
        try:
            for ext in (".idx", ".dat"):
                self._pull_file(src, vid, ext, base + ext,
                                collection=status.collection)
        except grpc.RpcError:
            for ext in (".idx", ".dat"):
                if os.path.exists(base + ext):
                    os.remove(base + ext)
            raise
        loc.add_volume(vid, status.collection)
        self.trigger_heartbeat()
        return volume_server_pb2.VolumeCopyResponse(
            last_append_at_ns=time.time_ns())

    def _pull_file(self, src_stub, vid: int, ext: str, dest_path: str,
                   collection: str = "", is_ec: bool = False,
                   ignore_missing: bool = False) -> None:
        tmp = dest_path + ".copying"
        with open(tmp, "wb") as f:
            for resp in src_stub.CopyFile(volume_server_pb2.CopyFileRequest(
                    volume_id=vid, ext=ext, collection=collection,
                    is_ec_volume=is_ec,
                    ignore_source_file_not_found=ignore_missing)):
                f.write(resp.file_content)
        os.replace(tmp, dest_path)

    # -- gRPC: sync status / incremental copy / tail ---------------------------

    def VolumeSyncStatus(self, request, context):
        """Handshake for followers (reference volume_backup.go:19-33)."""
        v = self.store.find_volume(request.volume_id)
        if v is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"volume {request.volume_id} not found")
        st = volume_backup.sync_status(v)
        return volume_server_pb2.VolumeSyncStatusResponse(
            volume_id=st["volume_id"], collection=st["collection"],
            replication=st["replication"], ttl=st["ttl"],
            tail_offset=st["tail_offset"],
            compact_revision=st["compact_revision"],
            idx_file_size=st["idx_file_size"])

    def VolumeIncrementalCopy(self, request, context):
        """Stream raw .dat bytes appended after since_ns
        (reference server/volume_grpc_copy_incremental.go)."""
        v = self.store.find_volume(request.volume_id)
        if v is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"volume {request.volume_id} not found")
        offset, is_last = volume_backup.binary_search_by_append_at_ns(
            v, request.since_ns)
        if is_last:
            return
        for chunk in volume_backup.read_dat_range(v, offset):
            yield volume_server_pb2.VolumeIncrementalCopyResponse(
                file_content=chunk)

    def VolumeTailSender(self, request, context):
        """Stream needles appended after since_ns; keep following until
        the tail stays quiet for idle_timeout_seconds (0 = follow
        forever; reference volume_grpc_tail.go:17-64)."""
        v = self.store.find_volume(request.volume_id)
        if v is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"volume {request.volume_id} not found")
        last_ns = request.since_ns
        draining = request.idle_timeout_seconds
        while True:
            if not context.is_active():
                # client went away: don't pin a gRPC worker thread
                # forever on an idle follow-mode stream
                return
            progressed = False
            offset, is_last = volume_backup.binary_search_by_append_at_ns(
                v, last_ns)
            if not is_last:
                for off, n in volume_backup.scan_dat_from(v, offset):
                    blob = n.to_bytes(v.version)
                    yield volume_server_pb2.VolumeTailSenderResponse(
                        needle_header=blob[:t.NEEDLE_HEADER_SIZE],
                        needle_body=blob[t.NEEDLE_HEADER_SIZE:])
                    if n.append_at_ns > last_ns:
                        last_ns = n.append_at_ns
                        progressed = True
            if request.idle_timeout_seconds == 0:
                time.sleep(1)
                continue
            if progressed:
                draining = request.idle_timeout_seconds
            else:
                draining -= 1
                if draining <= 0:
                    yield volume_server_pb2.VolumeTailSenderResponse(
                        is_last_chunk=True)
                    return
            time.sleep(1)

    def VolumeTailReceiver(self, request, context):
        """Pull a tail stream from source_volume_server and replay it
        into the local replica (reference volume_grpc_tail.go:80-94)."""
        v = self.store.find_volume(request.volume_id)
        if v is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"volume {request.volume_id} not found")
        src = volume_stub(request.source_volume_server)
        for resp in src.VolumeTailSender(
                volume_server_pb2.VolumeTailSenderRequest(
                    volume_id=request.volume_id,
                    since_ns=request.since_ns,
                    idle_timeout_seconds=request.idle_timeout_seconds)):
            if resp.is_last_chunk:
                break
            blob = bytes(resp.needle_header) + bytes(resp.needle_body)
            n = Needle.from_bytes(blob, v.version, check_crc=False)
            if len(n.data) == 0:
                v.delete_needle(n)
            else:
                v.write_needle(n)
        return volume_server_pb2.VolumeTailReceiverResponse()

    # -- gRPC: cloud tier ------------------------------------------------------

    def VolumeTierMoveDatToRemote(self, request, context):
        """Upload a sealed volume's bulk bytes to the named storage
        backend (reference volume_grpc_tier_upload.go). A normal
        volume moves its .dat; an erasure-coded vid moves this
        server's .ecNN shard files instead (the lifecycle engine's
        WARM -> COLD leg) — the .idx/.ecx index always stays local."""
        v = self.store.find_volume(request.volume_id)
        if v is None:
            ecv = self.store.find_ec_volume(request.volume_id)
            if ecv is None:
                context.abort(grpc.StatusCode.NOT_FOUND,
                              f"volume {request.volume_id} not found")
            try:
                total = volume_tier.move_ec_shards_to_remote(
                    ecv, request.destination_backend_name,
                    keep_local=request.keep_local_dat_file,
                    owner=self.url)
            except (VolumeError, BackendError) as e:
                context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                              str(e))
            yield volume_server_pb2.VolumeTierMoveDatToRemoteResponse(
                processed=total, processed_percentage=100.0)
            return
        total = max(v.content_size, 1)
        progress_state = {"sent": 0}

        def progress(nbytes):
            progress_state["sent"] += nbytes

        try:
            volume_tier.move_dat_to_remote(
                v, request.destination_backend_name,
                keep_local=request.keep_local_dat_file,
                owner=self.url,
                progress=progress)
        except (VolumeError, BackendError) as e:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
        yield volume_server_pb2.VolumeTierMoveDatToRemoteResponse(
            processed=progress_state["sent"],
            processed_percentage=100.0 * progress_state["sent"] / total)

    def VolumeTierMoveDatFromRemote(self, request, context):
        """Download a tiered volume's bulk bytes back to local disk
        (reference volume_grpc_tier_download.go); EC vids restore this
        server's shard files (the COLD -> WARM leg)."""
        v = self.store.find_volume(request.volume_id)
        if v is None:
            ecv = self.store.find_ec_volume(request.volume_id)
            if ecv is None:
                context.abort(grpc.StatusCode.NOT_FOUND,
                              f"volume {request.volume_id} not found")
            try:
                total = volume_tier.move_ec_shards_from_remote(
                    ecv, keep_remote=request.keep_remote_dat_file)
            except (VolumeError, BackendError) as e:
                context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                              str(e))
            yield volume_server_pb2.VolumeTierMoveDatFromRemoteResponse(
                processed=total, processed_percentage=100.0)
            return
        state = {"done": 0}

        def progress(nbytes):
            state["done"] += nbytes

        try:
            total = volume_tier.move_dat_from_remote(
                v, keep_remote=request.keep_remote_dat_file,
                progress=progress)
        except (VolumeError, BackendError) as e:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
        yield volume_server_pb2.VolumeTierMoveDatFromRemoteResponse(
            processed=total, processed_percentage=100.0)

    # -- gRPC: erasure coding --------------------------------------------------

    def VolumeEcShardsGenerate(self, request, context):
        vids = list(request.volume_ids) or [request.volume_id]
        try:
            if len(vids) == 1:
                store_ec.generate_ec_shards(
                    self.store, vids[0],
                    backend=request.encoder or self.ec_encoder)
            else:
                # cross-volume fused encode: one scheduler packs all
                # the volumes' chunks into shared RS dispatches — the
                # pod-scale mesh scheduler under -ec.mesh, the host
                # fleet otherwise
                store_ec.generate_ec_shards_batch(
                    self.store, vids,
                    backend=request.encoder or self.ec_encoder,
                    mesh_cfg=self.ec_mesh_cfg)
        except NeedleError as e:
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        for vid in vids:
            # tier conversion resets the vid's heat ledger: the EC era
            # starts counting from zero (reads re-register on demand)
            self._forget_heat(vid)
        return volume_server_pb2.VolumeEcShardsGenerateResponse()

    def VolumeEcShardsRebuild(self, request, context):
        try:
            rebuilt = store_ec.rebuild_ec_shards(
                self.store, request.volume_id,
                collection=request.collection or None,
                backend=request.encoder or self.ec_encoder)
        except EcShardNotFound as e:
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        if rebuilt:
            # rebuilt shard bytes supersede any reconstructed spans
            self._invalidate_volume_cache(request.volume_id, "rebuild")
        return volume_server_pb2.VolumeEcShardsRebuildResponse(
            rebuilt_shard_ids=rebuilt)

    def VolumeEcShardsCopy(self, request, context):
        vid = request.volume_id
        src = volume_stub(request.source_data_node)
        loc = next((l for l in self.store.locations if l.has_free_slot()),
                   self.store.locations[0])
        base = store_ec._base_name(loc.directory, request.collection, vid)
        for sid in request.shard_ids:
            self._pull_file(src, vid, f".ec{sid:02d}",
                            shard_file_name(base, sid),
                            collection=request.collection, is_ec=True)
        if request.copy_ecx_file:
            self._pull_file(src, vid, ".ecx", base + ".ecx",
                            collection=request.collection, is_ec=True)
        if request.copy_ecj_file:
            self._pull_file(src, vid, ".ecj", base + ".ecj",
                            collection=request.collection, is_ec=True,
                            ignore_missing=True)
        return volume_server_pb2.VolumeEcShardsCopyResponse()

    def VolumeEcShardsDelete(self, request, context):
        store_ec.delete_ec_shards(self.store, request.volume_id,
                                  collection=request.collection or None,
                                  shard_ids=list(request.shard_ids))
        # the shard set changed under any cached reconstructed spans
        self._invalidate_volume_cache(request.volume_id, "rebuild")
        self.trigger_heartbeat()
        return volume_server_pb2.VolumeEcShardsDeleteResponse()

    def VolumeEcShardsMount(self, request, context):
        try:
            store_ec.mount_ec_shards(self.store, request.volume_id,
                                     request.collection,
                                     list(request.shard_ids))
        except EcShardNotFound as e:
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        self.trigger_heartbeat()
        return volume_server_pb2.VolumeEcShardsMountResponse()

    def VolumeEcShardsUnmount(self, request, context):
        store_ec.unmount_ec_shards(self.store, request.volume_id,
                                   list(request.shard_ids))
        self.trigger_heartbeat()
        return volume_server_pb2.VolumeEcShardsUnmountResponse()

    def VolumeEcShardRead(self, request, context):
        try:
            data = store_ec.read_ec_shard(
                self.store, request.volume_id, request.shard_id,
                request.offset, request.size)
        except EcShardNotFound as e:
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        for i in range(0, len(data), COPY_CHUNK):
            yield volume_server_pb2.VolumeEcShardReadResponse(
                data=data[i:i + COPY_CHUNK])

    def VolumeEcBlobDelete(self, request, context):
        try:
            store_ec.delete_ec_needle(
                self.store, request.volume_id,
                Needle(id=request.file_key), cache=self.read_cache)
        except EcShardNotFound as e:
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        return volume_server_pb2.VolumeEcBlobDeleteResponse()

    def VolumeEcShardsToVolume(self, request, context):
        try:
            store_ec.ec_shards_to_volume(self.store, request.volume_id,
                                         request.collection,
                                         backend=self.ec_encoder)
        except EcShardNotFound as e:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
        # the vid serves from a normal volume now: EC-era cache entries
        # must not outlive the transition (writes can land again), and
        # the EC era's heat ledger resets with the tier
        self._invalidate_volume_cache(request.volume_id, "rebuild")
        self._forget_heat(request.volume_id)
        self.trigger_heartbeat()
        return volume_server_pb2.VolumeEcShardsToVolumeResponse()

    # -- gRPC: scrub control plane ---------------------------------------------

    def VolumeScrubStart(self, request, context):
        started = self.scrub.start(
            volume_ids=list(request.volume_ids) or None,
            throttle_mbps=request.throttle_mbps or None,
            full=request.full)
        return volume_server_pb2.VolumeScrubStartResponse(started=started)

    def VolumeScrubPause(self, request, context):
        return volume_server_pb2.VolumeScrubPauseResponse(
            paused=self.scrub.pause())

    def VolumeScrubStatus(self, request, context):
        return volume_server_pb2.VolumeScrubStatusResponse(
            **self.scrub.status())

    def _fetch_needle_from_replica(self, vid: int, corrupt: Needle):
        """Scrub repair source: the raw stored payload of one needle
        from any OTHER replica. Accept-Encoding gzip keeps a
        compressed needle's stored bytes as stored; cm=false stops the
        replica from resolving a chunk manifest into its chunks. The
        planner validates whatever comes back against the local
        record's own stored CRC, so a stale or corrupt replica copy is
        rejected, never written."""
        fid = f"{vid},{corrupt.id:x}{corrupt.cookie:08x}"
        for url in _breaker.sort_candidates(self._other_replicas(vid)):
            try:
                resp = http_client.request(
                    "GET", f"{url}/{fid}?cm=false",
                    headers={"Accept-Encoding": "gzip"}, timeout=30)
            except OSError:
                continue
            if resp.status == 200:
                return resp.body
        return None

    # -- gRPC: status ----------------------------------------------------------

    def VolumeServerStatus(self, request, context):
        disks = []
        for loc in self.store.locations:
            st = os.statvfs(loc.directory)
            disks.append(volume_server_pb2.DiskStatus(
                dir=loc.directory, all=st.f_blocks * st.f_frsize,
                free=st.f_bavail * st.f_frsize,
                used=(st.f_blocks - st.f_bfree) * st.f_frsize))
        return volume_server_pb2.VolumeServerStatusResponse(
            disk_statuses=disks)

    def VolumeServerLeave(self, request, context):
        """Graceful drain: stop heartbeats so the master forgets us."""
        self._stopping = True
        self._hb_wake.set()
        if self._hb_call is not None:
            self._hb_call.cancel()
        return volume_server_pb2.VolumeServerLeaveResponse()

    def VolumeStatus(self, request, context):
        """Liveness/readonly probe (reference volume_grpc_admin.go
        VolumeStatus)."""
        v = self.store.find_volume(request.volume_id)
        if v is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"volume {request.volume_id} not found")
        return volume_server_pb2.VolumeStatusResponse(
            is_read_only=v.read_only)

    def VolumeNeedleStatus(self, request, context):
        """One needle's metadata without its data (reference
        volume_grpc_query.go VolumeNeedleStatus): index entry + the
        stored record's mtime/crc."""
        v = self.store.find_volume(request.volume_id)
        if v is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"volume {request.volume_id} not found")
        nv = v.nm.get(request.needle_id)
        if nv is None or not t.size_is_valid(nv.size):
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"needle {request.needle_id} not found")
        # cookie=0 skips the cookie check — this is an admin probe
        try:
            got = v.read_needle(Needle(id=request.needle_id, cookie=0))
        except NeedleError as e:   # expired / torn / CRC-bad record
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        return volume_server_pb2.VolumeNeedleStatusResponse(
            needle_id=request.needle_id,
            cookie=got.cookie,
            size=nv.size,
            last_modified=got.append_at_ns // 1_000_000_000,
            crc=got.checksum,
            ttl=str(v.ttl))

    def VolumeConfigure(self, request, context):
        """Rewrite a volume's replica placement in its superblock
        (reference server/volume_grpc_admin.go:104)."""
        try:
            found = self.store.configure_volume(request.volume_id,
                                                request.replication)
        except (ValueError, VolumeError) as e:
            return volume_server_pb2.VolumeConfigureResponse(error=str(e))
        if not found:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"volume {request.volume_id} not found")
        self.trigger_heartbeat()
        return volume_server_pb2.VolumeConfigureResponse()

    # -- needle data ops (shared by HTTP and gRPC paths) -----------------------

    def _read_needle(self, vid: int, n: Needle,
                     record_heat: bool = True) -> Needle:
        if self.heat is not None and record_heat:
            # counted at admission, not success: a read of a dead
            # needle still heats the volume (the lifecycle policy cares
            # about demand, not hit rate). record_heat=False when the
            # async span fast path already counted this request's
            # admission and fell back here for the payload.
            self.heat.record(vid, n.id)
        if self.store.has_volume(vid):
            got = self.store.read_needle(vid, n)
        elif self.store.find_ec_volume(vid) is not None:
            got = store_ec.read_ec_needle(
                self.store, vid, n,
                remote_reader=self._make_remote_reader(vid),
                cache=self.read_cache, decoder=self.degraded)
        else:
            raise NeedleError(f"volume {vid} not found")
        if _failpoint._armed:
            # injection site volume.read: delay stalls this server's
            # reads (the chaos harness's slow-shard scenario), error
            # fails them, short/corrupt mangle the served payload
            got.data = _failpoint.mangle(
                "volume.read", got.data, vid=str(vid), server=self.url)
        return got

    def _delete_needle(self, vid: int, n: Needle) -> int:
        if self.store.has_volume(vid):
            size = self.store.delete_needle(vid, n)
            self._invalidate_needle_cache(vid, n.id, "delete")
            return size
        if self.store.find_ec_volume(vid) is not None:
            store_ec.delete_ec_needle(self.store, vid, n,
                                      cache=self.read_cache)
            return 0
        raise NeedleError(f"volume {vid} not found")

    # -- read-cache invalidation ----------------------------------------------

    def _invalidate_needle_cache(self, vid: int, needle_id: int,
                                 reason: str) -> None:
        if self.read_cache is not None:
            self.read_cache.invalidate(vid, needle_id, reason)

    def _invalidate_volume_cache(self, vid: int,
                                 reason: str = "scrub_repair") -> None:
        if self.read_cache is not None:
            self.read_cache.invalidate_volume(vid, reason)

    def _make_remote_reader(self, vid: int):
        def fetch_shard(url: str, shard_id: int, offset: int,
                        length: int) -> bytes:
            # deadline: a hung peer must fail this row, not pin
            # the caller (the decode fleet's dispatcher rides
            # this reader — head-of-line blocking is fatal there)
            chunks = [r.data for r in volume_stub(url)
                      .VolumeEcShardRead(
                          volume_server_pb2.VolumeEcShardReadRequest(
                              volume_id=vid, shard_id=shard_id,
                              offset=offset, size=length),
                          timeout=15)]
            data = b"".join(chunks)
            if len(data) != length:
                raise EcShardNotFound(
                    f"vid {vid} shard {shard_id}: short remote read")
            return data

        def remote_reader(shard_id: int, offset: int, length: int):
            urls = _breaker.sort_candidates(
                [u for u in self._ec_shard_locations(vid).get(shard_id, [])
                 if u != self.url])
            tried = bool(urls)
            if self.hedger is not None and len(urls) > 1:
                # a stalled shard holder hedges to another holder after
                # the tracked p95; first response wins
                try:
                    return self.hedger.fetch(
                        [lambda u=u: fetch_shard(u, shard_id, offset,
                                                 length) for u in urls])
                except _deadline.DeadlineExceeded:
                    # a spent budget is the CLIENT's state, not
                    # evidence against these shard locations — never
                    # fall into the forget-locations arm below
                    raise
                except (grpc.RpcError, OSError, EcShardNotFound):
                    pass
            else:
                for url in urls:
                    try:
                        return fetch_shard(url, shard_id, offset, length)
                    except (grpc.RpcError, EcShardNotFound):
                        continue
            if tried:
                # every known location failed: forget THIS shard's
                # locations so reads stop redialing a dead node
                # (reference forgetShardId, store_ec.go:214-219).
                # Subsequent reads of the shard go straight to
                # reconstruction; the master is re-asked once the
                # view's refresh window lapses (7m at >=10 known
                # shards, 11s once fewer than 10 remain) — the same
                # trade the reference makes
                self._forget_ec_shard(vid, shard_id)
            return None
        return remote_reader

    def _ec_shard_locations(self, vid: int) -> Dict[int, List[str]]:
        now = time.monotonic()
        cached = self._ec_locations.get(vid)
        if cached is not None:
            ts, locs = cached
            n_known = len(locs)
            if n_known >= TOTAL_SHARDS:
                window = EC_REFRESH_FULL_S
            elif n_known >= DATA_SHARDS:
                window = EC_REFRESH_PARTIAL_S
            else:
                window = EC_REFRESH_SPARSE_S
            if now - ts < window:
                return locs
        locs = dict(cached[1]) if cached is not None else {}
        try:
            resp = master_stub(self.current_master).LookupEcVolume(
                master_pb2.LookupEcVolumeRequest(volume_id=vid))
            # merge per shard like the reference (store_ec.go:249-257):
            # shards absent from the answer keep their last-known urls
            for sl in resp.shard_id_locations:
                locs[sl.shard_id] = [l.url for l in sl.locations]
        except grpc.RpcError:
            # master unreachable: serve stale cache if any, and don't
            # poison the cache with an empty map until the next window
            return cached[1] if cached is not None else {}
        self._ec_locations[vid] = (now, locs)
        return locs

    def _forget_ec_shard(self, vid: int, shard_id: int) -> None:
        cached = self._ec_locations.get(vid)
        if cached is not None:
            cached[1].pop(shard_id, None)

    def _forget_ec_locations(self, vid: int) -> None:
        self._ec_locations.pop(vid, None)

    # -- replication -----------------------------------------------------------

    def _other_replicas(self, vid: int) -> List[str]:
        """Replica urls for vid, cached per REPLICA_REFRESH_S — the
        pre-cache shape asked the master on EVERY replicated write."""
        now = time.monotonic()
        cached = self._replica_urls.get(vid)
        if cached is not None and now - cached[0] < REPLICA_REFRESH_S:
            return cached[1]
        try:
            resp = master_stub(self.current_master).LookupVolume(
                master_pb2.LookupVolumeRequest(volume_ids=[str(vid)]))
        except grpc.RpcError:
            # master unreachable: serve stale locations if any — a
            # replica POST to a moved node fails and forgets the vid
            return cached[1] if cached is not None else []
        urls = []
        for vl in resp.volume_id_locations:
            for loc in vl.locations:
                if loc.url != self.url:
                    urls.append(loc.url)
        if not urls:
            # never CACHE an empty view: a replica mid-restart is
            # missing from the master for a beat, and banking that
            # would ack 30s of unreplicated writes instead of one
            self._replica_urls.pop(vid, None)
            return urls
        self._replica_urls[vid] = (now, urls)
        return urls

    def _forget_replicas(self, vid: int) -> None:
        self._replica_urls.pop(vid, None)

    def _fan_out_replicas(self, vid: int, urls: List[str], op: str,
                          post_one) -> None:
        """Issue `post_one(url)` for every replica concurrently on the
        shared pool (reference topology/store_replicate.go fans these
        out with goroutines). Every POST runs to completion — an early
        failure never leaves a sibling's in-flight socket dangling to
        poison the keep-alive pool — then the FIRST error fails the
        write and forgets the vid's cached locations.

        Open-breaker peers sort last and their POSTs fail fast inside
        http_client (BreakerOpen) instead of tying a pool lane up for
        a connect timeout — the write still fails (replication is not
        optional) but in microseconds, not seconds."""
        urls = _breaker.sort_candidates(urls)
        from seaweedfs_tpu.stats import trace
        from seaweedfs_tpu.stats.metrics import \
            IngestReplicaFanoutSecondsHistogram
        sp = trace.span("ingest.replicate", vid=vid, op=op,
                        replicas=len(urls)) \
            if trace.is_enabled() else trace.NOOP
        t0 = time.perf_counter()
        with sp:
            outcomes = self._replicate_pool.run(
                [lambda u=u: post_one(u) for u in urls])
        IngestReplicaFanoutSecondsHistogram.labels(op).observe(
            time.perf_counter() - t0)
        first_err = None
        for url, (resp, exc) in zip(urls, outcomes):
            if exc is not None:
                err = f"{op} to {url} failed: {exc}"
            elif resp.status >= 300:
                err = f"{op} to {url} failed: {resp.status}"
            else:
                continue
            if first_err is None:
                first_err = err
        if first_err is not None:
            self._forget_replicas(vid)
            raise NeedleError(first_err)

    def replicated_write(self, vid: int, n: Needle,
                         fsync: bool = False) -> int:
        """Write locally then fan out the serialized needle to every
        other replica CONCURRENTLY (reference
        topology/store_replicate.go:21-94 + its goroutine fan-out).

        Like the reference, a volume whose replica placement says one
        copy never consults the master for replica locations — the
        placement is in the superblock, so the common 000 case stays a
        purely local append."""
        v = self.store.find_volume(vid)
        if v is not None and v.read_only:
            raise NeedleError(f"volume {vid} is read only")
        _, size = self.store.write_needle(vid, n, fsync=fsync)
        self._invalidate_needle_cache(vid, n.id, "overwrite")
        if v is not None and v.replica_placement.copy_count <= 1:
            return size
        urls = self._other_replicas(vid)
        if not urls:
            return size
        blob = n.to_bytes()

        def post_one(url):
            return http_client.request(
                "POST", f"{url}/admin/replicate?volume={vid}",
                body=blob,
                headers={"Content-Type": "application/octet-stream"},
                timeout=30)

        self._fan_out_replicas(vid, urls, "replicate", post_one)
        return size

    def replicated_delete(self, vid: int, n: Needle) -> int:
        size = self._delete_needle(vid, n)
        v = self.store.find_volume(vid)
        if v is not None and v.replica_placement.copy_count <= 1:
            return size
        urls = self._other_replicas(vid)
        if not urls:
            return size

        def post_one(url):
            return http_client.request(
                "POST",
                f"{url}/admin/replicate_delete"
                f"?volume={vid}&key={n.id:x}&cookie={n.cookie:08x}",
                timeout=30)

        self._fan_out_replicas(vid, urls, "replicate_delete", post_one)
        return size


# -- HTTP layer ---------------------------------------------------------------


def parse_byte_range(rng: str, total: int) -> Tuple[int, int]:
    """Parse a single "bytes=a-b" / "bytes=a-" / "bytes=-n" header
    against a payload of `total` bytes. Returns (start, end) inclusive;
    raises ValueError on anything unsatisfiable (HTTP 416)."""
    start_s, _, end_s = rng[len("bytes="):].partition("-")
    if not start_s:  # suffix range: last N bytes
        start = max(0, total - int(end_s))
        end = total - 1
    else:
        start = int(start_s)
        end = int(end_s) if end_s else total - 1
    end = min(end, total - 1)
    if start > end or start < 0:
        raise ValueError(f"unsatisfiable range {rng!r} for {total}")
    return start, end


def content_disposition(name: str) -> str:
    """inline; filename=... with CR/LF/quotes stripped — names can come
    from attacker-controlled manifest JSON, and a raw CRLF here would
    split the response into injected headers."""
    safe = name.replace("\r", "").replace("\n", "").replace('"', "")
    return f'inline; filename="{safe}"'


def parse_multipart(content_type: str, body: bytes):
    """Returns (filename, mime, data, encoding) of the first file part,
    where encoding is the part's Content-Encoding (reference
    needle_parse_upload.go). Parsing rides util.multipart.iter_parts."""
    from seaweedfs_tpu.util.multipart import iter_parts
    fallback = None
    for _name, filename, headers, data in iter_parts(content_type, body):
        mime = headers.get("content-type", "")
        encoding = headers.get("content-encoding", "")
        if filename:
            return filename, mime, data, encoding
        if fallback is None:
            fallback = ("", mime, data, encoding)
    if fallback is None:
        raise ValueError("empty multipart body")
    return fallback


def _make_http_handler(vs: VolumeServer):
    from seaweedfs_tpu.stats.metrics import instrument_http_handler

    class Handler(FastHandler):
        protocol_version = "HTTP/1.1"
        disable_nagle_algorithm = True  # small replies must not wait on delayed ACKs

        def log_message(self, fmt, *args):
            pass


        # -- plumbing ---------------------------------------------------------

        def _reply(self, code: int, body: bytes = b"",
                   headers: Optional[dict] = None) -> None:
            self.fast_reply(code, body, headers)

        def _json(self, payload: dict, code: int = 200) -> None:
            self.fast_reply(code, json.dumps(payload).encode(),
                            ctype="application/json")

        def _body(self) -> bytes:
            # framing-aware (Content-Length or chunked), identical on
            # both server models
            return self.read_body()

        def _parse_path(self):
            """/<vid>,<key_hex><cookie_hex> with optional leading dirs.

            Manual "?" split instead of urlparse: the data plane never
            carries params/fragments, and urlparse + parse_qs on every
            GET is measurable at small-file rates."""
            path, sep, query = self.path.partition("?")
            return parse_fid(path.lstrip("/")), \
                (parse_qs(query) if sep else {})

        # -- read -------------------------------------------------------------

        def do_GET(self):
            upath = self.path.partition("?")[0]
            if upath == "/status":
                self._json(self.server_status())
                return
            if upath == "/qos/status":
                # the data plane's own QoS admission state (the master
                # aggregates these under /cluster/qos)
                from seaweedfs_tpu import qos
                mgr = qos.manager()
                self._json(mgr.status() if mgr is not None
                           else {"enabled": False})
                return
            if upath in ("/debug/trace", "/debug/requests"):
                # cluster-trace collector + flight recorder on the data
                # port too: cluster.trace fans out over topology node
                # urls, which are HTTP ports, not metrics ports
                from seaweedfs_tpu.stats import cluster_trace
                self._json(cluster_trace.debug_payload(
                    self.path, "volumeServer", vs.url))
                return
            if upath in ("/ui", "/ui/"):
                import html as _html
                st = self.server_status()
                rows = "".join(
                    f"<tr><td>{v['id']}</td>"
                    f"<td>{_html.escape(v.get('collection') or '')}"
                    f"</td><td>{v['size']}</td><td>{v['file_count']}</td>"
                    f"<td>{'ro' if v.get('read_only') else 'rw'}</td></tr>"
                    for v in st["Volumes"])
                body = ("<html><head><title>seaweedfs-tpu volume</title>"
                        f"</head><body><h1>Volume server {vs.url}</h1>"
                        f"<p>master: {vs.current_master}</p>"
                        "<table border=1 cellpadding=4><tr><th>vid</th>"
                        "<th>collection</th><th>size</th><th>files</th>"
                        "<th>mode</th></tr>" + rows + "</table>"
                        "</body></html>").encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/html; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            try:
                f, params = self._parse_path()
            except ValueError as e:
                self._json({"error": str(e)}, code=404)
                return
            n = Needle(id=f.key, cookie=f.cookie)
            if not vs.store.has_volume(f.volume_id) and \
                    vs.store.find_ec_volume(f.volume_id) is None:
                self._redirect_to_replica(f)
                return
            record_heat = True
            if self.async_conn is not None and vs.serve.sendfile and \
                    not _failpoint._armed and \
                    vs.store.has_volume(f.volume_id):
                # zero-copy fast path: payload rides os.sendfile from
                # the volume fd straight to the socket. Falls back to
                # the byte path whenever the payload itself is needed
                # (compressed, chunk manifest, image resize, armed
                # failpoints, strict read verification).
                handled, heat_counted = \
                    self._try_send_needle_span(f, params)
                if handled:
                    return
                record_heat = not heat_counted
            try:
                got = vs._read_needle(f.volume_id, n,
                                      record_heat=record_heat)
                # a local read that outlived the client's budget (slow
                # disk, injected stall) must not get a reply the client
                # stopped waiting for — 504 via the arm below
                _deadline.check(f"volume {f.volume_id} read")
            except CookieMismatch:
                self._reply(404)
                return
            except _deadline.DeadlineExceeded as e:
                # the client's budget ran out somewhere down the read
                # chain (remote shard hop, decode wait): 504, not 404 —
                # the blob may well exist
                self._json({"error": str(e)}, code=504)
                return
            except _failpoint.FailpointError as e:
                # injected read failure: surfaces like the IO error it
                # stands in for
                self._json({"error": str(e)}, code=500)
                return
            except DataCorruptionError as e:
                # corrupt is not missing: a 404 would tell the client
                # the blob never existed; 500 + the scrub counter flags
                # it for repair instead
                from seaweedfs_tpu.stats.metrics import \
                    ScrubCorruptionsFoundCounter
                ScrubCorruptionsFoundCounter.labels("read").inc()
                self._json({"error": str(e)}, code=500)
                return
            except (NeedleError, EcShardNotFound) as e:
                self._json({"error": str(e)}, code=404)
                return
            if got.is_chunk_manifest and \
                    params.get("cm", [""])[0] != "false" and \
                    self._send_chunked(got):
                return
            self._send_needle(got, params)

        do_HEAD = do_GET

        def server_status(self) -> dict:
            return {
                "Version": "seaweedfs-tpu",
                "Volumes": [Store.volume_info(v)
                            for loc in vs.store.locations
                            for v in loc.volumes.values()],
                "Scrub": vs.scrub.status(),
                "Cache": vs.read_cache.stats()
                if vs.read_cache is not None else {"enabled": False},
                "Heat": vs.heat.snapshot()
                if vs.heat is not None else {"enabled": False},
            }

        def _redirect_to_replica(self, f) -> None:
            try:
                resp = master_stub(vs.current_master).LookupVolume(
                    master_pb2.LookupVolumeRequest(
                        volume_ids=[str(f.volume_id)]))
            except grpc.RpcError:
                self._json({"error": "master unreachable"}, code=500)
                return
            candidates = [loc for vl in resp.volume_id_locations
                          for loc in vl.locations if loc.url != vs.url]
            if candidates:
                # never redirect a client INTO a peer this server
                # knows is dead when a healthier replica exists
                loc = min(candidates,
                          key=lambda l: 1 if _breaker.is_open(l.url)
                          else 0)
                self._reply(302, headers={
                    "Location": f"http://{loc.public_url or loc.url}"
                                f"/{f}"})
                return
            self._json({"error": f"volume {f.volume_id} not found"},
                       code=404)

        def _send_chunked(self, got: Needle) -> bool:
            """Resolve a chunk-manifest needle and stream its sub-chunks
            (reference volume_server_handlers_read.go:180-216
            tryHandleChunkedFile). Returns False on a manifest that
            fails to parse, falling back to raw-needle semantics."""
            from seaweedfs_tpu.operation.chunked_file import (
                ChunkedFileReader, load_chunk_manifest)
            try:
                cm = load_chunk_manifest(got.data, got.is_compressed)
            except (ValueError, KeyError, TypeError):
                log.warning("volume %s: unparseable chunk manifest",
                            self.path)
                return False
            reader = ChunkedFileReader(cm.chunks, vs.current_master)
            total = reader.total_size
            headers = {"X-File-Store": "chunked",
                       "Accept-Ranges": "bytes"}
            name = cm.name or (got.name.decode("utf-8", "replace")
                               if got.name else "")
            if name:
                headers["Content-Disposition"] = content_disposition(name)
            if cm.mime and not cm.mime.startswith(
                    "application/octet-stream"):
                headers["Content-Type"] = cm.mime
            status, start, length = 200, 0, total
            rng = self.headers.get("range")
            if rng and rng.startswith("bytes="):
                try:
                    start, end = parse_byte_range(rng, total)
                except ValueError:
                    # RFC 7233 §4.4: 416 carries the representation size
                    self._reply(416, headers={
                        "Content-Range": f"bytes */{total}"})
                    return True
                status = 206
                length = end - start + 1
                headers["Content-Range"] = f"bytes {start}-{end}/{total}"
            self.send_response(status)
            for k, v in headers.items():
                self.send_header(k, v)
            self.send_header("Content-Length", str(length))
            self.end_headers()
            if self.command == "HEAD":
                return True
            sent = 0
            try:
                for block in reader.stream(start, length):
                    self.wfile.write(block)
                    sent += len(block)
            except (RuntimeError, OSError) as e:
                # headers are gone; all we can do is drop the connection
                # so the client sees a short body, like the reference's
                # logged write error
                log.warning("chunked read %s failed after %d bytes: %s",
                            self.path, sent, e)
                self.close_connection = True
            return True

        def _try_send_needle_span(self, f, params) -> tuple:
            """Async zero-copy GET: resolve the needle's payload span
            and reply through send_span (os.sendfile on the async
            connection). Returns (handled, heat_counted): handled
            means a response went out; otherwise the caller falls
            back to the byte path, skipping the heat record iff this
            attempt already counted the admission. Every reply here
            mirrors _send_needle/do_GET byte-for-byte."""
            if vs.heat is not None:
                # admission, exactly where _read_needle counts it
                vs.heat.record(f.volume_id, f.key)
            n = Needle(id=f.key, cookie=f.cookie)
            try:
                got_span = vs.store.read_needle_span(f.volume_id, n)
            except CookieMismatch:
                self._reply(404)
                return True, True
            except NeedleError as e:
                self._json({"error": str(e)}, code=404)
                return True, True
            if got_span is None:
                return False, True
            got, span = got_span
            try:
                _deadline.check(f"volume {f.volume_id} read")
            except _deadline.DeadlineExceeded as e:
                span.close()
                self._json({"error": str(e)}, code=504)
                return True, True
            params = params or {}
            mime = got.mime.decode("utf-8", "replace") if got.mime \
                else ""
            if got.is_compressed or \
                    (got.is_chunk_manifest and
                     params.get("cm", [""])[0] != "false") or \
                    (mime.startswith("image/") and
                     ("width" in params or "height" in params)):
                # the payload itself is needed: byte path owns these
                span.close()
                return False, True
            etag = f'"{got.etag}"'
            if self.headers.get("if-none-match") == etag:
                span.close()
                self._reply(304)
                return True, True
            headers = {"ETag": etag, "Accept-Ranges": "bytes"}
            if got.name:
                headers["Content-Disposition"] = content_disposition(
                    got.name.decode("utf-8", "replace"))
            if mime:
                headers["Content-Type"] = mime
            rng = self.headers.get("range")
            if rng and rng.startswith("bytes="):
                try:
                    start, end = parse_byte_range(rng, span.length)
                except ValueError:
                    span.close()
                    # RFC 7233 §4.4: 416 carries the representation size
                    self._reply(416, headers={
                        "Content-Range": f"bytes */{span.length}"})
                    return True, True
                headers["Content-Range"] = \
                    f"bytes {start}-{end}/{span.length}"
                span.offset += start
                span.length = end - start + 1
                self.send_span(206, span, headers)
                return True, True
            self.send_span(200, span, headers)
            return True, True

        def _send_needle(self, got: Needle,
                         params: Optional[dict] = None) -> None:
            etag = f'"{got.etag}"'
            if self.headers.get("if-none-match") == etag:
                self._reply(304)
                return
            data = got.data
            headers = {"ETag": etag, "Accept-Ranges": "bytes"}
            if got.name:
                headers["Content-Disposition"] = content_disposition(
                    got.name.decode("utf-8", "replace"))
            mime = got.mime.decode("utf-8", "replace") if got.mime else ""
            if mime:
                headers["Content-Type"] = mime
            params = params or {}
            want_resize = mime.startswith("image/") and \
                ("width" in params or "height" in params)
            if got.is_compressed:
                if not want_resize and "gzip" in (
                        self.headers.get("accept-encoding") or ""):
                    headers["Content-Encoding"] = "gzip"
                else:
                    data = gzip.decompress(data)
            if want_resize:
                # EXIF-upright then resize, like the reference read
                # handler (volume_server_handlers_read.go:219-243)
                from seaweedfs_tpu.images import fix_orientation, resized
                data = fix_orientation(data, mime)
                try:
                    width = int(params.get("width", ["0"])[0] or 0)
                    height = int(params.get("height", ["0"])[0] or 0)
                except ValueError:
                    width = height = 0
                data, _, _ = resized(
                    data, mime, width=width, height=height,
                    mode=params.get("mode", [""])[0])
            rng = self.headers.get("range")
            if rng and rng.startswith("bytes=") and not got.is_compressed:
                try:
                    start, end = parse_byte_range(rng, len(data))
                except ValueError:
                    # RFC 7233 §4.4: 416 carries the representation size
                    self._reply(416, headers={
                        "Content-Range": f"bytes */{len(data)}"})
                    return
                headers["Content-Range"] = \
                    f"bytes {start}-{end}/{len(data)}"
                self._reply(206, data[start:end + 1], headers)
                return
            self._reply(200, data, headers)

        # -- write ------------------------------------------------------------

        def do_POST(self):
            upath, sep, query = self.path.partition("?")
            if upath.startswith("/admin/"):
                params = parse_qs(query) if sep else {}
                if upath == "/admin/replicate":
                    self._handle_replicate(params)
                    return
                if upath == "/admin/replicate_delete":
                    self._handle_replicate_delete(params)
                    return
            try:
                f, params = self._parse_path()
            except ValueError as e:
                self._json({"error": str(e)}, code=400)
                return
            body = self._body()
            ctype = self.headers.get("content-type") or ""
            encoding = self.headers.get("content-encoding") or ""
            filename, mime, data = "", ctype, body
            if ctype.startswith("multipart/form-data"):
                try:
                    filename, mime, data, part_enc = \
                        parse_multipart(ctype, body)
                except ValueError as e:
                    self._json({"error": str(e)}, code=400)
                    return
                encoding = part_enc or encoding
            ttl_s = params.get("ttl", [""])[0]
            flags = FLAG_IS_COMPRESSED if encoding.lower() == "gzip" else 0
            if params.get("cm", [""])[0].lower() == "true":
                # chunk-manifest needle (reference
                # needle_parse_upload.go:180: pu.IsChunkedFile)
                flags |= FLAG_IS_CHUNK_MANIFEST
            n = Needle(id=f.key, cookie=f.cookie, data=data,
                       flags=flags,
                       name=filename.encode() if filename else b"",
                       mime=mime.encode() if mime and
                       mime != "application/octet-stream" else b"",
                       ttl=TTL.parse(ttl_s) if ttl_s else None)
            try:
                if params.get("type", [""])[0] == "replicate":
                    _, size = vs.store.write_needle(f.volume_id, n)
                else:
                    size = vs.replicated_write(
                        f.volume_id, n,
                        fsync="fsync" in params)
            except (NeedleError, urllib.error.URLError) as e:
                self._json({"error": str(e)}, code=500)
                return
            self._json({"name": filename, "size": size,
                        "eTag": n.etag}, code=201)

        do_PUT = do_POST

        def _handle_replicate(self, params: dict) -> None:
            vid = int(params["volume"][0])
            try:
                n = Needle.from_bytes(self._body())
                vs.store.write_needle(vid, n)
                vs._invalidate_needle_cache(vid, n.id, "overwrite")
            except NeedleError as e:
                self._json({"error": str(e)}, code=500)
                return
            self._json({"size": n.size}, code=201)

        def _handle_replicate_delete(self, params: dict) -> None:
            vid = int(params["volume"][0])
            n = Needle(id=int(params["key"][0], 16),
                       cookie=int(params["cookie"][0], 16))
            try:
                vs._delete_needle(vid, n)
            except (NeedleError, EcShardNotFound) as e:
                self._json({"error": str(e)}, code=404)
                return
            self._json({}, code=202)

        # -- delete -----------------------------------------------------------

        def do_DELETE(self):
            try:
                f, params = self._parse_path()
            except ValueError as e:
                self._json({"error": str(e)}, code=400)
                return
            n = Needle(id=f.key, cookie=f.cookie)
            try:
                got = vs._read_needle(f.volume_id, n)
                if got.cookie != f.cookie:
                    self._json({"error": "cookie mismatch"}, code=403)
                    return
                chunked_size = None
                if got.is_chunk_manifest:
                    # cascade: all sub-chunks must be gone before the
                    # manifest (reference
                    # volume_server_handlers_write.go:124-137)
                    from seaweedfs_tpu.operation.chunked_file import \
                        load_chunk_manifest
                    try:
                        cm = load_chunk_manifest(got.data,
                                                 got.is_compressed)
                    except (ValueError, KeyError, TypeError) as e:
                        self._json({"error":
                                    f"load chunks manifest: {e}"},
                                   code=500)
                        return
                    try:
                        cm.delete_chunks(vs.current_master)
                    except RuntimeError as e:
                        self._json({"error": f"delete chunks: {e}"},
                                   code=500)
                        return
                    chunked_size = cm.size
                if params.get("type", [""])[0] == "replicate":
                    size = vs._delete_needle(f.volume_id, n)
                else:
                    size = vs.replicated_delete(f.volume_id, n)
                if chunked_size is not None:
                    size = chunked_size
            except CookieMismatch:
                self._json({"error": "cookie mismatch"}, code=403)
                return
            except (NeedleError, EcShardNotFound) as e:
                self._json({"error": str(e)}, code=404)
                return
            self._json({"size": size}, code=202)

    # Prometheus request counter + latency + trace span per HTTP verb
    # (reference volume_server_handlers.go stats wrappers), via the
    # shared role decorator — one instrumentation point for every
    # server role's HTTP plane.
    return instrument_http_handler(Handler, "volumeServer")
