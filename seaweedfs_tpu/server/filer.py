"""Filer server: the namespace server.

HTTP serves the public file path (GET streams chunked content, POST
auto-chunks uploads across volume servers, DELETE removes entries);
gRPC serves the SeaweedFiler service incl. metadata subscriptions.

Reference: weed/server/filer_server.go, filer_server_handlers_write_
autochunk.go:28-300, filer_server_handlers_read.go, filer_grpc_server*.go.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.parse
from seaweedfs_tpu.util.http_server import (FastHandler, ServeConfig,
                                            make_http_server)
from typing import List, Optional

import grpc

from seaweedfs_tpu import rpc
from seaweedfs_tpu.resilience import deadline as _deadline
from seaweedfs_tpu.util import wlog
from seaweedfs_tpu.filer import (Filer, FilerError, MemoryStore, NotFound,
                                 SqliteStore, filechunks, stream)
from seaweedfs_tpu.filer import filer_conf as filer_conf_mod
from seaweedfs_tpu.filer.filechunk_manifest import maybe_manifestize
from seaweedfs_tpu.filer.filer import new_entry
from seaweedfs_tpu.filer.filerstore import join_path, split_path
from seaweedfs_tpu.operation import operations
from seaweedfs_tpu.pb import filer_pb2, master_pb2, master_stub
from seaweedfs_tpu.util import compression
from seaweedfs_tpu.util.chunk_cache import TieredChunkCache
from seaweedfs_tpu.util.cipher import encrypt
from seaweedfs_tpu.wdclient.masterclient import MasterClient

DEFAULT_CHUNK_SIZE = 8 << 20   # -maxMB analog


log = wlog.logger("filer")


def make_filer_store(store: str, meta_dir: Optional[str],
                     options: Optional[dict] = None):
    """FilerStore factory (reference filer.toml store sections +
    filerstore.go registry). `options` carries the store's filer.toml
    section (hostnames, credentials, endpoints)."""
    opts = dict(options or {})
    if store == "memory":
        return MemoryStore()
    if store == "sqlite":
        path = f"{meta_dir}/filer.db" if meta_dir else ":memory:"
        return SqliteStore(path)
    if store in ("weedkv", "kv", "leveldb"):
        from seaweedfs_tpu.filer.stores.kv_store import KvFilerStore
        if not meta_dir:
            raise ValueError("weedkv store needs a -dir/meta_dir")
        return KvFilerStore(f"{meta_dir}/weedkv")
    if store == "redis":
        from seaweedfs_tpu.filer.stores.redis_store import RedisStore
        return RedisStore(
            host=opts.get("host", "127.0.0.1"),
            port=int(opts.get("port", 6379)),
            password=opts.get("password", ""),
            database=int(opts.get("database", 0)))
    if store in ("redis_cluster", "redis_cluster2"):
        from seaweedfs_tpu.filer.stores.redis_store import \
            RedisClusterStore
        addrs = opts.get("addresses", ["localhost:6379"])
        if isinstance(addrs, str):
            addrs = [a.strip() for a in addrs.split(",") if a.strip()]
        return RedisClusterStore(addrs,
                                 password=opts.get("password", ""))
    if store == "etcd":
        from seaweedfs_tpu.filer.stores.etcd_store import EtcdStore
        return EtcdStore(endpoint=opts.get("servers", "127.0.0.1:2379"))
    if store == "mongodb":
        from urllib.parse import urlsplit

        from seaweedfs_tpu.filer.stores.mongodb_store import MongodbStore
        # canonical URIs carry a /db path, ?options, and credentials;
        # urlsplit handles all of them (netloc/hostname/port)
        u = urlsplit(opts.get("uri", "mongodb://localhost:27017"))
        return MongodbStore(host=u.hostname or "localhost",
                            port=u.port or 27017,
                            database=opts.get("database")
                            or (u.path.lstrip("/") or "seaweedfs"))
    if store in ("elastic", "elastic7"):
        from seaweedfs_tpu.filer.stores.elastic_store import ElasticStore
        servers = opts.get("servers", ["localhost:9200"])
        if isinstance(servers, str):
            servers = [servers]
        return ElasticStore(servers=servers,
                            username=opts.get("username", ""),
                            password=opts.get("password", ""))
    if store == "cassandra":
        from seaweedfs_tpu.filer.stores.cassandra_store import \
            CassandraStore
        hosts = opts.get("hosts", ["localhost:9042"])
        if isinstance(hosts, str):
            hosts = [hosts]
        host, _, port = hosts[0].partition(":")
        return CassandraStore(host=host, port=int(port or 9042),
                              keyspace=opts.get("keyspace", "seaweedfs"),
                              username=opts.get("username", ""),
                              password=opts.get("password", ""))
    if store == "hbase":
        from seaweedfs_tpu.filer.stores.hbase_store import HBaseStore
        # reference config key is "zkquorum"; this client dials the
        # region server directly (no ZK walk — hbase_store.py header)
        addr = opts.get("zkquorum", opts.get("address", "localhost:16020"))
        if isinstance(addr, list):
            addr = addr[0]
        # quorum strings are comma-separated ("zk1:2181,zk2:2181");
        # this client dials one endpoint, so take the first
        host, _, port = str(addr).split(",")[0].partition(":")
        return HBaseStore(host=host or "localhost",
                          port=int(port or 16020),
                          table=opts.get("table", "seaweedfs"))
    if store == "mysql":
        from seaweedfs_tpu.filer.stores.abstract_sql import MysqlStore
        return MysqlStore(
            host=opts.get("hostname", "localhost"),
            port=int(opts.get("port", 3306)),
            username=opts.get("username", ""),
            password=opts.get("password", ""),
            database=opts.get("database", "seaweedfs"))
    if store == "postgres":
        from seaweedfs_tpu.filer.stores.abstract_sql import PostgresStore
        return PostgresStore(
            host=opts.get("hostname", "localhost"),
            port=int(opts.get("port", 5432)),
            username=opts.get("username", ""),
            password=opts.get("password", ""),
            database=opts.get("database", "seaweedfs"))
    raise ValueError(
        f"unknown filer store {store!r} (memory | sqlite | weedkv | "
        "redis | etcd | mongodb | cassandra | elastic7 | hbase | "
        "mysql | postgres)")


def _advance_and_filter(events, prefix: str, since: int):
    """(new_since, matching events) for a subscription poll.

    `since` advances past EVERY scanned record, matching or not.
    Streaming loops must use THIS — not the readers' own path_prefix
    parameters — because reader-side filtering hides the timestamps
    needed to advance `since`, and a subscriber whose prefix matches
    nothing then spins at 100% CPU re-scanning the log forever.
    """
    from seaweedfs_tpu.filer.filer_notify import matches_prefix
    matching = []
    for ev in events:
        since = max(since, ev.ts_ns)
        if prefix and not matches_prefix(ev, prefix):
            continue
        matching.append(ev)
    return since, matching


class FilerServer:
    def __init__(self, master_url: str, ip: str = "127.0.0.1",
                 port: int = 8888, store: str = "memory",
                 meta_dir: Optional[str] = None,
                 collection: str = "", replication: str = "",
                 chunk_size: int = DEFAULT_CHUNK_SIZE,
                 cipher: bool = False,
                 cache_dir: Optional[str] = None,
                 peers: Optional[List[str]] = None,
                 store_options: Optional[dict] = None,
                 ingest_parallelism: int = 8,
                 assign_lease_count: int = 0,
                 hedge_reads: bool = False,
                 hedge_delay_ms: float = 10.0,
                 listing_cache_mb: int = 0,
                 serve: Optional[ServeConfig] = None):
        self.master_url = master_url
        self.ip = ip
        self.serve = serve or ServeConfig()
        self.port = port
        self.collection = collection
        self.replication = replication
        self.chunk_size = chunk_size
        self.cipher = cipher
        # ingest pipeline (-ingest.parallelism): chunk k+1 is sliced /
        # read off the socket while chunks k-w..k upload on this shared
        # pool. Constructing the pool spawns NOTHING; threads appear on
        # the first multi-chunk body (house rule, gated by
        # test_perf_gates.test_ingest_pipeline_disabled_overhead).
        self.ingest_parallelism = max(1, ingest_parallelism)
        from seaweedfs_tpu.stats.metrics import IngestPipelineOccupancyGauge
        from seaweedfs_tpu.util.fanout import FanOutPool
        self._ingest_pool = FanOutPool(
            self.ingest_parallelism, f"ingest-{port}",
            inflight_gauge=IngestPipelineOccupancyGauge)
        # fid lease cache (-assign.leaseCount): absent — not merely
        # empty — unless sized, so the disabled assign path is one
        # None check
        self.leases = None
        if assign_lease_count > 1:
            from seaweedfs_tpu.operation.assign_lease import LeaseCache
            self.leases = LeaseCache(count=assign_lease_count)
        # hedged chunk reads (-resilience.hedge): absent unless enabled
        # — the disabled read path is one None check; a constructed
        # Hedger spawns nothing until its first multi-replica fetch
        self.hedger = None
        if hedge_reads:
            from seaweedfs_tpu.resilience import Hedger
            self.hedger = Hedger(
                delay_floor_s=max(hedge_delay_ms, 0.1) / 1000.0,
                name=f"hedge-filer-{port}")
        backend = make_filer_store(store, meta_dir, store_options)
        self.filer = Filer(backend,
                           log_dir=f"{meta_dir}/logs" if meta_dir else None)
        # listing cache (-meta.listingCacheMB): absent — not merely
        # empty — unless sized; when armed, list_entries pages skip
        # the store and the metadata event log drops them on mutation
        self.listing_cache = None
        if listing_cache_mb > 0:
            from seaweedfs_tpu.filer.listing_cache import ListingCache
            self.listing_cache = ListingCache(listing_cache_mb << 20)
            self.filer.attach_listing_cache(self.listing_cache)
        self.filer.on_delete_chunks = self._delete_chunks_async
        self.filer.fetch_chunk_fn = lambda c: stream.fetch_chunk_bytes(
            self.lookup_fid_urls, c.file_id, bytes(c.cipher_key),
            c.is_compressed, hedger=self.hedger)
        self.chunk_cache = TieredChunkCache(
            disk_dir=f"{cache_dir}/chunks" if cache_dir else None)
        from seaweedfs_tpu.rpc import GRPC_PORT_OFFSET
        self.master_client = MasterClient(
            [master_url], client_name="filer",
            grpc_port=port + GRPC_PORT_OFFSET)
        # path-specific rules (/etc/seaweedfs/filer.conf inside the
        # namespace; reference filer_conf.go) — loaded lazily, reloaded
        # whenever that path is written through this filer
        self.filer_conf = filer_conf_mod.FilerConf()
        # multi-filer: merge peer filers' local logs into one view
        # (reference filer/meta_aggregator.go)
        # the signature must SURVIVE restarts (reference persists it in
        # the store): events written before a restart must still be
        # recognizable as our own
        import random
        import struct as _struct
        sig_blob = backend.kv_get(b"filer.store.signature")
        if sig_blob and len(sig_blob) == 4:
            self.filer.signature = _struct.unpack(">i", sig_blob)[0]
        else:
            self.filer.signature = random.randint(1, 0x7FFFFFFF)
            backend.kv_put(b"filer.store.signature",
                           _struct.pack(">i", self.filer.signature))
        self.meta_aggregator = None
        if peers:
            from seaweedfs_tpu.filer.meta_aggregator import MetaAggregator
            self.meta_aggregator = MetaAggregator(
                self.filer, f"{ip}:{port}", peers,
                signature=self.filer.signature,
                log_dir=f"{meta_dir}/aggr-logs" if meta_dir else None)
            self.filer.on_meta_event = self.meta_aggregator.wake
            if self.listing_cache is not None:
                # PEER mutations arrive through the aggregator's
                # subscription into its own MetaLog — the same
                # on_append seam invalidates here with reason="peer",
                # the contract that lets replica filers serve listings
                # without serving peers' stale pages
                lc = self.listing_cache
                self.meta_aggregator.aggr_log.on_append = \
                    lambda directory, ev: lc.apply_event(
                        directory, ev, reason="peer")
        self._grpc_server = None
        self._http_server = None
        self._http_thread = None
        self._stopping = False
        # live KeepConnected peers: (name, grpc_addr) -> [resources]
        self._brokers: dict = {}
        self._broker_lock = threading.Lock()

    def _maybe_reload_conf(self, *paths: str) -> None:
        if filer_conf_mod.FILER_CONF_PATH in paths:
            self.reload_filer_conf()

    def reload_filer_conf(self) -> None:
        """(Re)read /etc/seaweedfs/filer.conf from the namespace
        (reference filer_conf.go loadConfiguration)."""
        try:
            entry = self.filer.find_entry(filer_conf_mod.FILER_CONF_PATH)
        except NotFound:
            self.filer_conf = filer_conf_mod.FilerConf()
            return
        try:
            blob = b"".join(stream.stream_content(
                self.lookup_fid_urls, list(entry.chunks)))
            self.filer_conf = filer_conf_mod.FilerConf.from_bytes(blob)
            log.info("filer conf loaded: %d path rules",
                     len(self.filer_conf.rules))
        except Exception as e:
            log.warning("filer conf unreadable, keeping previous: %s", e)

    # -- lifecycle ------------------------------------------------------------

    @property
    def url(self) -> str:
        return f"{self.ip}:{self.port}"

    def start(self) -> None:
        handler = rpc.generic_handler(filer_pb2, "SeaweedFiler", self,
                                      stats_role="filer")
        self._grpc_server = rpc.make_server(
            f"{self.ip}:{self.port + rpc.GRPC_PORT_OFFSET}", [handler])
        self._http_server = make_http_server(
            (self.ip, self.port), _make_http_handler(self),
            role="filer", serve=self.serve)
        # lint: thread-ok(listener thread; ingress wrappers mint request context)
        self._http_thread = threading.Thread(
            target=self._http_server.serve_forever,
            name=f"filer-http-{self.port}", daemon=True)
        self._http_thread.start()
        self.master_client.start()
        if self.meta_aggregator is not None:
            self.meta_aggregator.start()
        self.reload_filer_conf()
        log.info("filer %s:%d started (store=%s, master=%s)",
                 self.ip, self.port, type(self.filer.store).__name__,
                 self.master_url)

    def stop(self) -> None:
        self._stopping = True
        if self.meta_aggregator is not None:
            self.meta_aggregator.stop()
        self.master_client.stop()
        if self._http_server:
            self._http_server.shutdown()
            self._http_server.server_close()
        if self._grpc_server:
            self._grpc_server.stop(grace=0.2)
        # drain the ingest pool and stop banking leases BEFORE closing
        # the filer store: queued chunk uploads still run, late ones
        # fall back inline (util/grace shutdown contract)
        self._ingest_pool.stop()
        if self.leases is not None:
            self.leases.close()
        self.filer.close()

    # -- helpers --------------------------------------------------------------

    def _delete_chunks_async(self, chunks: List[filer_pb2.FileChunk]) -> None:
        fids = [c.file_id for c in chunks if c.file_id]
        if not fids:
            return

        def run():
            try:
                operations.delete_files(self.master_url, fids)
            except Exception:
                # volumes may already be gone; vacuum will reclaim
                from seaweedfs_tpu.stats import metrics
                metrics.swallowed("filer.delete_chunks")

        # lint: thread-ok(deliberately detached: chunk deletion outlives the client reply)
        threading.Thread(target=run, daemon=True,
                         name="filer-delete-chunks").start()

    def lookup_fid_urls(self, file_id: str) -> List[str]:
        vid = int(file_id.split(",")[0])
        locs = self.master_client.lookup(vid)
        if locs:
            return [l.url for l in locs]
        if self.master_client.lookup_cache_enabled:
            # the client's coalescing cache already asked the master
            # (and holds the negative answer under its TTL); falling
            # through to operations.lookup would consult a SECOND
            # process-wide cache for the same master — doubled miss
            # RPCs, and its entries dodge invalidate_lookup
            return []
        return operations.lookup(self.master_url, vid)

    def _assign(self, collection: str = "", replication: str = "",
                ttl_sec: int = 0, data_center: str = ""):
        if self.leases is not None:
            return self.leases.acquire(
                self.master_url,
                collection=collection or self.collection,
                replication=replication or self.replication,
                ttl=ttl_string(ttl_sec),
                data_center=data_center)
        return operations.assign(
            self.master_url,
            collection=collection or self.collection,
            replication=replication or self.replication,
            ttl=ttl_string(ttl_sec),
            data_center=data_center)

    def _upload_one(self, off: int, piece: bytes, collection: str,
                    replication: str, ttl_sec: int, mime: str,
                    fsync: bool) -> filer_pb2.FileChunk:
        """Assign + upload ONE chunk; the unit both the serial and the
        pipelined paths run. A leased fid that fails at the volume
        server invalidates its whole volume's leases and retries once
        on a fresh direct assign (the lease went stale, not the data)."""
        from seaweedfs_tpu.stats import trace
        cipher_key = b""
        stored = piece
        if self.cipher:
            stored, cipher_key = encrypt(piece)
        sp = trace.span("ingest.chunk_upload", off=off, size=len(piece)) \
            if trace.is_enabled() else trace.NOOP
        with sp:
            a = self._assign(collection, replication, ttl_sec)
            try:
                resp = operations.upload_data(
                    f"{a.url}/{a.fid}", stored, mime=mime, fsync=fsync)
            except (RuntimeError, OSError):
                if self.leases is None:
                    raise
                self.leases.invalidate(a.fid)
                a = operations.assign(
                    self.master_url,
                    collection=collection or self.collection,
                    replication=replication or self.replication,
                    ttl=ttl_string(ttl_sec))
                resp = operations.upload_data(
                    f"{a.url}/{a.fid}", stored, mime=mime, fsync=fsync)
        return filer_pb2.FileChunk(
            file_id=a.fid, offset=off, size=len(piece),
            mtime=time.time_ns(), e_tag=resp.get("eTag", ""),
            cipher_key=cipher_key)

    def _upload_pieces(self, pieces, n_pieces: int, collection: str,
                       replication: str, ttl_sec: int, mime: str,
                       fsync: bool) -> List[filer_pb2.FileChunk]:
        """Run (offset, bytes) pieces through assign+upload.

        Single piece (or -ingest.parallelism 1): fully serial, zero
        threads — the disabled-overhead invariant. Multi-chunk: a
        bounded producer/consumer pipeline. The producer (this thread)
        slices piece k+1 — or reads it off the socket in the streaming
        path — while up to `window` older pieces upload on the shared
        pool. Results assemble in offset order; the first failure
        latches, stops the producer (cancel-on-first-failure: the tail
        is never submitted) and surfaces after every in-flight upload
        drains (reference uploadReaderToChunks' errgroup shape).
        """
        if n_pieces <= 1 or self.ingest_parallelism <= 1:
            return [self._upload_one(off, piece, collection, replication,
                                     ttl_sec, mime, fsync)
                    for off, piece in pieces]
        from collections import deque

        from seaweedfs_tpu.stats import trace
        from seaweedfs_tpu.stats.metrics import \
            IngestPipelineChunksHistogram
        IngestPipelineChunksHistogram.observe(n_pieces)
        window = self.ingest_parallelism
        pending: deque = deque()    # futures in submission order
        chunks: List[filer_pb2.FileChunk] = []
        first_err: Optional[BaseException] = None

        def drain_one():
            nonlocal first_err
            result, exc = pending.popleft().wait()
            if exc is not None:
                if first_err is None:
                    first_err = exc
            else:
                chunks.append(result)

        sp = trace.span("ingest.pipeline", chunks=n_pieces) \
            if trace.is_enabled() else trace.NOOP
        with sp:
            try:
                for off, piece in pieces:
                    if first_err is not None:
                        break
                    pending.append(self._ingest_pool.submit(
                        self._upload_one, off, piece, collection,
                        replication, ttl_sec, mime, fsync))
                    while len(pending) >= window:
                        drain_one()
            except Exception as e:
                # producer failure (e.g. the streaming reader's short
                # read): latch it like a consumer failure so the drain
                # below still runs — in-flight uploads must never be
                # orphaned on the shared pool
                if first_err is None:
                    first_err = e
            while pending:
                drain_one()
        if first_err is not None:
            raise first_err
        chunks.sort(key=lambda c: c.offset)
        return chunks

    def upload_to_chunks(self, data: bytes, collection: str = "",
                         replication: str = "", ttl_sec: int = 0,
                         mime: str = "",
                         fsync: bool = False) -> List[filer_pb2.FileChunk]:
        """Split `data` into chunkSize pieces, assign+upload each
        (reference uploadReaderToChunks)."""
        size = len(data)
        n_pieces = max(1, -(-size // self.chunk_size))
        pieces = ((off, data[off:off + self.chunk_size])
                  for off in range(0, max(size, 1), self.chunk_size))
        return self._upload_pieces(pieces, n_pieces, collection,
                                   replication, ttl_sec, mime, fsync)

    def upload_stream_to_chunks(self, reader, size: int,
                                collection: str = "",
                                replication: str = "", ttl_sec: int = 0,
                                mime: str = "", fsync: bool = False
                                ) -> List[filer_pb2.FileChunk]:
        """Like upload_to_chunks but the body arrives through `reader`
        (the request socket): chunk k+1 is read off the wire while
        earlier chunks upload — the whole body is never resident."""
        n_pieces = max(1, -(-size // self.chunk_size))

        def pieces():
            off = 0
            while off < size or off == 0:
                want = min(self.chunk_size, size - off)
                piece = reader.read(want) if want else b""
                if want and len(piece) != want:
                    raise OSError(
                        f"short read: body ended {off + len(piece)}"
                        f"/{size}")
                yield off, piece
                off += max(len(piece), 1)

        return self._upload_pieces(pieces(), n_pieces, collection,
                                   replication, ttl_sec, mime, fsync)

    def save_manifest_blob(self, data: bytes) -> filer_pb2.FileChunk:
        a = self._assign()
        resp = operations.upload_data(f"{a.url}/{a.fid}", data)
        return filer_pb2.FileChunk(
            file_id=a.fid, size=len(data), mtime=time.time_ns(),
            e_tag=resp.get("eTag", ""))

    # -- gRPC: entry CRUD -----------------------------------------------------

    def LookupDirectoryEntry(self, request, context):
        try:
            # Filer.find_entry applies lazy TTL expiry (purge + chunk GC)
            e = self.filer.find_entry(
                join_path(request.directory, request.name))
        except NotFound:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"{request.directory}/{request.name}")
        return filer_pb2.LookupDirectoryEntryResponse(entry=e)

    def ListEntries(self, request, context):
        limit = request.limit or 1024
        entries = self.filer.list_entries(
            request.directory,
            start_name=request.start_from_file_name,
            inclusive=request.inclusive_start_from,
            limit=limit, prefix=request.prefix)
        for e in entries:
            yield filer_pb2.ListEntriesResponse(entry=e)

    def CreateEntry(self, request, context):
        try:
            self.filer.create_entry(
                request.directory, request.entry, o_excl=request.o_excl,
                from_other_cluster=request.is_from_other_cluster,
                signatures=list(request.signatures))
            self._maybe_reload_conf(
                join_path(request.directory, request.entry.name))
            return filer_pb2.CreateEntryResponse()
        except FilerError as e:
            return filer_pb2.CreateEntryResponse(error=str(e))

    def UpdateEntry(self, request, context):
        self.filer.update_entry(
            request.directory, request.entry,
            from_other_cluster=request.is_from_other_cluster,
            signatures=list(request.signatures))
        self._maybe_reload_conf(
            join_path(request.directory, request.entry.name))
        return filer_pb2.UpdateEntryResponse()

    def AppendToEntry(self, request, context):
        self.filer.append_chunks(
            join_path(request.directory, request.entry_name),
            list(request.chunks))
        return filer_pb2.AppendToEntryResponse()

    def DeleteEntry(self, request, context):
        try:
            self.filer.delete_entry(
                join_path(request.directory, request.name),
                recursive=request.is_recursive,
                ignore_recursive_error=request.ignore_recursive_error,
                delete_data=request.is_delete_data,
                from_other_cluster=request.is_from_other_cluster,
                signatures=list(request.signatures))
            self._maybe_reload_conf(
                join_path(request.directory, request.name))
            return filer_pb2.DeleteEntryResponse()
        except FilerError as e:
            return filer_pb2.DeleteEntryResponse(error=str(e))

    def AtomicRenameEntry(self, request, context):
        try:
            self.filer.atomic_rename(
                request.old_directory, request.old_name,
                request.new_directory, request.new_name)
        except NotFound:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"{request.old_directory}/{request.old_name}")
        self._maybe_reload_conf(
            join_path(request.old_directory, request.old_name),
            join_path(request.new_directory, request.new_name))
        return filer_pb2.AtomicRenameEntryResponse()

    # -- gRPC: volume plumbing ------------------------------------------------

    def AssignVolume(self, request, context):
        try:
            a = self._assign(request.collection, request.replication,
                             request.ttl_sec, request.data_center)
        except RuntimeError as e:
            return filer_pb2.AssignVolumeResponse(error=str(e))
        return filer_pb2.AssignVolumeResponse(
            file_id=a.fid, url=a.url, public_url=a.public_url,
            count=a.count,
            collection=request.collection or self.collection,
            replication=request.replication or self.replication)

    def LookupVolume(self, request, context):
        """All requested vids resolve in ONE batched master round trip
        (operations.lookup_many: misses fuse through the coalescing
        cache when -meta.lookupTTL arms it; disabled it loops the
        same per-vid RPCs the old code made). Per-vid failures — and
        unparseable vids — answer as empty location lists, exactly
        like the old per-vid error handling (ROADMAP item 4
        residual)."""
        resp = filer_pb2.LookupVolumeResponse()
        vids = {}
        for vid_s in request.volume_ids:
            try:
                vids[int(vid_s)] = None
            except ValueError:
                pass
        got = operations.lookup_many(self.master_url, list(vids)) \
            if vids else {}
        for vid_s in dict.fromkeys(request.volume_ids):
            locs = resp.locations_map[vid_s]
            try:
                urls = got.get(int(vid_s), [])
            except ValueError:
                urls = []
            for u in urls:
                locs.locations.add(url=u, public_url=u)
        return resp

    def CollectionList(self, request, context):
        resp = master_stub(self.master_url).CollectionList(
            master_pb2.CollectionListRequest(
                include_normal_volumes=request.include_normal_volumes,
                include_ec_volumes=request.include_ec_volumes))
        return filer_pb2.CollectionListResponse(
            collections=[filer_pb2.Collection(name=c.name)
                         for c in resp.collections])

    def DeleteCollection(self, request, context):
        master_stub(self.master_url).CollectionDelete(
            master_pb2.CollectionDeleteRequest(name=request.collection))
        return filer_pb2.DeleteCollectionResponse()

    def Statistics(self, request, context):
        resp = master_stub(self.master_url).Statistics(
            master_pb2.StatisticsRequest(
                replication=request.replication,
                collection=request.collection, ttl=request.ttl))
        return filer_pb2.StatisticsResponse(
            total_size=resp.total_size, used_size=resp.used_size,
            file_count=resp.file_count)

    def GetFilerConfiguration(self, request, context):
        return filer_pb2.GetFilerConfigurationResponse(
            masters=[self.master_url], replication=self.replication,
            collection=self.collection,
            max_mb=self.chunk_size >> 20,
            dir_buckets="/buckets", cipher=self.cipher)

    # -- gRPC: subscriptions --------------------------------------------------

    def SubscribeMetadata(self, request, context):
        """Cluster-wide merged stream when peers are configured (the
        MetaAggregator view); the local log otherwise.

        `since` advances past EVERY scanned record, matching or not —
        advancing only on yielded records made a prefix subscriber spin
        at 100% CPU once any unrelated event existed (the wait call saw
        newer data and returned immediately, forever)."""
        if self.meta_aggregator is not None:
            agg = self.meta_aggregator
            since = request.since_ns
            while context.is_active() and not self._stopping:
                ver = agg.version  # read BEFORE scanning: no lost wakeups
                events = agg.events_since(since)
                since, matching = _advance_and_filter(
                    events, request.path_prefix, since)
                yield from matching
                if not events:
                    agg.wait_for_version(ver, timeout=0.5)
            return
        yield from self.SubscribeLocalMetadata(request, context)

    def SubscribeLocalMetadata(self, request, context):
        since = request.since_ns
        while context.is_active() and not self._stopping:
            events = self.filer.meta_log.read_events_since(since)
            since, matching = _advance_and_filter(
                events, request.path_prefix, since)
            yield from matching
            if not events:
                self.filer.meta_log.wait_for_data(since, timeout=0.5)

    # -- gRPC: broker registration / discovery --------------------------------

    def KeepConnected(self, request_iterator, context):
        """Peers (message brokers) hold this stream open, advertising
        their gRPC address and owned resources; LocateBroker answers
        from the live set (reference filer_grpc_server.go
        KeepConnected/LocateBroker)."""
        from seaweedfs_tpu.rpc import peer_ip
        key = None
        token = object()   # this stream's ownership marker: a quickly
        # reconnecting broker reuses the same (name, addr) key, and the
        # OLD stream's teardown must not deregister the NEW stream
        try:
            for req in request_iterator:
                new_key = (req.name,
                           f"{peer_ip(context)}:{req.grpc_port}")
                with self._broker_lock:
                    if key is not None and key != new_key:
                        cur = self._brokers.get(key)
                        if cur and cur[0] is token:
                            # re-advertised identity: drop our old entry
                            self._brokers.pop(key, None)
                    key = new_key
                    self._brokers[key] = (token, list(req.resources))
                yield filer_pb2.KeepConnectedResponse()
                if not context.is_active() or self._stopping:
                    break
        finally:
            if key is not None:
                with self._broker_lock:
                    cur = self._brokers.get(key)
                    if cur and cur[0] is token:
                        self._brokers.pop(key, None)

    def LocateBroker(self, request, context):
        with self._broker_lock:
            brokers = {addr: res for (_n, addr), (_tok, res)
                       in self._brokers.items()}
        for addr, resources in brokers.items():
            if request.resource in resources:
                return filer_pb2.LocateBrokerResponse(
                    found=True,
                    resources=[filer_pb2.LocateBrokerResponse.Resource(
                        grpc_addresses=addr,
                        resource_count=len(resources))])
        return filer_pb2.LocateBrokerResponse(
            found=False,
            resources=[filer_pb2.LocateBrokerResponse.Resource(
                grpc_addresses=addr, resource_count=len(res))
                for addr, res in sorted(brokers.items())])

    # -- gRPC: KV -------------------------------------------------------------

    def KvGet(self, request, context):
        v = self.filer.store.kv_get(request.key)
        if v is None:
            return filer_pb2.KvGetResponse(error="not found")
        return filer_pb2.KvGetResponse(value=v)

    def KvPut(self, request, context):
        self.filer.store.kv_put(request.key, request.value)
        return filer_pb2.KvPutResponse()


# -- HTTP layer ---------------------------------------------------------------


def _entry_json(e: filer_pb2.Entry, directory: str) -> dict:
    return {
        "FullPath": join_path(directory, e.name),
        "Mtime": e.attributes.mtime,
        "Crtime": e.attributes.crtime,
        "Mode": e.attributes.file_mode,
        "Uid": e.attributes.uid,
        "Gid": e.attributes.gid,
        "Mime": e.attributes.mime,
        "Replication": e.attributes.replication,
        "Collection": e.attributes.collection,
        "TtlSec": e.attributes.ttl_sec,
        "FileSize": filechunks.total_size(e.chunks),
        "IsDirectory": e.is_directory,
        "chunks": len(e.chunks),
    }


def _make_http_handler(fs: FilerServer):
    class Handler(FastHandler):
        protocol_version = "HTTP/1.1"
        disable_nagle_algorithm = True  # small replies must not wait on delayed ACKs

        def log_message(self, fmt, *args):
            pass

        def _reply(self, code: int, body: bytes = b"",
                   headers: Optional[dict] = None) -> None:
            self.send_response(code)
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            if self.command != "HEAD" and body:
                self.wfile.write(body)

        def _json(self, obj, code: int = 200,
                  headers: Optional[dict] = None) -> None:
            hs = {"Content-Type": "application/json"}
            hs.update(headers or {})
            self._reply(code, json.dumps(obj).encode(), hs)

        def _path_and_params(self):
            u = urllib.parse.urlparse(self.path)
            return (urllib.parse.unquote(u.path) or "/",
                    urllib.parse.parse_qs(u.query))

        def _body(self) -> bytes:
            # framing-aware (Content-Length or chunked), identical on
            # both server models
            return self.read_body()

        # -- read -------------------------------------------------------------

        def do_GET(self):
            path, params = self._path_and_params()
            if path in ("/debug/trace", "/debug/requests"):
                # reserved collector/flight-recorder paths (never
                # namespace lookups): cluster.trace fans out over the
                # filer's data port like every other role
                from seaweedfs_tpu.stats import cluster_trace
                self._json(cluster_trace.debug_payload(
                    self.path, "filer", fs.url))
                return
            try:
                entry = fs.filer.find_entry(path)
            except NotFound:
                self._json({"error": f"{path} not found"}, code=404)
                return
            if entry.is_directory:
                if self.headers.get("x-sw-object-only"):
                    # gateway proxy mode (S3): a directory is not an
                    # object — 404 instead of a listing, so the gateway
                    # can proxy GETs in one hop without a pre-lookup
                    self._json({"error": f"{path} is a directory"},
                               code=404)
                    return
                self._list_dir(path, params)
                return
            self._serve_file(path, entry)

        do_HEAD = do_GET

        def _list_dir(self, path: str, params: dict) -> None:
            try:
                limit = int(params.get("limit", ["100"])[0])
            except ValueError:
                self._json({"error": "bad limit"}, code=400)
                return
            last = params.get("lastFileName", [""])[0]
            entries = fs.filer.list_entries(path, start_name=last,
                                            inclusive=False, limit=limit)
            # browsers get the directory-browser UI (reference
            # weed/server/filer_ui/ renders HTML when the client
            # accepts it; API clients keep the JSON listing)
            if "text/html" in (self.headers.get("Accept") or ""):
                self._list_dir_html(path, entries)
                return
            self._json({
                "Path": path,
                "Entries": [_entry_json(e, path) for e in entries],
                "Limit": limit,
                "LastFileName": entries[-1].name if entries else "",
                "ShouldDisplayLoadMore": len(entries) == limit,
            })

        def _list_dir_html(self, path: str, entries) -> None:
            import html as _html

            def link(p: str) -> str:
                # percent-encode THEN html-escape: names may contain
                # URL-reserved chars (#, ?, %) the browser would
                # otherwise misparse out of the href
                return _html.escape(urllib.parse.quote(p), quote=True)

            crumbs, acc = ['<a href="/">/</a>'], ""
            for part in [p for p in path.split("/") if p]:
                acc += "/" + part
                crumbs.append(
                    f'<a href="{link(acc)}/">{_html.escape(part)}</a>')
            rows = []
            for e in entries:
                href = link(join_path(path, e.name))
                name = _html.escape(e.name)
                if e.is_directory:
                    rows.append(
                        f'<tr><td><a href="{href}/">{name}/</a></td>'
                        "<td>-</td></tr>")
                else:
                    # same size formula as the JSON listing and the
                    # file-serving path (filechunks.total_size)
                    size = filechunks.total_size(e.chunks)
                    rows.append(
                        f'<tr><td><a href="{href}">{name}</a></td>'
                        f"<td>{size}</td></tr>")
            body = ("<html><head><title>seaweedfs-tpu filer</title>"
                    "</head><body>"
                    f"<h1>Filer {fs.ip}:{fs.port}</h1>"
                    f"<p>{' / '.join(crumbs)}</p>"
                    "<table border=1 cellpadding=4>"
                    "<tr><th>name</th><th>size</th></tr>"
                    + "".join(rows) + "</table></body></html>").encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/html; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            if self.command != "HEAD":
                self.wfile.write(body)

        def _serve_file(self, path: str, entry: filer_pb2.Entry) -> None:
            size = filechunks.total_size(entry.chunks)
            etag = f'"{filechunks.etag_of_chunks(list(entry.chunks))}"' \
                if entry.chunks else '""'
            if self.headers.get("If-None-Match") == etag:
                self._reply(304)
                return
            headers = {"ETag": etag, "Accept-Ranges": "bytes"}
            if entry.attributes.mime:
                headers["Content-Type"] = entry.attributes.mime
            rng = self.headers.get("Range")
            offset, length, code = 0, size, 200
            if rng and rng.startswith("bytes="):
                try:
                    start_s, _, end_s = rng[len("bytes="):].partition("-")
                    if not start_s:
                        offset = max(0, size - int(end_s))
                        end = size - 1
                    else:
                        offset = int(start_s)
                        end = min(int(end_s) if end_s else size - 1,
                                  size - 1)
                    if offset > end or offset < 0:
                        raise ValueError
                    length = end - offset + 1
                    headers["Content-Range"] = \
                        f"bytes {offset}-{end}/{size}"
                    code = 206
                except ValueError:
                    # RFC 7233 §4.4: 416 carries the representation size
                    self._reply(416, headers={
                        "Content-Range": f"bytes */{size}"})
                    return
            if self.command == "HEAD":
                headers["Content-Length"] = str(length)
                self.send_response(code)
                for k, v in headers.items():
                    self.send_header(k, v)
                self.end_headers()
                return
            if fs.master_client.lookup_cache_enabled:
                # only chunks the requested window actually touches: a
                # 1KB Range read of a 10,000-chunk file must not
                # resolve 10,000 vids the stream will never fetch
                chunk_vids = {int(c.file_id.split(",")[0])
                              for c in entry.chunks
                              if c.file_id and c.offset < offset + length
                              and c.offset + c.size > offset}
                if len(chunk_vids) > 1:
                    # resolve every chunk's volume in ONE batched
                    # master round trip; the per-chunk lookups inside
                    # stream_content then answer from the cache (a
                    # 64-chunk file used to cost up to 64 round trips)
                    fs.master_client.lookup_many(chunk_vids)
            try:
                data = b"".join(stream.stream_content(
                    fs.lookup_fid_urls, list(entry.chunks), offset,
                    length, cache=fs.chunk_cache, hedger=fs.hedger))
            except _deadline.DeadlineExceeded as e:
                self._json({"error": str(e)}, code=504)
                return
            except IOError as e:
                # the FAILED chunk's fetch exhausted every replica the
                # lookup returned: drop that vid's cached belief so
                # the retry re-asks the master. The error text is
                # authoritative for WHICH vid (manifest-inner chunks
                # never appear in entry.chunks, so no membership
                # check); unrecognized text invalidates NOTHING —
                # blanket-dropping all 64 would turn one bad volume
                # into a 64-vid re-resolve storm on every retry.
                import re as _re
                m = _re.search(r"fetch (\d+),", str(e))
                if m:
                    fs.master_client.invalidate_lookup(int(m.group(1)))
                self._json({"error": str(e)}, code=500)
                return
            self._reply(code, data, headers)

        # -- write ------------------------------------------------------------

        def do_POST(self):
            path, params = self._path_and_params()
            ctype = self.headers.get("Content-Type") or ""
            clen = int(self.headers.get("Content-Length") or 0)
            # multi-chunk non-multipart bodies stream off the socket
            # chunk by chunk (read overlaps upload; the body is never
            # resident). Any reply sent before the body is drained must
            # drop the connection — leftover body bytes would desync
            # the next keep-alive request.
            streaming = (clen > fs.chunk_size
                         and not ctype.startswith("multipart/form-data"))
            body = b"" if streaming else self._body()
            filename, mime, data = "", ctype, body
            if ctype.startswith("multipart/form-data"):
                from seaweedfs_tpu.server.volume import parse_multipart
                try:
                    filename, mime, data, enc = parse_multipart(ctype, body)
                    if enc == "gzip":
                        data = compression.decompress(data)
                except ValueError as e:
                    self._json({"error": str(e)}, code=400)
                    return
            if path.endswith("/"):
                path = path + filename if filename else path[:-1]
            directory, name = split_path(path)
            if not name:
                self.close_connection = streaming or self.close_connection
                self._json({"error": "cannot write to /"}, code=400)
                return
            collection = params.get("collection", [""])[0]
            replication = params.get("replication", [""])[0]
            ttl_param = params.get("ttl", [""])[0]
            rule = fs.filer_conf.match(join_path(directory, name))
            fsync = "fsync" in params
            if rule is not None:
                collection = collection or rule.collection
                replication = replication or rule.replication
                ttl_param = ttl_param or rule.ttl
                fsync = fsync or rule.fsync
            try:
                ttl_sec = _parse_ttl_seconds(ttl_param)
            except ValueError:
                self.close_connection = streaming or self.close_connection
                self._json({"error": "bad ttl"}, code=400)
                return
            try:
                if streaming:
                    chunks = fs.upload_stream_to_chunks(
                        self.rfile, clen, collection=collection,
                        replication=replication, ttl_sec=ttl_sec,
                        mime=mime, fsync=fsync)
                    data_size = clen
                else:
                    chunks = fs.upload_to_chunks(
                        data, collection=collection,
                        replication=replication, ttl_sec=ttl_sec,
                        mime=mime, fsync=fsync)
                    data_size = len(data)
                chunks = maybe_manifestize(fs.save_manifest_blob, chunks)
            except _deadline.DeadlineExceeded as e:
                # the client's budget ran out mid-ingest: the remaining
                # chunks were never uploaded, and the 504 says so
                # before the filer wastes more work on an abandoned body
                self.close_connection = streaming or self.close_connection
                self._json({"error": str(e)}, code=504)
                return
            except (RuntimeError, OSError) as e:
                # mid-stream failure: part of the body may still sit
                # unread on the socket
                self.close_connection = streaming or self.close_connection
                self._json({"error": str(e)}, code=500)
                return
            entry = new_entry(
                name, mime=mime if mime and
                mime != "application/octet-stream" else "",
                ttl_sec=ttl_sec, collection=collection,
                replication=replication)
            entry.chunks.extend(chunks)
            try:
                fs.filer.create_entry(directory, entry)
            except FilerError as e:
                self._json({"error": str(e)}, code=500)
                return
            fs._maybe_reload_conf(join_path(directory, name))
            self._json({"name": name, "size": data_size}, code=201,
                       headers={"ETag": filechunks.etag_of_chunks(chunks)})

        do_PUT = do_POST

        # -- delete -----------------------------------------------------------

        def do_DELETE(self):
            path, params = self._path_and_params()
            recursive = params.get("recursive", [""])[0] == "true"
            ignore = params.get("ignoreRecursiveError", [""])[0] == "true"
            try:
                fs.filer.delete_entry(path, recursive=recursive,
                                      ignore_recursive_error=ignore)
            except FilerError as e:
                self._json({"error": str(e)}, code=409)
                return
            self._reply(204)

    from seaweedfs_tpu.stats.metrics import instrument_http_handler
    return instrument_http_handler(Handler, "filer")


def _parse_ttl_seconds(s: str) -> int:
    if not s:
        return 0
    units = {"s": 1, "m": 60, "h": 3600, "d": 86400, "w": 604800,
             "M": 2592000, "y": 31536000}
    if s[-1] in units:
        return int(s[:-1]) * units[s[-1]]
    return int(s)


def ttl_string(ttl_sec: int) -> str:
    """Seconds → the volume TTL grammar (count ≤ 255 + unit), rounding
    up to the smallest unit that fits (a volume TTL is one byte count +
    one byte unit, storage/superblock.py TTL.parse)."""
    if ttl_sec <= 0:
        return ""
    for suffix, secs in (("s", 1), ("m", 60), ("h", 3600), ("d", 86400),
                         ("w", 604800), ("M", 2592000), ("y", 31536000)):
        count = -(-ttl_sec // secs)  # ceil: never expire early
        if count <= 255:
            return f"{count}{suffix}"
    return "255y"
