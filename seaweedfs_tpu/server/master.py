"""Master server: cluster control plane.

One process owns the Topology, assigns file ids, grows volumes, drives
vacuum, and feeds every client a live vid->location cache over the
KeepConnected stream.

Reference: weed/server/master_server.go, master_grpc_server.go
(SendHeartbeat :20-176, KeepConnected :178-233), master_server_handlers*.go,
topology/topology_vacuum.go.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time

import grpc
from seaweedfs_tpu.util.http_server import (FastHandler, ServeConfig,
                                            make_http_server)
from typing import Dict, List, Optional, Set
from urllib.parse import parse_qs

from seaweedfs_tpu import rpc
from seaweedfs_tpu.util import wlog
from seaweedfs_tpu.pb import master_pb2, raft_pb2, volume_server_pb2, \
    volume_stub
from seaweedfs_tpu.server import convert
from seaweedfs_tpu.server.raft import NotLeader, RaftNode
from seaweedfs_tpu.storage.superblock import ReplicaPlacement
from seaweedfs_tpu.topology.sequence import MemorySequencer
from seaweedfs_tpu.topology.topology import Topology
from seaweedfs_tpu.topology.volume_growth import NoFreeSlots, VolumeGrowth, growth_count


log = wlog.logger("master")


class AdminLock:
    """Cluster-wide exclusive admin lease (reference
    wdclient/exclusive_locks + master_grpc_server_admin.go)."""

    RENEW_WINDOW_NS = 10 * 1_000_000_000

    def __init__(self):
        self._lock = threading.Lock()
        self._token = 0
        self._ts_ns = 0

    def lease(self, previous_token: int) -> tuple:
        now = time.monotonic_ns()
        with self._lock:
            held = self._token and now - self._ts_ns < self.RENEW_WINDOW_NS
            if held and previous_token != self._token:
                raise PermissionError("admin lock held by another client")
            self._token = now
            self._ts_ns = now
            return self._token, self._ts_ns

    def release(self, previous_token: int) -> None:
        with self._lock:
            if previous_token == self._token:
                self._token = 0
                self._ts_ns = 0


def plan_scrub_stagger(urls: List[str],
                       interval_s: float) -> List[tuple]:
    """Spread one scrub window over the fleet: [(url, wait_before_s)].

    Node i starts interval_s/n after node i-1, so the whole topology
    is covered once per interval while at most one node begins its
    scan at any instant — pure over the url list, unit-testable
    without a cluster (the house planning-function pattern)."""
    if not urls:
        return []
    gap = interval_s / len(urls)
    return [(url, 0.0 if i == 0 else gap) for i, url in enumerate(urls)]


class MasterServer:
    SEQ_WATERMARK_GAP = 10000  # ids raft-committed ahead of allocation

    def __init__(self, ip: str = "127.0.0.1", port: int = 9333,
                 meta_dir: Optional[str] = None,
                 volume_size_limit_mb: int = 30 * 1024,
                 default_replication: str = "000",
                 pulse_seconds: float = 5.0,
                 garbage_threshold: float = 0.3,
                 peers: Optional[List[str]] = None,
                 raft_election_timeout: float = 0.5,
                 maintenance_scripts: Optional[List[str]] = None,
                 maintenance_interval_s: float = 17 * 60,
                 scrub_interval_s: float = 0.0,
                 scrub_throttle_mbps: float = 0.0,
                 lifecycle: Optional[object] = None,
                 sequencer_type: str = "memory",
                 sequencer_node_id: Optional[int] = None,
                 sequencer_etcd_urls: str = "127.0.0.1:2379",
                 serve: Optional[ServeConfig] = None):
        self.ip = ip
        self.port = port
        self.meta_dir = meta_dir
        self.serve = serve or ServeConfig()
        self.default_replication = default_replication
        self.garbage_threshold = garbage_threshold
        if sequencer_type == "snowflake":
            # coordination-free ids (reference [master.sequencer]
            # type=snowflake; the etcd kind needs an etcd server).
            # node_id must differ per master: configured explicitly, or
            # derived from ip:port (NOT the port alone — multi-master
            # clusters conventionally share a port across hosts)
            from seaweedfs_tpu.topology.sequence import SnowflakeSequencer
            import zlib
            node_id = sequencer_node_id if sequencer_node_id is not None \
                else zlib.crc32(f"{ip}:{port}".encode()) & 0x3FF
            seq = SnowflakeSequencer(node_id=node_id)
        elif sequencer_type == "etcd":
            # externally-coordinated contiguous ids (reference
            # [master.sequencer] type=etcd, sequence/etcd_sequencer.go)
            from seaweedfs_tpu.topology.sequence import EtcdSequencer
            seq = EtcdSequencer(
                endpoint=sequencer_etcd_urls.split(",")[0].strip())
        elif sequencer_type in ("memory", ""):
            seq = MemorySequencer(start=self._load_sequence())
        else:
            raise ValueError(
                f"unknown sequencer type {sequencer_type!r} "
                "(memory | snowflake | etcd)")
        self.topo = Topology(volume_size_limit=volume_size_limit_mb << 20,
                             sequencer=seq, pulse_seconds=pulse_seconds)
        self.growth = VolumeGrowth(self.topo)
        self.admin_lock = AdminLock()
        # raft: single-node (no peers) degenerates to permanent leader.
        # NB: RaftNode.__init__ replays the committed log through
        # _raft_apply before self.raft exists — the apply/restore
        # callbacks must not touch self.raft (they use _applied_state).
        self._applied_state = {"max_volume_id": 0, "sequence": 0}
        self._seq_watermark = 0
        self._seq_lock = threading.Lock()
        self.raft = RaftNode(
            f"{ip}:{port}", peers or [], meta_dir,
            apply=self._raft_apply,
            snapshot_fn=lambda: dict(self._applied_state),
            restore_fn=self._raft_restore,
            election_timeout=raft_election_timeout)
        self._grpc_server = None
        self._http_server = None
        self._http_thread = None
        self._grow_lock = threading.Lock()
        # heartbeat stream identity per node url (reconnect-safe cleanup)
        self._node_streams: Dict[str, object] = {}
        # KeepConnected subscribers: name -> queue of VolumeLocation
        self._subscribers: Dict[int, queue.Queue] = {}
        self._client_addrs: Dict[int, tuple] = {}  # key -> (type, addr)
        self._sub_seq = 0
        self._sub_lock = threading.Lock()
        self._stopping = False
        # leader-only admin-script cron (reference
        # master_server.go:187-263 startAdminScripts; defaults come
        # from the master.toml scaffold, scaffold.go:422-433)
        self.maintenance_scripts = maintenance_scripts or []
        self.maintenance_interval_s = maintenance_interval_s
        self._maint_thread: Optional[threading.Thread] = None
        self._maint_wake = threading.Event()
        # leader-only scrub scheduler: every interval, each volume
        # server gets one VolumeScrubStart, staggered across the
        # window so the fleet never scrubs in lockstep (0 = disabled)
        self.scrub_interval_s = scrub_interval_s
        self.scrub_throttle_mbps = scrub_throttle_mbps
        self._scrub_thread: Optional[threading.Thread] = None
        self._scrub_wake = threading.Event()
        # heat-driven lifecycle policy engine (-lifecycle): absent —
        # not merely idle — unless configured, so a default master
        # pays nothing (no engine object, no thread, heartbeats
        # byte-identical; test_lifecycle_disabled_overhead)
        self.lifecycle = None
        if lifecycle is not None:
            from seaweedfs_tpu.lifecycle import LifecycleEngine
            self.lifecycle = LifecycleEngine(self, lifecycle)

    # -- lifecycle -----------------------------------------------------------

    @property
    def url(self) -> str:
        return f"{self.ip}:{self.port}"

    def start(self) -> None:
        if self.port == 0:
            raise ValueError("master port must be fixed (grpc = port+10000)")
        handler = rpc.generic_handler(master_pb2, "Seaweed", self,
                                      stats_role="master")
        raft_handler = rpc.generic_handler(raft_pb2, "Raft", self.raft,
                                           stats_role="raft")
        self._grpc_server = rpc.make_server(
            f"{self.ip}:{self.port + rpc.GRPC_PORT_OFFSET}",
            [handler, raft_handler])
        self.raft.start()
        self._http_server = make_http_server(
            (self.ip, self.port), _make_http_handler(self),
            role="master", serve=self.serve)
        # lint: thread-ok(listener thread; ingress wrappers mint request context)
        self._http_thread = threading.Thread(
            target=self._http_server.serve_forever, name="master-http",
            daemon=True)
        self._http_thread.start()
        if self.maintenance_scripts:
            # lint: thread-ok(maintenance cron daemon; no request context)
            self._maint_thread = threading.Thread(
                target=self._maintenance_loop, name="master-maintenance",
                daemon=True)
            self._maint_thread.start()
        if self.scrub_interval_s > 0:
            # lint: thread-ok(scrub scheduler daemon; no request context)
            self._scrub_thread = threading.Thread(
                target=self._scrub_loop, name="master-scrub",
                daemon=True)
            self._scrub_thread.start()
        if self.lifecycle is not None:
            self.lifecycle.start()
        log.info("master %s started (grpc :%d)", self.url,
                 self.port + rpc.GRPC_PORT_OFFSET)

    def stop(self) -> None:
        log.info("master %s stopping", self.url)
        self._stopping = True
        self._maint_wake.set()
        self._scrub_wake.set()
        if self.lifecycle is not None:
            self.lifecycle.stop()
        self.raft.stop()
        self._save_sequence()
        if self._http_server:
            self._http_server.shutdown()
            self._http_server.server_close()
        if self._grpc_server:
            self._grpc_server.stop(grace=0.2)

    def _sequence_path(self) -> Optional[str]:
        return os.path.join(self.meta_dir, "sequence.json") \
            if self.meta_dir else None

    def _load_sequence(self) -> int:
        p = self._sequence_path() if self.meta_dir else None
        if p and os.path.exists(p):
            with open(p) as f:
                return json.load(f).get("next", 1)
        return 1

    def _save_sequence(self) -> None:
        if not getattr(self.topo.sequence, "persistable", True):
            return  # snowflake ids must not seed a later memory run
        p = self._sequence_path()
        if p:
            os.makedirs(self.meta_dir, exist_ok=True)
            tmp = p + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"next": self.topo.sequence.peek}, f)
            os.replace(tmp, p)

    # -- maintenance cron ------------------------------------------------------

    def _maintenance_loop(self) -> None:
        """Leader-only: run the configured shell scripts every
        interval, so EC encode/rebuild/balance and vacuum happen with
        no operator action (reference master_server.go:187-263)."""
        from seaweedfs_tpu.shell import CommandError, Shell
        while not self._stopping:
            self._maint_wake.wait(timeout=self.maintenance_interval_s)
            self._maint_wake.clear()
            if self._stopping:
                return
            if not self.raft.is_leader:
                continue
            sh = Shell(self.url)
            for script in self.maintenance_scripts:
                if self._stopping:
                    return
                if not self.raft.is_leader:
                    log.info("maintenance: lost leadership mid-pass; "
                             "aborting remaining scripts")
                    break
                try:
                    out = sh.run_command(script)
                    if out.strip():
                        log.info("maintenance %r:\n%s", script,
                                 out.strip())
                except CommandError as e:
                    log.warning("maintenance %r failed: %s", script, e)
                except Exception:
                    log.exception("maintenance %r crashed", script)

    def run_maintenance_now(self) -> None:
        """Test/ops hook: trigger one cron pass immediately."""
        self._maint_wake.set()

    # -- scrub scheduler -------------------------------------------------------

    def _scrub_loop(self) -> None:
        """Leader-only: once per scrub_interval_s, start a scrub pass
        on every volume server, staggered across the window so disks
        fleet-wide never take the scan IO at the same instant. The
        stagger waits are spent INSIDE the interval window (the tail
        wait covers only the remainder), so each node's period is the
        configured interval, not interval + stagger."""
        while not self._stopping:
            cycle_start = time.monotonic()
            if self.raft.is_leader:
                urls = sorted(n.url for n in self.topo.nodes())
                for url, offset in plan_scrub_stagger(
                        urls, self.scrub_interval_s):
                    if offset > 0:
                        self._scrub_wake.wait(timeout=offset)
                        self._scrub_wake.clear()
                    if self._stopping or not self.raft.is_leader:
                        break
                    self._start_scrub_on(url)
            if self._stopping:
                return
            remainder = self.scrub_interval_s - \
                (time.monotonic() - cycle_start)
            if remainder > 0:
                self._scrub_wake.wait(timeout=remainder)
                self._scrub_wake.clear()

    def _start_scrub_on(self, url: str) -> bool:
        try:
            resp = volume_stub(url).VolumeScrubStart(
                volume_server_pb2.VolumeScrubStartRequest(
                    throttle_mbps=self.scrub_throttle_mbps))
            if resp.started:
                log.info("scrub window opened on %s", url)
            return resp.started
        except grpc.RpcError as e:
            log.warning("scrub start on %s failed: %s", url,
                        getattr(e, "code", lambda: e)())
            return False

    def scrub_all_now(self) -> List[str]:
        """Test/ops hook: fire VolumeScrubStart on every node now
        (no stagger). Returns the urls that accepted."""
        return [n.url for n in self.topo.nodes()
                if self._start_scrub_on(n.url)]

    # -- raft ------------------------------------------------------------------

    @property
    def is_leader(self) -> bool:
        return self.raft.is_leader

    def leader_url(self) -> Optional[str]:
        return self.raft.leader()

    def _require_leader(self) -> None:
        if not self.raft.is_leader:
            raise NotLeader(self.raft.leader())

    def _raft_apply(self, cmd: dict, term: int = 0) -> None:
        """Committed-log state machine: max volume id + file-id
        sequence watermarks (the state the reference snapshots via
        chrislusf/raft; server/raft_server.go:21-60).

        Runs during RaftNode.__init__ replay (before self.raft is
        assigned), so it must not dereference self.raft."""
        op = cmd.get("op")
        raft = getattr(self, "raft", None)
        if op == "max_volume_id":
            value = int(cmd["value"])
            self.topo.adjust_max_volume_id(value)
            self._applied_state["max_volume_id"] = max(
                self._applied_state["max_volume_id"], value)
        elif op == "sequence":
            value = int(cmd["value"])
            self._applied_state["sequence"] = max(
                self._applied_state["sequence"], value)
            # Raise the sequencer floor for every watermark EXCEPT the
            # sitting leader's own current-term proposals (its
            # in-memory sequence is the source of truth there). A
            # prior-term watermark applied after winning an election
            # must still raise the floor, or this leader re-issues file
            # ids the dead leader already handed out.
            own_proposal = raft is not None and raft.is_leader and                 term == raft.current_term
            if not own_proposal:
                self.topo.sequence.set_max(value)

    def _raft_restore(self, state: dict) -> None:
        """Reinstall a raft snapshot (log compaction / catch-up)."""
        if not state:
            return
        self._applied_state.update({
            "max_volume_id": int(state.get("max_volume_id", 0)),
            "sequence": int(state.get("sequence", 0))})
        if self._applied_state["max_volume_id"]:
            self.topo.adjust_max_volume_id(
                self._applied_state["max_volume_id"])
        if self._applied_state["sequence"]:
            self.topo.sequence.set_max(self._applied_state["sequence"])

    def _ensure_sequence_watermark(self, count: int) -> None:
        """Guarantee the raft-committed watermark stays ahead of every
        id this assign can allocate. Caller holds _seq_lock, so the
        check-then-allocate window is atomic: no id >= the committed
        watermark is ever handed out, and a failed-over leader resuming
        at the watermark can never duplicate one."""
        if not self.raft.peers or \
                not getattr(self.topo.sequence, "needs_watermark", True):
            # time-based sequencers are collision-free without raft;
            # watermarking them would raft-propose on ~every assign
            return
        peek = self.topo.sequence.peek
        if peek + count >= self._seq_watermark:
            new_wm = peek + count + self.SEQ_WATERMARK_GAP
            self.raft.propose({"op": "sequence", "value": new_wm})
            self._seq_watermark = new_wm

    # -- KeepConnected fan-out -----------------------------------------------

    def _broadcast(self, loc: master_pb2.VolumeLocation) -> None:
        with self._sub_lock:
            for q in self._subscribers.values():
                # lint: block-ok(unbounded Queue.put never blocks)
                q.put(loc)

    def _full_locations(self) -> List[master_pb2.VolumeLocation]:
        locs = []
        for node in self.topo.nodes():
            vids = sorted(set(node.volumes) | set(node.ec_shards))
            if vids:
                locs.append(master_pb2.VolumeLocation(
                    url=node.url, public_url=node.public_url,
                    new_vids=vids))
        return locs

    # -- gRPC: Seaweed service ------------------------------------------------

    def SendHeartbeat(self, request_iterator, context):
        if not self.raft.is_leader:
            # tell the volume server who the leader is and end the
            # stream; it redials (reference master_grpc_server.go:20-28)
            next(request_iterator, None)
            yield master_pb2.HeartbeatResponse(
                leader=self.raft.leader() or "")
            return
        node_url = None
        stream_id = object()  # identity of THIS connection
        try:
            for hb in request_iterator:
                d = convert.heartbeat_from_pb(hb)
                node_url = f"{d['ip']}:{d['port']}"
                self._node_streams[node_url] = stream_id
                prev = self.topo.find_node(node_url)
                before = (set(prev.volumes) | set(prev.ec_shards)) \
                    if prev else set()
                if prev is None:
                    log.info("volume server %s connected (dc=%s rack=%s)",
                             node_url, hb.data_center or "DefaultDataCenter",
                             hb.rack or "DefaultRack")
                node = self.topo.sync_heartbeat(
                    d, dc=hb.data_center or "DefaultDataCenter",
                    rack=hb.rack or "DefaultRack")
                after = set(node.volumes) | set(node.ec_shards)
                new, deleted = sorted(after - before), sorted(before - after)
                if new or deleted:
                    self._broadcast(master_pb2.VolumeLocation(
                        url=node.url, public_url=node.public_url,
                        new_vids=new, deleted_vids=deleted))
                if not self.raft.is_leader:
                    yield master_pb2.HeartbeatResponse(
                        leader=self.raft.leader() or "")
                    return
                yield master_pb2.HeartbeatResponse(
                    volume_size_limit=self.topo.volume_size_limit,
                    leader=self.url)
        finally:
            # stream break == node death (reference master_grpc_server.go:22-50)
            # — but only if the node hasn't already reconnected on a
            # fresh stream (cleanup is tied to this connection)
            if node_url is not None and not self._stopping and \
                    self._node_streams.get(node_url) is stream_id:
                self._node_streams.pop(node_url, None)
                node = self.topo.find_node(node_url)
                if node is not None:
                    gone = sorted(set(node.volumes) | set(node.ec_shards))
                    log.warning("volume server %s disconnected; "
                                "unregistering %d volumes/shards",
                                node_url, len(gone))
                    self.topo.unregister_node(node_url)
                    if gone:
                        self._broadcast(master_pb2.VolumeLocation(
                            url=node_url, public_url=node.public_url,
                            deleted_vids=gone))

    def KeepConnected(self, request_iterator, context):
        try:
            intro = next(request_iterator)  # client introduces itself
        except StopIteration:
            return
        if not self.raft.is_leader:
            yield master_pb2.VolumeLocation(
                leader=self.raft.leader() or "")
            return
        # remember who's connected for ListMasterClients (reference
        # master_grpc_server.go clientChans keyed by "<type>@<addr>")
        client_addr = f"{rpc.peer_ip(context)}:{intro.grpc_port}"
        q: queue.Queue = queue.Queue()
        with self._sub_lock:
            self._sub_seq += 1
            key = self._sub_seq
            self._subscribers[key] = q
            self._client_addrs[key] = (intro.name, client_addr)
        try:
            yield master_pb2.VolumeLocation(leader=self.url)
            for loc in self._full_locations():
                yield loc
            while context.is_active():
                try:
                    yield q.get(timeout=1.0)
                except queue.Empty:
                    continue
        finally:
            with self._sub_lock:
                self._subscribers.pop(key, None)
                self._client_addrs.pop(key, None)

    def ListMasterClients(self, request, context):
        """Reference master_grpc_server.go ListMasterClients: the gRPC
        addresses of live KeepConnected clients of one type (the name
        the client introduced itself with, e.g. "filer", "brk")."""
        with self._sub_lock:
            addrs = [addr for name, addr in self._client_addrs.values()
                     if name == request.client_type]
        return master_pb2.ListMasterClientsResponse(grpc_addresses=addrs)

    def LookupVolume(self, request, context):
        out = []
        for vid_str in request.volume_ids:
            vid_part = vid_str.split(",")[0]
            try:
                vid = int(vid_part)
            except ValueError:
                out.append(master_pb2.LookupVolumeResponse.VolumeIdLocation(
                    volume_id=vid_str, error="unknown volume id"))
                continue
            locs = self.lookup_locations(vid, request.collection)
            if locs:
                out.append(master_pb2.LookupVolumeResponse.VolumeIdLocation(
                    volume_id=vid_str,
                    locations=[master_pb2.Location(url=u, public_url=p)
                               for u, p in locs]))
            else:
                out.append(master_pb2.LookupVolumeResponse.VolumeIdLocation(
                    volume_id=vid_str, error=f"volume {vid} not found"))
        return master_pb2.LookupVolumeResponse(volume_id_locations=out)

    def lookup_locations(self, vid: int, collection: str = "") -> List[tuple]:
        """[(url, public_url)] over normal replicas, else EC shard holders."""
        nodes = self.topo.lookup(vid, collection)
        if nodes:
            return [(n.url, n.public_url) for n in nodes]
        by_url = self.topo.lookup_ec(vid)
        urls = []
        for u in by_url:
            n = self.topo.find_node(u)
            urls.append((u, n.public_url if n else u))
        return urls

    def Assign(self, request, context):
        try:
            result = self.assign(
                count=max(1, request.count or 1),
                replication=request.replication,
                collection=request.collection,
                ttl=request.ttl,
                data_center=request.data_center,
                writable_volume_count=request.writable_volume_count)
        except (NoFreeSlots, RuntimeError, NotLeader, TimeoutError) as e:
            return master_pb2.AssignResponse(error=str(e))
        fid, count, locs = result
        return master_pb2.AssignResponse(
            fid=fid, url=locs[0].url, public_url=locs[0].public_url,
            count=count)

    def assign(self, count: int = 1, replication: str = "",
               collection: str = "", ttl: str = "", data_center: str = "",
               writable_volume_count: int = 0):
        self._require_leader()
        rp = ReplicaPlacement.parse(replication or self.default_replication)
        rb = rp.to_byte()
        if not self.topo.has_writable(collection, rb, ttl):
            with self._grow_lock:
                if not self.topo.has_writable(collection, rb, ttl):
                    self.grow_volumes(
                        writable_volume_count or growth_count(rp.copy_count),
                        replication or self.default_replication,
                        collection, ttl, data_center)
        with self._seq_lock:
            self._ensure_sequence_watermark(count)
            picked = self.topo.pick_for_write(
                count=count, collection=collection, replica_byte=rb,
                ttl=ttl)
        if picked is None:
            raise RuntimeError("no writable volumes")
        return picked

    def grow_volumes(self, target_count: int, replication: str,
                     collection: str = "", ttl: str = "",
                     data_center: str = "") -> List[int]:
        """AutomaticGrowByType: allocate `target_count` new volumes on
        placement-picked servers (reference volume_growth.go:70-240)."""
        self._require_leader()
        rp = ReplicaPlacement.parse(replication or self.default_replication)
        grown = []
        for _ in range(max(1, target_count)):
            try:
                nodes = self.growth.find_empty_slots(rp, data_center)
            except NoFreeSlots:
                if grown:
                    break  # partial growth still unblocks the assign
                raise
            vid = self.topo.reserve_volume_ids(1)[0]
            # replicate the new max volume id before using it, so a
            # failed-over leader never re-issues vids (reference
            # topology.go NextVolumeId raft command)
            self.raft.propose({"op": "max_volume_id", "value": vid})
            ok_nodes = []
            for n in nodes:
                try:
                    volume_stub(n.url).AllocateVolume(
                        volume_server_pb2.AllocateVolumeRequest(
                            volume_id=vid, collection=collection,
                            replication=str(rp), ttl=ttl))
                    ok_nodes.append(n)
                except grpc.RpcError:
                    continue  # dead node: heartbeat loss will reap it
            if len(ok_nodes) < rp.copy_count:
                # under-replicated: leave any created replicas for
                # volume.fix.replication; don't hand out write locations
                if grown:
                    break
                raise RuntimeError(
                    f"volume allocation failed: {len(ok_nodes)}/"
                    f"{rp.copy_count} replicas created for vid {vid}")
            from seaweedfs_tpu.topology.node import VolumeInfo
            for n in ok_nodes:
                info = VolumeInfo(id=vid, collection=collection,
                                  replica_placement=rp.to_byte(), ttl=ttl)
                n.volumes[vid] = info
                self.topo.register_volume(info, n)
            self._broadcast_new_vid(vid, ok_nodes)
            grown.append(vid)
        return grown

    def _broadcast_new_vid(self, vid: int, nodes) -> None:
        for n in nodes:
            self._broadcast(master_pb2.VolumeLocation(
                url=n.url, public_url=n.public_url, new_vids=[vid]))

    def Statistics(self, request, context):
        used = file_count = 0
        for node in self.topo.nodes():
            for v in node.volumes.values():
                if request.collection and v.collection != request.collection:
                    continue
                used += v.size
                file_count += v.file_count
        total = sum(n.max_volumes for n in self.topo.nodes()) \
            * self.topo.volume_size_limit
        return master_pb2.StatisticsResponse(
            total_size=total, used_size=used, file_count=file_count)

    def CollectionList(self, request, context):
        names: Set[str] = set()
        if request.include_normal_volumes or not request.include_ec_volumes:
            for (col, _, _), vl in self.topo.layouts.items():
                if vl.volume_ids:
                    names.add(col)
        if request.include_ec_volumes:
            names.update(self.topo.ec_collections.values())
        names.discard("")
        return master_pb2.CollectionListResponse(
            collections=[master_pb2.Collection(name=n) for n in sorted(names)])

    def CollectionDelete(self, request, context):
        for node in self.topo.nodes():
            try:
                volume_stub(node.url).DeleteCollection(
                    volume_server_pb2.DeleteCollectionRequest(
                        collection=request.name))
            except Exception:
                # node down: its heartbeat resync will converge
                from seaweedfs_tpu.stats import metrics
                metrics.swallowed("master.collection_delete")
        return master_pb2.CollectionDeleteResponse()

    def VolumeList(self, request, context):
        return master_pb2.VolumeListResponse(
            topology_info=convert.topology_to_pb(self.topo.to_map()),
            volume_size_limit_mb=self.topo.volume_size_limit >> 20)

    def LookupEcVolume(self, request, context):
        by_url = self.topo.lookup_ec(request.volume_id)
        if not by_url:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"ec volume {request.volume_id} not found")
        shard_locs: Dict[int, List[str]] = {}
        for url, bits in by_url.items():
            for sid in bits.shard_ids:
                shard_locs.setdefault(sid, []).append(url)
        return master_pb2.LookupEcVolumeResponse(
            volume_id=request.volume_id,
            shard_id_locations=[
                master_pb2.LookupEcVolumeResponse.EcShardIdLocation(
                    shard_id=sid,
                    locations=[master_pb2.Location(
                        url=u,
                        public_url=getattr(self.topo.find_node(u),
                                           "public_url", u))
                        for u in urls])
                for sid, urls in sorted(shard_locs.items())])

    def VacuumVolume(self, request, context):
        try:
            self.vacuum(request.garbage_threshold or self.garbage_threshold)
        except NotLeader as e:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
        return master_pb2.VacuumVolumeResponse()

    def GetMasterConfiguration(self, request, context):
        return master_pb2.GetMasterConfigurationResponse()

    def LeaseAdminToken(self, request, context):
        if not self.raft.is_leader:
            # the cluster-wide lock lives on the raft leader only —
            # leasing from a follower/deposed leader would give two
            # holders (reference: exclusive locks ride the leader)
            context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                          f"not the raft leader; leader is "
                          f"{self.raft.leader() or '?'}")
        try:
            token, ts = self.admin_lock.lease(request.previous_token)
        except PermissionError as e:
            context.abort(grpc.StatusCode.PERMISSION_DENIED, str(e))
        return master_pb2.LeaseAdminTokenResponse(token=token, lock_ts_ns=ts)

    def ReleaseAdminToken(self, request, context):
        self.admin_lock.release(request.previous_token)
        return master_pb2.ReleaseAdminTokenResponse()

    # -- vacuum driver --------------------------------------------------------

    def vacuum(self, garbage_threshold: Optional[float] = None) -> List[int]:
        """Poll garbage ratios and compact over-threshold volumes on all
        replicas (reference topology/topology_vacuum.go:17-201)."""
        self._require_leader()
        threshold = garbage_threshold or self.garbage_threshold
        compacted = []
        seen: Set[int] = set()
        for node in self.topo.nodes():
            for vid, info in list(node.volumes.items()):
                if vid in seen or info.read_only:
                    continue
                seen.add(vid)
                replicas = self.topo.lookup(vid, info.collection) or [node]
                try:
                    if self._vacuum_one(vid, replicas, threshold):
                        compacted.append(vid)
                except Exception:
                    # failed mid-compaction: best-effort cleanup on
                    # every replica, and the failure is ledgered
                    from seaweedfs_tpu.stats import metrics
                    metrics.swallowed("master.vacuum")
                    for r in replicas:
                        try:
                            volume_stub(r.url).VacuumVolumeCleanup(
                                volume_server_pb2.VacuumVolumeCleanupRequest(
                                    volume_id=vid))
                        except Exception:
                            from seaweedfs_tpu.stats import metrics
                            metrics.swallowed("master.vacuum_cleanup")
        return compacted

    def _vacuum_one(self, vid: int, replicas, threshold: float) -> bool:
        stubs = [volume_stub(r.url) for r in replicas]
        checks = [s.VacuumVolumeCheck(
            volume_server_pb2.VacuumVolumeCheckRequest(volume_id=vid))
            for s in stubs]
        if not checks or min(c.garbage_ratio for c in checks) < threshold:
            return False
        for s in stubs:
            s.VacuumVolumeCompact(volume_server_pb2.VacuumVolumeCompactRequest(
                volume_id=vid))
        for s in stubs:
            s.VacuumVolumeCommit(volume_server_pb2.VacuumVolumeCommitRequest(
                volume_id=vid))
        return True

    # -- HTTP view ------------------------------------------------------------

    def http_assign(self, params: dict) -> dict:
        try:
            fid, count, locs = self.assign(
                count=int(params.get("count", ["1"])[0]),
                replication=params.get("replication", [""])[0],
                collection=params.get("collection", [""])[0],
                ttl=params.get("ttl", [""])[0],
                data_center=params.get("dataCenter", [""])[0])
        except (NoFreeSlots, RuntimeError, NotLeader, TimeoutError) as e:
            return {"error": str(e)}
        return {"fid": fid, "url": locs[0].url,
                "publicUrl": locs[0].public_url, "count": count}

    def http_lookup(self, params: dict) -> dict:
        """GET /dir/lookup. Legacy ``volumeId``/``fileId`` answers ONE
        vid in the reference shape (byte-identical; the comma there
        belongs to the fid grammar ``<vid>,<key><cookie>``). The
        batched ``volumeIds=a,b,c`` surface (ISSUE 12) answers every
        vid as its own result-or-error entry, so one bad vid can never
        fail the batch — the wdclient coalescing cache's transport."""
        collection = params.get("collection", [""])[0]
        if "volumeIds" in params:
            out = []
            for part in params.get("volumeIds", [""])[0].split(","):
                try:
                    vid = int(part)
                except ValueError:
                    out.append({"volumeId": part,
                                "error": f"bad volume id {part!r}"})
                    continue
                locs = self.lookup_locations(vid, collection)
                if locs:
                    out.append({"volumeId": str(vid),
                                "locations": [{"url": u, "publicUrl": p}
                                              for u, p in locs]})
                else:
                    out.append({"volumeId": str(vid),
                                "error": "volume not found"})
            return {"volumeIdLocations": out}
        raw = params.get("volumeId", params.get("fileId", [""]))[0]
        try:
            vid = int(raw.split(",")[0])
        except ValueError:
            return {"error": f"bad volume id {raw!r}"}
        locs = self.lookup_locations(vid, collection)
        if not locs:
            return {"volumeId": str(vid), "error": "volume not found"}
        return {"volumeId": str(vid),
                "locations": [{"url": u, "publicUrl": p} for u, p in locs]}

    def http_grow(self, params: dict) -> dict:
        try:
            grown = self.grow_volumes(
                int(params.get("count", ["1"])[0]),
                params.get("replication", [self.default_replication])[0],
                params.get("collection", [""])[0],
                params.get("ttl", [""])[0],
                params.get("dataCenter", [""])[0])
        except (NoFreeSlots, NotLeader, TimeoutError, RuntimeError) as e:
            return {"error": str(e)}
        return {"count": len(grown), "volumeIds": grown}

    def http_cluster_status(self) -> dict:
        return {"IsLeader": self.raft.is_leader,
                "Leader": self.raft.leader() or "",
                "Peers": self.raft.peers}

    def http_status(self) -> dict:
        """GET /status: the master's role block (the volume server's
        /status twin) — Lifecycle state machine + live cluster heat."""
        return {
            "Version": "seaweedfs-tpu",
            "IsLeader": self.raft.is_leader,
            "Lifecycle": self.lifecycle.status()
            if self.lifecycle is not None else {"enabled": False},
            "Heat": {str(vid): rec for vid, rec in
                     sorted(self.topo.cluster_heat().items())},
        }

    def http_cluster_heat(self) -> dict:
        """GET /cluster/heat: the heartbeat-fed cluster heat map, with
        each vid's observed tier — what `cluster.heat` renders."""
        heat = self.topo.cluster_heat()
        ec_vids = set(self.topo.ec_locations)
        vol_vids = {vid for n in self.topo.nodes() for vid in n.volumes}
        out = {}
        for vid in sorted(vol_vids | ec_vids | set(heat)):
            rec = dict(heat.get(vid, {"reads_window": 0.0, "ewma": 0.0,
                                      "servers": []}))
            rec["tier"] = "warm" if vid in ec_vids and vid not in vol_vids \
                else "hot"
            if self.lifecycle is not None:
                st = self.lifecycle.states.get(vid)
                if st is not None:
                    rec["state"] = st.state
            out[str(vid)] = rec
        return {"volumes": out}

    def http_cluster_qos(self) -> dict:
        """GET /cluster/qos: this master's own QoS block plus every
        data node's /qos/status, fanned out with short per-node
        timeouts — what the `cluster.qos` shell command renders. A
        node that doesn't answer (older build, mid-restart) reports an
        error entry instead of failing the whole view."""
        from seaweedfs_tpu import qos
        from seaweedfs_tpu.util import http_client
        mgr = qos.manager()
        out = {"master": mgr.status() if mgr is not None
               else {"enabled": False}, "nodes": {}}
        for n in self.topo.nodes():
            try:
                resp = http_client.request(
                    "GET", f"{n.url}/qos/status", timeout=2.0)
                out["nodes"][n.url] = json.loads(resp.body)
            except Exception as e:  # noqa: BLE001 - per-node best effort
                out["nodes"][n.url] = {"error": str(e)}
        return out

    def http_lifecycle(self, params: dict, method: str = "GET") -> dict:
        """GET/POST /cluster/lifecycle: status (default), and the
        volume.lifecycle verbs — pause / resume / force."""
        if self.lifecycle is None:
            return {"enabled": False,
                    "error": "lifecycle disabled (start the master "
                             "with -lifecycle)"}
        action = params.get("action", [""])[0]
        if not action or action == "status":
            return self.lifecycle.status()
        if method != "POST":
            return {"error": f"action {action!r} requires POST"}
        if action == "pause":
            self.lifecycle.pause()
            return {"paused": True}
        if action == "resume":
            self.lifecycle.resume()
            return {"paused": False}
        if action == "run":
            self.lifecycle.run_pass_now()
            return {"triggered": True}
        if action == "force":
            try:
                vid = int(params.get("volumeId", ["0"])[0])
                kind = self.lifecycle.force(
                    vid, params.get("target", [""])[0])
            except ValueError as e:
                return {"error": str(e)}
            self.lifecycle.run_pass_now()
            return {"queued": kind, "volumeId": vid}
        return {"error": f"unknown action {action!r} (status | pause | "
                         "resume | run | force)"}


def _make_http_handler(ms: MasterServer):
    class Handler(FastHandler):
        protocol_version = "HTTP/1.1"
        disable_nagle_algorithm = True  # small replies must not wait on delayed ACKs

        def log_message(self, fmt, *args):  # quiet
            pass

        def _html(self, body: str, code: int = 200) -> None:
            blob = body.encode()
            self.send_response(code)
            self.send_header("Content-Type", "text/html; charset=utf-8")
            self.send_header("Content-Length", str(len(blob)))
            self.end_headers()
            self.wfile.write(blob)

        def _json(self, payload: dict, code: int = 200) -> None:
            self.fast_reply(code, json.dumps(payload).encode(),
                            ctype="application/json")

        def _proxy_to_leader(self) -> bool:
            """Forward this request to the raft leader (reference
            master_server.go:155-185 proxyToLeader). Returns True if
            the request was handled (proxied or error-answered)."""
            if ms.raft.is_leader:
                return False
            leader = ms.raft.leader()
            if not leader:
                self._json({"error": "no raft leader elected yet"},
                           code=503)
                return True
            import urllib.request as _rq
            import urllib.error as _er
            url = f"http://{leader}{self.path}"
            try:
                with _rq.urlopen(_rq.Request(url, method=self.command),
                                 timeout=30) as r:
                    body = r.read()
                    self.send_response(r.status)
                    self.send_header(
                        "Content-Type",
                        r.headers.get("Content-Type", "application/json"))
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
            except _er.URLError as e:
                self._json({"error": f"leader {leader} unreachable: {e}"},
                           code=502)
            return True

        def do_GET(self):
            upath, sep, query = self.path.partition("?")
            params = parse_qs(query) if sep else {}
            if upath in ("/debug/trace", "/debug/requests"):
                # local collector/flight-recorder state — never proxied
                # to the leader (each process answers for itself)
                from seaweedfs_tpu.stats import cluster_trace
                self._json(cluster_trace.debug_payload(
                    self.path, "master", ms.url))
                return
            if upath == "/status":
                # this master's own role block (the volume server's
                # /status twin) — never proxied
                self._json(ms.http_status())
                return
            if upath == "/qos/status":
                # this process's own QoS admission state — never
                # proxied (every role answers for itself; the fanned
                # cluster view is /cluster/qos)
                from seaweedfs_tpu import qos
                mgr = qos.manager()
                self._json(mgr.status() if mgr is not None
                           else {"enabled": False})
                return
            if upath != "/cluster/status" and self._proxy_to_leader():
                return
            if upath == "/dir/assign":
                self._json(ms.http_assign(params))
            elif upath == "/dir/lookup":
                self._json(ms.http_lookup(params))
            elif upath == "/dir/status":
                self._json({"Topology": ms.topo.to_map(),
                            "Version": "seaweedfs-tpu"})
            elif upath == "/vol/grow":
                self._json(ms.http_grow(params))
            elif upath == "/vol/vacuum":
                t = params.get("garbageThreshold", [None])[0]
                vids = ms.vacuum(float(t) if t else None)
                self._json({"compacted": vids})
            elif upath == "/cluster/status":
                self._json(ms.http_cluster_status())
            elif upath == "/cluster/heat":
                self._json(ms.http_cluster_heat())
            elif upath == "/cluster/qos":
                self._json(ms.http_cluster_qos())
            elif upath == "/cluster/lifecycle":
                self._json(ms.http_lifecycle(params, self.command))
            elif upath in ("/", "/ui"):
                self._html(_master_ui(ms))
            else:
                self._json({"error": f"unknown path {upath}"}, code=404)

        do_POST = do_GET

    from seaweedfs_tpu.stats.metrics import instrument_http_handler
    return instrument_http_handler(Handler, "master")


def _master_ui(ms: MasterServer) -> str:
    """Plain status page (reference master UI, server/master_ui/).
    Every interpolated string is escaped — node urls, rack names etc.
    originate from heartbeats, i.e. remote input."""
    import html as _html
    esc = _html.escape
    rows = []
    for node in ms.topo.nodes():
        rows.append(
            f"<tr><td>{esc(node.url)}</td><td>{len(node.volumes)}"
            f"/{node.max_volumes}</td><td>{len(node.ec_shards)}</td>"
            f"<td>{esc(node.rack.id if node.rack else '')}</td></tr>")
    raft = ms.raft
    return (
        "<html><head><title>seaweedfs-tpu master</title></head><body>"
        f"<h1>Master {esc(ms.url)}</h1>"
        f"<p>leader: {esc(raft.leader() or '?')} | "
        f"is_leader: {raft.is_leader}"
        f" | peers: {esc(', '.join(raft.peers)) or '(single)'}"
        f" | volume size limit: {ms.topo.volume_size_limit >> 20} MB</p>"
        "<h2>Topology</h2><table border=1 cellpadding=4>"
        "<tr><th>volume server</th><th>volumes</th><th>ec shards</th>"
        "<th>rack</th></tr>" + "".join(rows) + "</table>"
        "<p><a href=/dir/status>dir status (json)</a> | "
        "<a href=/cluster/status>cluster status (json)</a></p>"
        "</body></html>")
