"""proto <-> plain-dict bridges.

The in-memory model (topology, store heartbeats) speaks plain dicts —
the house test pattern fabricates those — so the wire layer converts at
the server boundary. Reference equivalent: the pb structs are used
directly throughout weed/topology; here the dict model predates the pb
layer and stays the source of truth.
"""

from __future__ import annotations

from typing import List

from seaweedfs_tpu.pb import master_pb2
from seaweedfs_tpu.storage.superblock import TTL


def ttl_to_int(ttl: str) -> int:
    return int.from_bytes(TTL.parse(ttl or "").to_bytes(), "big")


def ttl_from_int(v: int) -> str:
    return str(TTL.from_bytes(int(v).to_bytes(2, "big")))


def volume_info_to_pb(info: dict) -> master_pb2.VolumeInformationMessage:
    return master_pb2.VolumeInformationMessage(
        id=int(info["id"]),
        size=int(info.get("size", 0)),
        collection=info.get("collection", ""),
        file_count=int(info.get("file_count", 0)),
        delete_count=int(info.get("delete_count", 0)),
        deleted_byte_count=int(info.get("deleted_byte_count", 0)),
        read_only=bool(info.get("read_only", False)),
        replica_placement=int(info.get("replica_placement", 0)),
        version=int(info.get("version", 3)),
        ttl=ttl_to_int(info.get("ttl", "")),
        compact_revision=int(info.get("compact_revision", 0)),
        modified_at_second=int(info.get("modified_at_second", 0)))


def volume_info_from_pb(m: master_pb2.VolumeInformationMessage) -> dict:
    return {
        "id": m.id,
        "size": m.size,
        "collection": m.collection,
        "file_count": m.file_count,
        "delete_count": m.delete_count,
        "deleted_byte_count": m.deleted_byte_count,
        "read_only": m.read_only,
        "replica_placement": m.replica_placement,
        "version": m.version or 3,
        "ttl": ttl_from_int(m.ttl),
        "modified_at_second": m.modified_at_second,
    }


def ec_info_to_pb(info: dict) -> master_pb2.VolumeEcShardInformationMessage:
    return master_pb2.VolumeEcShardInformationMessage(
        id=int(info["id"]),
        collection=info.get("collection", ""),
        ec_index_bits=int(info["ec_index_bits"]))


def ec_info_from_pb(m) -> dict:
    return {"id": m.id, "collection": m.collection,
            "ec_index_bits": m.ec_index_bits}


def heartbeat_from_pb(hb: master_pb2.Heartbeat) -> dict:
    d = {
        "ip": hb.ip,
        "port": hb.port,
        "public_url": hb.public_url,
        "max_volume_count": hb.max_volume_count,
        "max_file_key": hb.max_file_key,
        "volumes": [volume_info_from_pb(v) for v in hb.volumes],
        "ec_shards": [ec_info_from_pb(e) for e in hb.ec_shards],
    }
    if hb.volume_heats:
        d["volume_heats"] = [
            {"id": h.id, "reads_window": h.reads_window, "ewma": h.ewma}
            for h in hb.volume_heats]
    return d


def heartbeat_to_pb(hb: dict, data_center: str = "",
                    rack: str = "") -> master_pb2.Heartbeat:
    # volume_heats stays absent unless -heat.track populated it: a
    # heat-disabled server's heartbeat must serialize byte-identically
    # to the pre-heat wire format (test_lifecycle_disabled_overhead)
    return master_pb2.Heartbeat(
        ip=hb["ip"],
        port=hb["port"],
        public_url=hb.get("public_url", ""),
        max_volume_count=hb.get("max_volume_count", 0),
        max_file_key=hb.get("max_file_key", 0),
        data_center=data_center,
        rack=rack,
        volumes=[volume_info_to_pb(v) for v in hb.get("volumes", [])],
        ec_shards=[ec_info_to_pb(e) for e in hb.get("ec_shards", [])],
        volume_heats=[master_pb2.VolumeHeatMessage(
            id=int(h["id"]),
            reads_window=int(h.get("reads_window", 0)),
            ewma=float(h.get("ewma", 0.0)))
            for h in hb.get("volume_heats", [])])


def topology_to_pb(topo_map: dict) -> master_pb2.TopologyInfo:
    """Topology.to_map() -> TopologyInfo proto (the shell's working view;
    reference weed/topology/topology_map.go)."""
    dcs: List[master_pb2.DataCenterInfo] = []
    for dc in topo_map.get("data_centers", []):
        racks = []
        for r in dc.get("racks", []):
            dns = []
            for n in r.get("nodes", []):
                vol_infos = [volume_info_to_pb(v) for v in n.get("volumes", [])]
                ec_infos = [ec_info_to_pb(e) for e in n.get("ec_shards", [])]
                dns.append(master_pb2.DataNodeInfo(
                    id=n["url"],
                    volume_count=len(vol_infos),
                    max_volume_count=n.get("max_volumes", 0),
                    free_volume_count=max(
                        0, n.get("max_volumes", 0) - len(vol_infos)),
                    active_volume_count=len(vol_infos),
                    volume_infos=vol_infos,
                    ec_shard_infos=ec_infos))
            racks.append(master_pb2.RackInfo(
                id=r["id"],
                volume_count=sum(d.volume_count for d in dns),
                max_volume_count=sum(d.max_volume_count for d in dns),
                free_volume_count=sum(d.free_volume_count for d in dns),
                data_node_infos=dns))
        dcs.append(master_pb2.DataCenterInfo(
            id=dc["id"],
            volume_count=sum(r.volume_count for r in racks),
            max_volume_count=sum(r.max_volume_count for r in racks),
            free_volume_count=sum(r.free_volume_count for r in racks),
            rack_infos=racks))
    return master_pb2.TopologyInfo(
        id="topo",
        volume_count=sum(d.volume_count for d in dcs),
        max_volume_count=sum(d.max_volume_count for d in dcs),
        free_volume_count=sum(d.free_volume_count for d in dcs),
        data_center_infos=dcs)
