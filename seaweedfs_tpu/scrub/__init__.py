"""Background integrity scrub & self-repair.

SeaweedFS trusts bytes once written: needle CRCs are checked on reads,
EC shards never again after encode — the latent-sector-error failure
mode warm stores guard against with continuous scrubbing (f4-style).
This package closes that gap with three parts:

  scanner   walks mounted volumes and EC volumes at a throttled pace,
            recomputing needle CRCs and re-encoding EC data shards
            through the fleet dispatcher (ec/fleet.py) in fused
            [B, 10, chunk] batches, comparing against stored parity.
  planner   classifies damage (bad parity shard vs bad data shard vs
            unrecoverable), quarantines corrupt files with a .corrupt
            rename, and reconstructs shards via the fleet rebuild path
            (needles come back from replicas).
  daemon    the control plane: a background thread per volume server
            with start/pause/status, wired to VolumeScrubStart/Pause/
            Status RPCs, the HTTP /status page, the master's staggered
            scheduler, and the `volume.scrub` shell command.

Everything is instrumented with the PR 2 primitives: scrub.pass/scan/
verify/repair spans and the SeaweedFS_scrub_* metric families.
"""

from seaweedfs_tpu.scrub.daemon import ScrubDaemon, PassResult
from seaweedfs_tpu.scrub.planner import (EcDamage, classify_ec_damage,
                                         repair_ec_volume, repair_needle)
from seaweedfs_tpu.scrub.scanner import (EcNeedleScan, NeedleScan,
                                         scan_ec_volume_needles,
                                         scan_volume)

__all__ = [
    "ScrubDaemon", "PassResult",
    "EcDamage", "classify_ec_damage", "repair_ec_volume", "repair_needle",
    "EcNeedleScan", "NeedleScan", "scan_ec_volume_needles", "scan_volume",
]
