"""Scrub repair planner: classify damage, quarantine, reconstruct.

Classification uses both evidence streams the scanner produces:

  * needle-CRC localization names corrupt DATA shards directly.
  * the stripe verify (re-encode vs stored parity) names corrupt
    PARITY shards — but only when the data shards are clean: a corrupt
    data shard contaminates ALL four recomputed parity streams, so
    parity mismatches are trusted only on a volume with no data-shard
    evidence.
  * when all four parity streams disagree and the needle sweep found
    nothing (damage in dead bytes of a data shard — padding, an
    overwritten record — that no live CRC covers), the syndrome probe
    localize_from_parity_deltas names the culprit: a single-byte error
    e in data shard d shifts recomputed parity row p by exactly
    M[p,d]*e in GF(2^8), so the shard whose matrix column divides all
    four observed deltas to the SAME e is the corrupt one. The Cauchy
    rows make that division ambiguous only for genuine multi-shard
    damage, which falls through to the parity verdict and is caught by
    the post-repair verify round.

Repair is quarantine-then-rebuild: each condemned .ecNN is renamed to
.ecNN.corrupt (never deleted — the operator's forensic copy), then the
fleet rebuild path reconstructs it from the surviving >=10 shards,
byte-identical to the original. RS(10,4) caps repairable damage at 4
shards per volume; anything past that is unrecoverable and stays
quarantine-free so whatever still reads, still reads.

Needle repair in normal volumes has no parity to lean on: the good
bytes come from a replica (replica_fetch), validated against the
corrupt record's own stored CRC before being rewritten in place.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set, Tuple

from seaweedfs_tpu.ec import fleet
from seaweedfs_tpu.ec.encoder import shard_file_name
from seaweedfs_tpu.ec.shard_bits import TOTAL_SHARDS
from seaweedfs_tpu.ops.rs_code import DATA_SHARDS, PARITY_SHARDS
from seaweedfs_tpu.stats import trace
from seaweedfs_tpu.storage.needle import Needle, NeedleError, masked_crc
from seaweedfs_tpu.storage.volume import Volume, VolumeError


@dataclass
class EcDamage:
    """Everything the scanner learned about one EC volume."""

    base: str
    bad_data: Set[int] = field(default_factory=set)
    parity_mismatch: Dict[int, int] = field(default_factory=dict)
    first_mismatch: Dict[int, int] = field(default_factory=dict)
    parity_checked: List[int] = field(default_factory=list)
    missing: List[int] = field(default_factory=list)


def _shard_byte(base: str, sid: int, offset: int) -> int:
    with open(shard_file_name(base, sid), "rb") as f:
        f.seek(offset)
        b = f.read(1)
    return b[0] if b else 0


def localize_from_parity_deltas(base: str, offsets,
                                parity_ids=None) -> Set[int]:
    """Syndrome probe: name the single corrupt DATA shard behind an
    every-parity-stream mismatch (see module docstring). Probes one
    byte column per offset over the parity shards actually present
    (`parity_ids`, default all four); returns the data shards
    unambiguously identified (empty = not single-shard damage — a
    single parity row can never discriminate, so it returns nothing)."""
    from seaweedfs_tpu.ops import gf256
    from seaweedfs_tpu.ops.rs_code import coding_matrix
    m = coding_matrix()
    parity_ids = sorted(parity_ids) if parity_ids else \
        list(range(DATA_SHARDS, TOTAL_SHARDS))
    culprits: Set[int] = set()
    for offset in offsets:
        col = [_shard_byte(base, d, offset) for d in range(DATA_SHARDS)]
        delta = {}
        for sid in parity_ids:
            acc = 0
            for d in range(DATA_SHARDS):
                acc ^= int(gf256.GF_MUL_TABLE[m[sid, d], col[d]])
            delta[sid] = acc ^ _shard_byte(base, sid, offset)
        if not all(delta.values()):
            continue  # some parity agrees here: not a data-shard error
        cands = [d for d in range(DATA_SHARDS)
                 if len({gf256.gf_div(delta[sid], int(m[sid, d]))
                         for sid in parity_ids}) == 1]
        if len(parity_ids) >= 2 and len(cands) == 1:
            culprits.add(cands[0])
    return culprits


def classify_ec_damage(damage: EcDamage) -> Tuple[str, List[int]]:
    """-> (verdict, shard ids to rebuild). Verdicts:

    clean          nothing to do
    data           condemned data shard(s) (+ any missing files)
    parity         condemned parity shard(s) (+ any missing files)
    unrecoverable  more than PARITY_SHARDS shards condemned, or fewer
                   than DATA_SHARDS survivors to rebuild from
    """
    bad: Set[int] = set(damage.missing)
    verdict = "clean"
    if damage.bad_data:
        # data evidence wins; parity mismatches are contaminated and
        # get re-judged by the post-repair verify round
        bad |= damage.bad_data
        verdict = "data"
    elif damage.parity_mismatch:
        bad |= set(damage.parity_mismatch)
        verdict = "parity"
    elif bad:
        verdict = "data" if any(s < DATA_SHARDS for s in bad) else "parity"
    if not bad:
        return "clean", []
    if len(bad) > PARITY_SHARDS or TOTAL_SHARDS - len(bad) < DATA_SHARDS:
        return "unrecoverable", sorted(bad)
    return verdict, sorted(bad)


def quarantine_shard(base: str, shard_id: int) -> bool:
    """<base>.ecNN -> <base>.ecNN.corrupt (never deleted). A prior
    quarantine of the same shard is rotated away rather than clobbered."""
    path = shard_file_name(base, shard_id)
    if not os.path.exists(path):
        return False
    marker = path + ".corrupt"
    if os.path.exists(marker):
        os.replace(marker, marker + ".old")
    os.replace(path, marker)
    return True


def repair_ec_volume(base: str, bad_shards: List[int],
                     backend: str = "auto",
                     unmount: Optional[Callable[[int], None]] = None,
                     remount: Optional[Callable[[int], None]] = None,
                     ) -> List[int]:
    """Quarantine + rebuild the condemned shards of one volume.

    unmount/remount hooks let the store drop its open fd on a shard
    before the rename and re-open it after the rebuild (a mounted
    EcVolumeShard holds the old inode otherwise). Returns the rebuilt
    shard ids; raises if fewer than DATA_SHARDS survivors remain.
    """
    with trace.span("scrub.repair", base=os.path.basename(base),
                    shards=len(bad_shards)):
        for sid in bad_shards:
            if unmount is not None:
                unmount(sid)
            quarantine_shard(base, sid)
        rebuilt = fleet.fleet_rebuild_ec_files(
            [base], backend=backend, wanted=list(bad_shards))[base]
        for sid in bad_shards:
            if remount is not None:
                remount(sid)
        return rebuilt


def verify_ec_repair(base: str, backend: str = "auto") -> "fleet.VerifyResult":
    """Post-repair stripe verify of ONE volume (the daemon's second
    evidence round: after a data-shard rebuild, any parity mismatch
    that remains is genuine parity damage)."""
    return fleet.fleet_verify_ec_files([base], backend=backend)[base]


def repair_needle(v: Volume, corrupt: Needle,
                  replica_fetch: Callable[[int, Needle], Optional[bytes]],
                  ) -> bool:
    """Rewrite one CRC-bad needle from a replica's copy.

    The corrupt record's header (id/cookie/flags/checksum) survives —
    only `data` failed its CRC — so the replica's bytes are validated
    against the LOCAL record's stored checksum before anything is
    written: a replica that is itself corrupt (or serves a newer
    overwrite) never lands here. The rewrite is a cookie-checked
    append committed directly under the volume lock with the seal
    lifted only inside that critical section — no client write can
    slip onto a sealed volume through the repair window, and routing
    through the group-commit worker (which would need the same lock)
    is bypassed. The bad record becomes dead space for vacuum.
    """
    from seaweedfs_tpu.storage.volume import _WriteRequest
    data = replica_fetch(v.id, corrupt)
    if data is None or masked_crc(data) != corrupt.checksum:
        return False
    fixed = Needle(id=corrupt.id, cookie=corrupt.cookie, data=data,
                   flags=corrupt.flags, name=corrupt.name,
                   mime=corrupt.mime, pairs=corrupt.pairs,
                   last_modified=corrupt.last_modified, ttl=corrupt.ttl)
    with trace.span("scrub.repair", vid=v.id, needle=corrupt.id):
        req = _WriteRequest("write", fixed)
        with v._lock:
            was_ro, v.read_only = v.read_only, False
            try:
                v._apply_batch([req])
            finally:
                v.read_only = was_ro
        try:
            req.wait()
        except (NeedleError, VolumeError):
            return False
    return True
