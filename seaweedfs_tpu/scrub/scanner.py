"""Scrub scanner: walk stored bytes and recompute their checksums.

Two surfaces, matching the two on-disk formats:

  * normal volumes — every LIVE needle record in the .dat (the copy
    the needle map points at; dead overwrites and tombstoned garbage
    are vacuum's business, not corruption) gets its masked CRC
    recomputed via the same `verify_needle_integrity` predicate the
    SEAWEED_VERIFY_READS read gate uses.
  * EC volumes — needle-level: each live .ecx entry is re-assembled
    from LOCAL shards and CRC-checked, and a failure is localized to
    the data shard at fault by single-shard-exclusion reconstruction;
    stripe-level: `ec/fleet.fleet_verify_ec_files` re-encodes the data
    shards through the fused dispatcher and compares parity (that call
    is batched across many volumes by the daemon, not per-volume here).

The scanner only ever reads; every repair decision belongs to
scrub/planner.py.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import List, Optional, Set, Tuple

from seaweedfs_tpu.ec.ec_volume import EcVolume
from seaweedfs_tpu.ec.shard_bits import DATA_SHARDS
from seaweedfs_tpu.ops.rs_code import ReedSolomon
from seaweedfs_tpu.stats import trace
from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.needle import (DataCorruptionError, Needle,
                                          NeedleError, actual_size,
                                          verify_needle_integrity)
from seaweedfs_tpu.storage.volume import Volume

# What a corrupt record can throw at parse time: a CRC failure is a
# clean DataCorruptionError, but a TRUNCATED/garbled record dies lower
# — struct.unpack on a short tail, body[off] past the end. All of it
# is corruption evidence; none of it may abort the scrub pass.
PARSE_ERRORS = (NeedleError, struct.error, IndexError, ValueError)


@dataclass
class NeedleScan:
    """One volume's needle sweep."""

    bytes_scanned: int = 0
    needles_verified: int = 0
    corrupt: List[Tuple[int, Needle]] = field(default_factory=list)
    # (dat offset, parsed-but-CRC-bad needle) — header metadata
    # (id/cookie/checksum) is still the repair planner's handle on it


def scan_volume(v: Volume, throttler=None) -> NeedleScan:
    """Verify every live needle of one mounted volume.

    Uses the volume's own scan fd (scan_needles), so a long scrub
    never races the serving read/write handles; the needle map is
    consulted per record to skip dead copies.
    """
    res = NeedleScan()
    with trace.span("scrub.scan", vid=v.id):
        for offset, n in v.scan_needles():
            nv = v.nm.get(n.id)
            if nv is None or nv.offset != offset or \
                    not t.size_is_valid(nv.size):
                continue  # overwritten or deleted: not the live copy
            length = actual_size(n.size, v.version)
            res.bytes_scanned += length
            res.needles_verified += 1
            if throttler is not None:
                throttler.maybe_slowdown(length)
            try:
                verify_needle_integrity(n)
            except DataCorruptionError:
                res.corrupt.append((offset, n))
    return res


@dataclass
class EcNeedleScan:
    """One EC volume's needle sweep over local shards."""

    bytes_scanned: int = 0
    needles_verified: int = 0
    corrupt: List[int] = field(default_factory=list)   # needle ids
    bad_data_shards: Set[int] = field(default_factory=set)
    skipped_remote: int = 0   # needles touching non-local shards


def scan_ec_volume_needles(ecv: EcVolume, version: int = 3,
                           throttler=None,
                           rs: Optional[ReedSolomon] = None) -> EcNeedleScan:
    """CRC-verify every live .ecx needle assembled from LOCAL shards.

    A CRC failure is localized by single-shard exclusion: re-read the
    needle with each touched data shard treated as missing (RS
    reconstruction from the other shards); the exclusion that makes
    the CRC pass names the corrupt shard. Needles spanning shards this
    server doesn't hold are skipped (their holder scrubs them).
    """
    res = EcNeedleScan()
    with trace.span("scrub.scan_ec", vid=ecv.volume_id):
        for i in range(len(ecv._keys)):
            size = int(ecv._sizes[i])
            if t.size_is_deleted(size) or size < 0:
                continue
            key = int(ecv._keys[i])
            try:
                _, _, intervals = ecv.locate_needle(key, version)
            except NeedleError:
                continue  # tombstoned between snapshot and read
            placed = [iv.to_shard_and_offset(ecv.large_block,
                                             ecv.small_block) + (iv.size,)
                      for iv in intervals]
            if any(sid not in ecv.shards for sid, _, _ in placed):
                res.skipped_remote += 1
                continue
            blob = b"".join(ecv.shards[sid].read_at(off, ln)
                            for sid, off, ln in placed)
            res.bytes_scanned += len(blob)
            res.needles_verified += 1
            if throttler is not None:
                throttler.maybe_slowdown(len(blob))
            try:
                Needle.from_bytes(blob, version)
            except PARSE_ERRORS:  # CRC mismatch or a torn/short parse
                res.corrupt.append(key)
                res.bad_data_shards |= _localize_bad_shard(
                    ecv, placed, version, rs)
    return res


def _localize_bad_shard(ecv: EcVolume, placed, version: int,
                        rs: Optional[ReedSolomon]) -> Set[int]:
    """Which single data shard, if excluded and RS-reconstructed,
    makes the needle's CRC pass? Empty set = not localizable this way
    (multi-shard damage, or parity too corrupt to reconstruct with) —
    the planner then falls back on the stripe-verify evidence."""
    rs = rs or ReedSolomon()
    candidates = sorted({sid for sid, _, _ in placed if sid < DATA_SHARDS})
    for suspect in candidates:
        try:
            pieces = []
            for sid, off, ln in placed:
                if sid == suspect:
                    pieces.append(ecv._recover_interval(sid, off, ln,
                                                        None, rs))
                else:
                    pieces.append(ecv.shards[sid].read_at(off, ln))
            Needle.from_bytes(b"".join(pieces), version)
        except PARSE_ERRORS:
            continue
        return {suspect}
    return set()
