"""Scrub daemon: the volume server's background integrity thread.

One daemon per server. Constructing it costs nothing — no thread, no
IO — until start() is called (the scrub-disabled perf gate in
tests/test_perf_gates.py holds the server to that). A pass walks every
mounted volume and EC volume:

  1. needle sweep per normal volume (scanner.scan_volume), corrupt
     needles re-fetched from replicas (planner.repair_needle);
  2. needle sweep per EC volume over local shards, localizing bad
     data shards by exclusion;
  3. ONE fused stripe verify across ALL the server's EC volumes
     (fleet_verify_ec_files) — verification rides the same batched
     TPU/mesh dispatch path as encode;
  4. per damaged EC volume: classify -> quarantine .corrupt ->
     fleet rebuild -> re-verify (a data repair un-contaminates the
     parity evidence; round two condemns genuinely bad parity).

Pacing rides util.throttler.Throttler (burst-capped), so an idle-hour
backlog can't turn into a full-rate IO storm. pause() takes effect at
volume granularity; start() on a paused daemon resumes it. Counters
feed both the per-server status RPC and the global SeaweedFS_scrub_*
Prometheus families.
"""

from __future__ import annotations

import threading
import time
import weakref
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

from seaweedfs_tpu.ec import fleet
from seaweedfs_tpu.scrub import planner, scanner
from seaweedfs_tpu.stats import trace
from seaweedfs_tpu.stats.metrics import (
    ScrubCorruptionsFoundCounter, ScrubCorruptionsRepairedCounter,
    ScrubNeedlesVerifiedCounter, ScrubPassSecondsHistogram,
    ScrubScanLagGauge, ScrubScannedBytesCounter,
    ScrubStripesVerifiedCounter, ScrubUnrecoverableCounter)
from seaweedfs_tpu.storage.store import Store
from seaweedfs_tpu.util import wlog
from seaweedfs_tpu.util.throttler import Throttler

log = wlog.logger("scrub")


@dataclass
class PassResult:
    """What one scrub pass saw and did."""

    bytes_scanned: int = 0
    needles_verified: int = 0
    stripes_verified: int = 0
    corruptions_found: int = 0
    corruptions_repaired: int = 0
    unrecoverable: int = 0
    volumes: int = 0
    ec_volumes: int = 0
    details: List[str] = field(default_factory=list)


class ScrubPaused(Exception):
    """Raised inside a pass when stop() interrupts it."""


class ScrubDaemon:
    """start/pause/status control plane over the scanner + planner."""

    def __init__(self, store: Store, mbps: float = 0.0,
                 backend: str = "auto", interval_s: float = 0.0,
                 replica_fetch: Optional[Callable] = None,
                 export_lag: bool = True,
                 on_repair: Optional[Callable[[int], None]] = None,
                 mesh_cfg: Optional[dict] = None):
        self.store = store
        self.mbps = mbps
        self.backend = backend
        self.interval_s = interval_s
        self.replica_fetch = replica_fetch
        # -ec.mesh* knobs: when set, the fused stripe verify rides the
        # unified pod-scale scheduler (parallel/mesh_fleet), falling
        # back to the host fleet verifier on any MeshError
        self.mesh_cfg = mesh_cfg
        # on_repair(vid) fires after scrub rewrites any bytes of a
        # volume (needle rewrite or EC shard reconstruction) — the
        # volume server hangs read-cache invalidation here so a repair
        # can never serve a pre-repair cached blob
        self.on_repair = on_repair
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None  # guarded_by(self._lock)
        self._resume = threading.Event()
        self._resume.set()            # not paused
        self._wake = threading.Event()  # interval sleep interrupt
        # the pass thread polls these lock-free (loop conditions /
        # status); every WRITE takes the lock so start/stop/pause
        # serialize against each other
        self._stopping = False  # guarded_by(self._lock, writes)
        # overrides for the FIRST pass of a freshly-started thread
        # only: a targeted/throttled start must never narrow or
        # re-budget the later periodic passes (written under the lock
        # BEFORE the thread spawns — happens-before via Thread.start)
        self._pass_volume_ids: Optional[List[int]] = None  # guarded_by(self._lock, writes)
        self._pass_mbps: Optional[float] = None  # guarded_by(self._lock, writes)
        self._state = "idle"  # guarded_by(self._lock, writes)
        self.current_volume_id = 0
        self.passes_completed = 0
        self.last_pass_unix = 0.0
        self.totals = PassResult()
        if export_lag:
            # weakref: the gauge is process-global and must neither pin
            # a dead daemon's Store in memory nor keep reporting it
            ref = weakref.ref(self)
            ScrubScanLagGauge.set_function(
                lambda: d._scan_lag() if (d := ref()) is not None else 0.0)

    def _scan_lag(self) -> float:
        """Seconds since the last completed pass — evaluated at metric
        COLLECTION time, so a stalled scrubber's lag keeps growing on
        every Prometheus scrape instead of freezing at the last
        status() call."""
        return round(time.time() - self.last_pass_unix, 3) \
            if self.last_pass_unix else 0.0

    # -- control -------------------------------------------------------------

    def start(self, volume_ids: Optional[Sequence[int]] = None,
              throttle_mbps: Optional[float] = None,
              full: bool = False) -> bool:
        """Begin a pass (or resume a paused one). Returns False when a
        pass is already running un-paused — and in that case changes
        NOTHING (a rejected start must not retarget or re-budget the
        running work)."""
        with self._lock:
            if self._stopping:
                return False
            if self._thread is not None and self._thread.is_alive():
                if not self._resume.is_set():
                    self._state = "running"
                    self._resume.set()   # un-pause
                    return True
                self._wake.set()         # cut an interval sleep short
                return False
            if full:
                self.totals = PassResult()
                self.passes_completed = 0
            # overrides apply to the first pass only; the interval
            # loop reverts to whole-store scope and the server budget
            self._pass_volume_ids = list(volume_ids) if volume_ids else None
            self._pass_mbps = throttle_mbps \
                if throttle_mbps is not None and throttle_mbps > 0 else None
            self._state = "running"
            self._resume.set()
            # lint: thread-ok(scrub daemon paced by -scrubMBps; no request context)
            self._thread = threading.Thread(
                target=self._run, name="scrub-daemon", daemon=True)
            self._thread.start()
            return True

    def pause(self) -> bool:
        """Hold the pass at the next volume boundary. Returns True if
        there was a live pass to pause."""
        with self._lock:
            alive = self._thread is not None and self._thread.is_alive()
            if alive:
                self._state = "paused"
            self._resume.clear()
            return alive

    def stop(self) -> None:
        # _stopping must flip under the lock: the unlocked write could
        # land AFTER a concurrent start() passed its _stopping check
        # but BEFORE it spawned — stop() would then join the OLD
        # (dead) thread while a fresh pass thread sails on past
        # shutdown (guard-check finding, ISSUE 10; regression test
        # under the schedule explorer in tests/test_scheduler.py)
        with self._lock:
            self._stopping = True
            t = self._thread
        self._resume.set()
        self._wake.set()
        if t is not None:
            t.join(timeout=10)
        with self._lock:
            self._state = "idle"

    def status(self) -> Dict:
        lag = self._scan_lag()
        t = self.totals
        return {
            "state": self._state,
            "bytes_scanned": t.bytes_scanned,
            "needles_verified": t.needles_verified,
            "stripes_verified": t.stripes_verified,
            "corruptions_found": t.corruptions_found,
            "corruptions_repaired": t.corruptions_repaired,
            "unrecoverable": t.unrecoverable,
            "current_volume_id": self.current_volume_id,
            "passes_completed": self.passes_completed,
            "last_pass_unix": self.last_pass_unix,
            "scan_lag_seconds": lag,
        }

    # -- the pass ------------------------------------------------------------

    def _checkpoint(self, vid: int) -> None:
        """Between-volumes barrier: block while paused, abort on stop."""
        self.current_volume_id = vid
        while not self._resume.wait(timeout=0.5):
            if self._stopping:
                raise ScrubPaused()
        if self._stopping:
            raise ScrubPaused()

    def _run(self) -> None:
        # the whole daemon runs as the _internal QoS tenant: its
        # replica/shard fetches are weighted low on every fan-out pool
        # and exempt from admission shed (repair trades latency for
        # durability, never the other way). No-op context when QoS off.
        from seaweedfs_tpu import qos
        vids, mbps = self._pass_volume_ids, self._pass_mbps
        while not self._stopping:
            try:
                with qos.internal_context():
                    self.run_pass(vids, mbps=mbps)
            except ScrubPaused:
                return
            except Exception:
                log.exception("scrub pass failed")
            vids, mbps = None, None  # later passes: whole store, server budget
            if self.interval_s <= 0:
                break
            self._wake.wait(timeout=self.interval_s)
            self._wake.clear()
        with self._lock:
            if not self._stopping:   # stop() owns the final state
                self._state = "idle"

    def run_pass(self, volume_ids: Optional[Sequence[int]] = None,
                 mbps: Optional[float] = None) -> PassResult:
        """One synchronous sweep over everything mounted locally."""
        res = PassResult()
        mbps = self.mbps if mbps is None else mbps
        throttler = Throttler(mbps) if mbps > 0 else None
        t0 = time.perf_counter()
        only = set(volume_ids) if volume_ids else None
        with trace.span("scrub.pass"):
            self._scan_volumes(res, throttler, only)
            self._scan_ec_volumes(res, throttler, only)
        ScrubPassSecondsHistogram.observe(time.perf_counter() - t0)
        self.last_pass_unix = time.time()
        self.passes_completed += 1
        self.current_volume_id = 0
        self._accumulate(res)
        return res

    def _accumulate(self, res: PassResult) -> None:
        t = self.totals
        t.bytes_scanned += res.bytes_scanned
        t.needles_verified += res.needles_verified
        t.stripes_verified += res.stripes_verified
        t.corruptions_found += res.corruptions_found
        t.corruptions_repaired += res.corruptions_repaired
        t.unrecoverable += res.unrecoverable
        t.volumes += res.volumes
        t.ec_volumes += res.ec_volumes
        t.details.extend(res.details)
        del t.details[:-100]   # ring: keep the newest hundred findings

    def _scan_volumes(self, res: PassResult, throttler, only) -> None:
        for loc in self.store.locations:
            for vid, v in list(loc.volumes.items()):
                if only is not None and vid not in only:
                    continue
                if v.is_remote:
                    continue  # cloud-tiered bytes are the backend's
                self._checkpoint(vid)
                scan = scanner.scan_volume(v, throttler)
                res.volumes += 1
                res.bytes_scanned += scan.bytes_scanned
                res.needles_verified += scan.needles_verified
                ScrubScannedBytesCounter.inc(scan.bytes_scanned)
                ScrubNeedlesVerifiedCounter.inc(scan.needles_verified)
                for offset, n in scan.corrupt:
                    res.corruptions_found += 1
                    ScrubCorruptionsFoundCounter.labels("needle").inc()
                    log.warning("volume %d: needle %x at %d fails CRC",
                                vid, n.id, offset)
                    if self.replica_fetch is not None and \
                            planner.repair_needle(v, n, self.replica_fetch):
                        res.corruptions_repaired += 1
                        ScrubCorruptionsRepairedCounter.labels(
                            "needle").inc()
                        if self.on_repair is not None:
                            self.on_repair(vid)
                        res.details.append(
                            f"volume {vid}: needle {n.id:x} rewritten "
                            f"from replica")
                    else:
                        res.unrecoverable += 1
                        ScrubUnrecoverableCounter.inc()
                        res.details.append(
                            f"volume {vid}: needle {n.id:x} corrupt, "
                            f"no healthy replica")

    def _scan_ec_volumes(self, res: PassResult, throttler, only) -> None:
        ecvs = [(vid, ecv)
                for loc in self.store.locations
                for vid, ecv in list(loc.ec_volumes.items())
                if only is None or vid in only]
        if not ecvs:
            return
        damages: Dict[int, planner.EcDamage] = {}
        for vid, ecv in ecvs:
            self._checkpoint(vid)
            scan = scanner.scan_ec_volume_needles(ecv, throttler=throttler)
            res.ec_volumes += 1
            res.bytes_scanned += scan.bytes_scanned
            res.needles_verified += scan.needles_verified
            ScrubScannedBytesCounter.inc(scan.bytes_scanned)
            ScrubNeedlesVerifiedCounter.inc(scan.needles_verified)
            if scan.corrupt:
                log.warning("ec volume %d: %d needle(s) fail CRC "
                            "(bad data shards: %s)", vid,
                            len(scan.corrupt),
                            sorted(scan.bad_data_shards) or "?")
            damages[vid] = planner.EcDamage(
                base=ecv.base_name, bad_data=scan.bad_data_shards)
        # ONE fused verify across the whole fleet of local EC volumes:
        # spans from every volume share RS dispatches (the tentpole)
        self._checkpoint(0)
        by_base = {ecv.base_name: (vid, ecv) for vid, ecv in ecvs}
        with trace.span("scrub.verify", volumes=len(by_base)):
            mesh_fleet = fleet.mesh_fleet_or_none() \
                if self.mesh_cfg is not None else None
            if mesh_fleet is not None:
                verified = mesh_fleet.pod_verify_ec_files(
                    list(by_base), backend=self.backend,
                    throttler=throttler, **self.mesh_cfg)
            else:
                verified = fleet.fleet_verify_ec_files(
                    list(by_base), backend=self.backend,
                    throttler=throttler)
        for base, vr in verified.items():
            vid, ecv = by_base[base]
            d = damages[vid]
            d.parity_mismatch = dict(vr.parity_mismatch)
            d.first_mismatch = dict(vr.first_mismatch)
            d.parity_checked = list(vr.parity_checked)
            # a shard file gone while this server still has it mounted
            # is local damage; shards living on OTHER servers are just
            # absent here and theirs to scrub
            d.missing = [s for s in vr.missing if s in ecv.shards]
            res.stripes_verified += vr.spans
            res.bytes_scanned += vr.bytes_verified
            ScrubStripesVerifiedCounter.inc(vr.spans)
            ScrubScannedBytesCounter.inc(vr.bytes_verified)
        for vid, ecv in ecvs:
            self._repair_ec(vid, ecv, damages[vid], res)

    def _repair_ec(self, vid: int, ecv, damage: planner.EcDamage,
                   res: PassResult, rounds: int = 2) -> None:
        """Classify -> quarantine -> rebuild -> re-verify, at most
        `rounds` times (round one clears data damage, whose recomputed
        parity contaminated round-zero evidence; round two then judges
        the parity shards on their own)."""
        for _ in range(rounds):
            checked = set(damage.parity_checked)
            if not damage.bad_data and len(checked) >= 2 and \
                    set(damage.parity_mismatch) == checked:
                # every LOCALLY-CHECKED parity stream disagrees but no
                # live needle is bad: dead-space damage in a data
                # shard. The syndrome probe names it, so the shard
                # itself comes back byte-identical instead of parity
                # being re-encoded around corrupt data (>=2 parity rows
                # are needed to discriminate; with every quotient test
                # ambiguous the probe returns nothing and the parity
                # verdict stands)
                damage.bad_data |= planner.localize_from_parity_deltas(
                    damage.base, sorted(set(damage.first_mismatch
                                            .values())),
                    parity_ids=sorted(checked))
            verdict, bad = planner.classify_ec_damage(damage)
            if verdict == "clean":
                return
            kinds = ["ec_data" if s < fleet.DATA_SHARDS else "ec_parity"
                     for s in bad]
            for k in kinds:
                res.corruptions_found += 1
                ScrubCorruptionsFoundCounter.labels(k).inc()
            if verdict == "unrecoverable":
                res.unrecoverable += len(bad)
                ScrubUnrecoverableCounter.inc(len(bad))
                res.details.append(
                    f"ec volume {vid}: shards {bad} unrecoverable "
                    f"(>{fleet.TOTAL_SHARDS - fleet.DATA_SHARDS} damaged)")
                log.error("ec volume %d: shards %s unrecoverable",
                          vid, bad)
                return
            self._checkpoint(vid)
            log.warning("ec volume %d: rebuilding %s shard(s) %s",
                        vid, verdict, bad)
            try:
                planner.repair_ec_volume(
                    damage.base, bad, backend=self.backend,
                    unmount=ecv.unmount_shard, remount=ecv.mount_shard)
            except (ValueError, OSError) as e:
                res.unrecoverable += len(bad)
                ScrubUnrecoverableCounter.inc(len(bad))
                res.details.append(
                    f"ec volume {vid}: rebuild of {bad} failed: {e}")
                log.error("ec volume %d: rebuild failed: %s", vid, e)
                return
            if self.on_repair is not None:
                self.on_repair(vid)
            vr = planner.verify_ec_repair(damage.base,
                                          backend=self.backend)
            res.stripes_verified += vr.spans
            ScrubStripesVerifiedCounter.inc(vr.spans)
            for k in kinds:
                res.corruptions_repaired += 1
                ScrubCorruptionsRepairedCounter.labels(k).inc()
            res.details.append(
                f"ec volume {vid}: shards {bad} reconstructed")
            # evidence for the next round: repaired shards are clean
            # by construction, only fresh parity mismatches remain
            damage = planner.EcDamage(
                base=damage.base,
                parity_mismatch=dict(vr.parity_mismatch),
                first_mismatch=dict(vr.first_mismatch),
                parity_checked=list(vr.parity_checked))
            if vr.clean:
                return
        log.error("ec volume %d: still inconsistent after %d repair "
                  "rounds", vid, rounds)
