"""VolumeGrowth: choose servers for new volume replicas honoring the
xyz replica placement (x = other data centers, y = other racks in the
same DC, z = other servers in the same rack).

Reference: weed/topology/volume_growth.go:70-240. The selection is
re-expressed as explicit candidate filtering + weighted sampling over
free slots instead of the reference's randomized node-walk callbacks.
"""

from __future__ import annotations

import random
from typing import List, Optional

from seaweedfs_tpu.storage.superblock import ReplicaPlacement
from seaweedfs_tpu.topology.node import DataNode


# how many volumes to grow per request, by total replica count
# (reference volume_growth.go:30-45: more replicas -> grow fewer at once)
def growth_count(copy_count: int) -> int:
    return {1: 7, 2: 6, 3: 3}.get(copy_count, 1)


class NoFreeSlots(Exception):
    pass


class VolumeGrowth:
    def __init__(self, topology):
        self.topo = topology

    def find_empty_slots(self, rp: ReplicaPlacement,
                         data_center: str = "") -> List[DataNode]:
        """Pick copy_count() nodes satisfying the placement grammar.

        Strategy: pick the main rack server cluster first (1 + same_rack
        servers in one rack, each on a distinct node), then same_dc
        racks, then other DCs — mirroring findEmptySlotsForOneVolume.
        """
        dcs = list(self.topo.data_centers.values())
        if data_center:
            dcs = [dc for dc in dcs if dc.id == data_center]
        random.shuffle(dcs)
        for dc in dcs:
            picked = self._try_dc(dc, rp)
            if picked is not None:
                return picked
        raise NoFreeSlots(
            f"no placement for {rp}: not enough free slots spread over "
            f"{'dc ' + data_center if data_center else 'the cluster'}")

    def _try_dc(self, dc, rp: ReplicaPlacement) -> Optional[List[DataNode]]:
        racks = [r for r in dc.racks.values() if r.free_slots() > 0]
        random.shuffle(racks)
        for main_rack in racks:
            nodes = [n for n in main_rack.nodes.values() if n.free_slots() > 0]
            if len(nodes) < 1 + rp.same_rack:
                continue
            main_nodes = random.sample(nodes, 1 + rp.same_rack)
            # other racks in this DC
            other_racks = [r for r in racks if r is not main_rack]
            if len(other_racks) < rp.diff_rack:
                continue
            rack_nodes = []
            for r in random.sample(other_racks, rp.diff_rack):
                cands = [n for n in r.nodes.values() if n.free_slots() > 0]
                if not cands:
                    break
                rack_nodes.append(random.choice(cands))
            if len(rack_nodes) < rp.diff_rack:
                continue
            # other DCs
            other_dcs = [d for d in self.topo.data_centers.values()
                         if d is not dc and d.free_slots() > 0]
            if len(other_dcs) < rp.diff_dc:
                continue
            dc_nodes = []
            for d in random.sample(other_dcs, rp.diff_dc):
                cands = [n for n in d.nodes() if n.free_slots() > 0]
                if not cands:
                    break
                dc_nodes.append(random.choice(cands))
            if len(dc_nodes) < rp.diff_dc:
                continue
            return main_nodes + rack_nodes + dc_nodes
        return None
