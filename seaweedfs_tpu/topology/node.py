"""The DataCenter -> Rack -> DataNode tree.

Reference: weed/topology/node.go, data_center.go, rack.go,
data_node.go, data_node_ec.go. Capacity accounting is recomputed from
the children on demand instead of incrementally adjusted — cluster
sizes (thousands of nodes) make O(children) walks cheap and remove the
reference's careful up-the-tree delta propagation.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from seaweedfs_tpu.ec.shard_bits import ShardBits, TOTAL_SHARDS


class VolumeInfo:
    """The master's record of one volume replica on one node
    (a plain-data mirror of Store.volume_info)."""

    __slots__ = ("id", "collection", "size", "file_count", "delete_count",
                 "deleted_byte_count", "read_only", "replica_placement",
                 "ttl", "version", "modified_at_second")

    def __init__(self, id: int, collection: str = "", size: int = 0,
                 file_count: int = 0, delete_count: int = 0,
                 deleted_byte_count: int = 0, read_only: bool = False,
                 replica_placement: int = 0, ttl: str = "", version: int = 3,
                 modified_at_second: int = 0,
                 **_ignored):
        self.id = id
        self.collection = collection
        self.size = size
        self.file_count = file_count
        self.delete_count = delete_count
        self.deleted_byte_count = deleted_byte_count
        self.read_only = read_only
        self.replica_placement = replica_placement
        self.ttl = ttl
        self.version = version
        self.modified_at_second = modified_at_second

    def to_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__}


class DataNode:
    def __init__(self, node_id: str, ip: str, port: int,
                 public_url: str = "", max_volumes: int = 8):
        self.id = node_id
        self.ip = ip
        self.port = port
        self.public_url = public_url or f"{ip}:{port}"
        self.max_volumes = max_volumes
        self.volumes: Dict[int, VolumeInfo] = {}
        self.ec_shards: Dict[int, ShardBits] = {}  # vid -> mounted shards
        self.ec_collections: Dict[int, str] = {}
        # vid -> (reads_window, ewma) from the heartbeat heat payload
        # (empty unless the server runs -heat.track)
        self.heat: Dict[int, tuple] = {}
        self.rack: Optional["Rack"] = None
        self.last_seen = time.time()

    @property
    def url(self) -> str:
        return f"{self.ip}:{self.port}"

    @property
    def volume_count(self) -> int:
        return len(self.volumes)

    @property
    def ec_shard_count(self) -> int:
        return sum(b.count for b in self.ec_shards.values())

    def free_slots(self) -> int:
        # EC shards consume slot capacity at shard granularity
        # (14 shards ~ 1.4 volumes of space but bookkept conservatively
        # as shards/total like the reference's slot math)
        used = self.volume_count + (
            self.ec_shard_count + TOTAL_SHARDS - 1) // TOTAL_SHARDS
        return max(0, self.max_volumes - used)

    def update_volumes(self, infos: List[dict]) -> tuple:
        """Full sync from a heartbeat; returns (new, deleted) VolumeInfos."""
        incoming = {int(i["id"]): VolumeInfo(**{**i, "id": int(i["id"])})
                    for i in infos}
        new = [v for vid, v in incoming.items() if vid not in self.volumes]
        deleted = [v for vid, v in self.volumes.items() if vid not in incoming]
        self.volumes = incoming
        self.last_seen = time.time()
        return new, deleted

    def update_heat(self, infos: List[dict]) -> bool:
        """Full sync of the heartbeat heat payload: the node's view is
        replaced wholesale, so a vid the server forgot (deleted volume,
        EC conversion) drops out of the cluster heat map on the very
        next pulse instead of freezing at its last value. Returns True
        when the VID SET changed — gauge children read values through
        scrape-time callables, so only membership changes need the
        (cluster-wide) gauge registry resync."""
        incoming = {int(h["id"]): (float(h.get("reads_window", 0)),
                                   float(h.get("ewma", 0.0)))
                    for h in infos}
        changed = incoming.keys() != self.heat.keys()
        self.heat = incoming
        return changed

    def update_ec_shards(self, infos: List[dict]) -> tuple:
        """Full sync of EC shard bits; returns (new, deleted) as
        (vid, ShardBits) pairs."""
        incoming: Dict[int, ShardBits] = {}
        collections: Dict[int, str] = {}
        for i in infos:
            vid = int(i["id"])
            bits = i["ec_index_bits"]
            if not isinstance(bits, ShardBits):
                bits = ShardBits(int(bits))
            incoming[vid] = bits
            collections[vid] = i.get("collection", "")
        new, deleted = [], []
        for vid, bits in incoming.items():
            prev = self.ec_shards.get(vid, ShardBits(0))
            gained = bits.minus(prev)
            if gained.count:
                new.append((vid, gained))
        for vid, prev in self.ec_shards.items():
            lost = prev.minus(incoming.get(vid, ShardBits(0)))
            if lost.count:
                deleted.append((vid, lost))
        self.ec_shards = incoming
        self.ec_collections = collections
        return new, deleted


class Rack:
    def __init__(self, rack_id: str):
        self.id = rack_id
        self.nodes: Dict[str, DataNode] = {}
        self.data_center: Optional["DataCenter"] = None

    def get_or_create_node(self, node_id: str, ip: str, port: int,
                           public_url: str = "",
                           max_volumes: int = 8) -> DataNode:
        dn = self.nodes.get(node_id)
        if dn is None:
            dn = DataNode(node_id, ip, port, public_url, max_volumes)
            dn.rack = self
            self.nodes[node_id] = dn
        dn.max_volumes = max_volumes or dn.max_volumes
        return dn

    def free_slots(self) -> int:
        return sum(n.free_slots() for n in self.nodes.values())


class DataCenter:
    def __init__(self, dc_id: str):
        self.id = dc_id
        self.racks: Dict[str, Rack] = {}

    def get_or_create_rack(self, rack_id: str) -> Rack:
        r = self.racks.get(rack_id)
        if r is None:
            r = Rack(rack_id)
            r.data_center = self
            self.racks[rack_id] = r
        return r

    def free_slots(self) -> int:
        return sum(r.free_slots() for r in self.racks.values())

    def nodes(self) -> List[DataNode]:
        return [n for r in self.racks.values() for n in r.nodes.values()]
