"""VolumeLayout: writable/readonly volume sets for one
(collection, replication, ttl) class.

Reference: weed/topology/volume_layout.go:16-140. State machine per vid:
a volume is writable iff it has the full replica count, no replica is
read-only, and it isn't oversized.
"""

from __future__ import annotations

import random
import threading
from typing import Dict, List, Optional

from seaweedfs_tpu.topology.node import DataNode, VolumeInfo


class VolumeLayout:
    def __init__(self, replica_count: int = 1, ttl: str = "",
                 volume_size_limit: int = 30 << 30):
        self.replica_count = max(1, replica_count)
        self.ttl = ttl
        self.volume_size_limit = volume_size_limit
        self.locations: Dict[int, List[DataNode]] = {}  # guarded_by(self._lock)
        self.writable: set[int] = set()  # guarded_by(self._lock)
        self.oversized: set[int] = set()  # guarded_by(self._lock)
        # vid -> node urls whose replica reports read-only (a vid is
        # readonly while ANY replica is; tracked per-node so a flip back
        # to writable on re-heartbeat clears correctly)
        self.readonly_on: Dict[int, set] = {}  # guarded_by(self._lock)
        self._lock = threading.RLock()

    def register(self, info: VolumeInfo, dn: DataNode) -> None:
        """Idempotent per-heartbeat state sync for one replica: location,
        read-only flag, and size class all refresh in both directions."""
        with self._lock:
            locs = self.locations.setdefault(info.id, [])
            if dn not in locs:
                locs.append(dn)
            ro = self.readonly_on.setdefault(info.id, set())
            if info.read_only:
                ro.add(dn.url)
            else:
                ro.discard(dn.url)
            if info.size >= self.volume_size_limit:
                self.oversized.add(info.id)
            else:
                self.oversized.discard(info.id)
            self._recheck(info.id)

    def unregister(self, vid: int, dn: DataNode) -> None:
        with self._lock:
            locs = self.locations.get(vid, [])
            if dn in locs:
                locs.remove(dn)
            self.readonly_on.get(vid, set()).discard(dn.url)
            if not locs:
                self.locations.pop(vid, None)
                self.writable.discard(vid)
                self.readonly_on.pop(vid, None)
                self.oversized.discard(vid)
            else:
                self._recheck(vid)

    def _recheck(self, vid: int) -> None:  # requires(self._lock)
        ok = (len(self.locations.get(vid, [])) >= self.replica_count
              and not self.readonly_on.get(vid)
              and vid not in self.oversized)
        if ok:
            self.writable.add(vid)
        else:
            self.writable.discard(vid)

    def set_oversized(self, vid: int) -> None:
        with self._lock:
            self.oversized.add(vid)
            self.writable.discard(vid)

    def pick_for_write(self) -> Optional[tuple[int, List[DataNode]]]:
        with self._lock:
            if not self.writable:
                return None
            vid = random.choice(tuple(self.writable))
            return vid, list(self.locations[vid])

    def lookup(self, vid: int) -> List[DataNode]:
        with self._lock:
            return list(self.locations.get(vid, []))

    @property
    def writable_count(self) -> int:
        return len(self.writable)

    def volume_ids(self) -> List[int]:
        with self._lock:
            return list(self.locations)
