"""Cluster metadata: the master's in-memory view.

DataCenter -> Rack -> DataNode tree with capacity counters, per-
(collection, replication, ttl) volume layouts, replica-placement-aware
volume growth, and the file-id sequencer.

Reference: weed/topology (topology.go, volume_layout.go,
volume_growth.go), weed/sequence.
"""

from seaweedfs_tpu.topology.node import DataNode, Rack, DataCenter
from seaweedfs_tpu.topology.topology import Topology
from seaweedfs_tpu.topology.volume_layout import VolumeLayout
from seaweedfs_tpu.topology.volume_growth import VolumeGrowth
from seaweedfs_tpu.topology.sequence import MemorySequencer

__all__ = [
    "DataNode", "Rack", "DataCenter", "Topology", "VolumeLayout",
    "VolumeGrowth", "MemorySequencer",
]
