"""Monotonic file-id sequencer (reference weed/sequence).

The memory sequencer hands out batches; its high-water mark is restored
from volume-server heartbeats (max_file_key) and persisted via the
master's raft snapshot in the reference — here the master snapshots it
to a small json file (seaweedfs_tpu/server/master.py).
"""

from __future__ import annotations

import threading


class MemorySequencer:
    # contiguous ids: the master raft-watermarks and snapshots them
    needs_watermark = True
    persistable = True

    def __init__(self, start: int = 1):
        self._next = max(1, start)
        self._lock = threading.Lock()

    def next_batch(self, count: int = 1) -> int:
        """Reserve `count` ids; returns the first."""
        with self._lock:
            first = self._next
            self._next += count
            return first

    def set_max(self, seen: int) -> None:
        """Raise the floor above any id observed in the wild."""
        with self._lock:
            if seen >= self._next:
                self._next = seen + 1

    @property
    def peek(self) -> int:
        return self._next


class SnowflakeSequencer:
    """Coordination-free unique ids: 41-bit millisecond timestamp,
    10-bit node id, 12-bit per-ms counter (the reference's snowflake
    option in master.toml [master.sequencer]; its etcd kind needs an
    etcd server and is not available in this image).

    Ids are unique across masters WITHOUT raft/etcd coordination, at
    the cost of non-contiguous key space.
    """

    EPOCH_MS = 1_600_000_000_000  # 2020-09-13, keeps 41 bits ample
    MAX_COUNTER = 0xFFF
    # time-based ids: no raft watermark needed, and snapshotting the
    # huge timestamp ids into sequence.json would poison a later
    # memory-sequencer restart
    needs_watermark = False
    persistable = False

    def __init__(self, node_id: int = 0):
        self.node_id = node_id & 0x3FF
        self._lock = threading.Lock()
        self._last_ms = 0
        self._counter = -1

    def _advance_ms(self) -> None:  # requires(self._lock)
        import time
        now_ms = int(time.time() * 1000) - self.EPOCH_MS
        # logical advance: reserving a near-future millisecond block is
        # cheaper than spinning and ids stay unique either way
        self._last_ms = max(now_ms, self._last_ms + 1)
        self._counter = -1

    def next_batch(self, count: int = 1) -> int:
        """Returns the first of `count` CONSECUTIVE ids. The range must
        fit one millisecond block (4096 ids) or first+count-1 would
        bleed into the node-id bits and collide with another master."""
        if count > self.MAX_COUNTER + 1:
            raise ValueError(
                f"snowflake cannot issue {count} consecutive ids "
                f"(max {self.MAX_COUNTER + 1} per batch)")
        with self._lock:
            import time
            now_ms = int(time.time() * 1000) - self.EPOCH_MS
            if now_ms > self._last_ms:
                self._last_ms = now_ms
                self._counter = -1
            if self._counter + count > self.MAX_COUNTER:
                self._advance_ms()
            first_counter = self._counter + 1
            self._counter += count
            return (self._last_ms << 22) | (self.node_id << 12) | \
                first_counter

    def set_max(self, seen: int) -> None:
        pass  # time-based: never collides with observed ids

    @property
    def peek(self) -> int:
        """Non-consuming: the id the next allocation would start at."""
        with self._lock:
            return (self._last_ms << 22) | (self.node_id << 12) | \
                min(self._counter + 1, self.MAX_COUNTER)


class EtcdSequencer:
    """Externally-coordinated contiguous ids (reference
    weed/sequence/etcd_sequencer.go): the high-water mark lives in one
    etcd key, advanced in CAS-claimed batches so any number of masters
    (even without raft) hand out disjoint ranges. Rides the JSON
    gateway client (util/etcd_client.py), no SDK."""

    KEY = b"weed_master_sequence"
    STEP = 100  # ids claimed per CAS round-trip (reference's batch)
    # etcd IS the watermark; nothing to snapshot locally
    needs_watermark = False
    persistable = False

    def __init__(self, endpoint: str = "127.0.0.1:2379"):
        from seaweedfs_tpu.util.etcd_client import EtcdClient
        self.client = EtcdClient(endpoint)
        self._lock = threading.Lock()
        self._next = 0   # next id to hand out locally
        self._ceiling = 0  # end (exclusive) of the claimed range

    def _claim(self, at_least: int) -> None:  # requires(self._lock)
        """CAS-advance the shared counter until a batch is claimed."""
        while True:
            cur = self.client.get(self.KEY)
            floor = int(cur) if cur else 1
            want = max(floor, at_least)
            new_ceiling = want + self.STEP
            if self.client.cas(self.KEY, cur, str(new_ceiling).encode()):
                self._next = want
                self._ceiling = new_ceiling
                return

    def next_batch(self, count: int = 1) -> int:
        with self._lock:
            if self._next + count > self._ceiling:
                self._claim(self._next)
                while self._next + count > self._ceiling:
                    # huge batch: keep claiming contiguously
                    cur = self.client.get(self.KEY)
                    if cur and int(cur) == self._ceiling and \
                            self.client.cas(
                                self.KEY, cur,
                                str(self._ceiling + self.STEP).encode()):
                        self._ceiling += self.STEP
                    else:
                        # lost contiguity to another master: restart
                        self._claim(self._ceiling)
            first = self._next
            self._next += count
            return first

    def set_max(self, seen: int) -> None:
        with self._lock:
            # ids below our claimed ceiling can only be our own or
            # another master's already-CAS-claimed range — no conflict.
            # Only an id at/above the ceiling means the etcd counter
            # state was lost (wiped cluster) and the floor must be
            # pushed up; re-claiming on every heartbeat would burn a
            # full STEP batch each time (review round 3).
            if seen >= self._ceiling:
                self._claim(seen + 1)

    @property
    def peek(self) -> int:
        with self._lock:
            return self._next
