"""Monotonic file-id sequencer (reference weed/sequence).

The memory sequencer hands out batches; its high-water mark is restored
from volume-server heartbeats (max_file_key) and persisted via the
master's raft snapshot in the reference — here the master snapshots it
to a small json file (seaweedfs_tpu/server/master.py).
"""

from __future__ import annotations

import threading


class MemorySequencer:
    def __init__(self, start: int = 1):
        self._next = max(1, start)
        self._lock = threading.Lock()

    def next_batch(self, count: int = 1) -> int:
        """Reserve `count` ids; returns the first."""
        with self._lock:
            first = self._next
            self._next += count
            return first

    def set_max(self, seen: int) -> None:
        """Raise the floor above any id observed in the wild."""
        with self._lock:
            if seen >= self._next:
                self._next = seen + 1

    @property
    def peek(self) -> int:
        return self._next
