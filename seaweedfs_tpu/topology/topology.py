"""Topology: the master's root object.

Heartbeat ingest, vid -> locations lookup (normal + EC), layout
bookkeeping, write assignment, dead-node reaping.

Reference: weed/topology/topology.go, topology_ec.go, and the
heartbeat handler server/master_grpc_server.go:20-176.
"""

from __future__ import annotations

import random
import threading
import time
import weakref
from typing import Dict, List, Optional, Tuple

from seaweedfs_tpu.ec.shard_bits import ShardBits
from seaweedfs_tpu.storage.superblock import ReplicaPlacement
from seaweedfs_tpu.topology.node import DataCenter, DataNode, VolumeInfo
from seaweedfs_tpu.topology.sequence import MemorySequencer
from seaweedfs_tpu.topology.volume_layout import VolumeLayout

# Topologies that have ever seen heartbeat heat, for the
# SeaweedFS_cluster_volume_heat{vid} gauge: children read through a
# weak set at scrape time (the stats/heat.py pattern) so a stopped
# master's topology is collectable and two in-process masters SUM
# rather than clobber. Registration happens only on heartbeats that
# carry heat, so heat-disabled clusters never touch any of this.
_HEAT_TOPOS: "weakref.WeakSet[Topology]" = weakref.WeakSet()
_heat_registered: set = set()
_heat_reg_lock = threading.Lock()


def _cluster_vid_heat(vid: int) -> float:
    total = 0.0
    for t in list(_HEAT_TOPOS):
        for n in t.nodes():
            h = n.heat.get(vid)
            if h is not None:
                total += h[0]
    return total


def _sync_cluster_heat_gauge(topo: "Topology") -> None:
    """Register gauge children for newly-heated vids and drop children
    for vids no longer reported anywhere — label hygiene at the
    cluster aggregate, mirroring HeatTracker.forget server-side."""
    from seaweedfs_tpu.stats.metrics import ClusterVolumeHeatGauge
    _HEAT_TOPOS.add(topo)
    live = {vid for t in list(_HEAT_TOPOS)
            for n in t.nodes() for vid in n.heat}
    with _heat_reg_lock:
        for vid in live - _heat_registered:
            ClusterVolumeHeatGauge.labels(str(vid)).set_function(
                lambda vid=vid: _cluster_vid_heat(vid))
        for vid in _heat_registered - live:
            ClusterVolumeHeatGauge.remove(str(vid))
        _heat_registered.clear()
        _heat_registered.update(live)


class Topology:
    def __init__(self, volume_size_limit: int = 30 << 30,
                 sequencer: Optional[MemorySequencer] = None,
                 pulse_seconds: float = 5.0):
        self.volume_size_limit = volume_size_limit
        self.sequence = sequencer or MemorySequencer()
        self.pulse_seconds = pulse_seconds
        self.data_centers: Dict[str, DataCenter] = {}
        # (collection, replica_byte, ttl) -> VolumeLayout
        self.layouts: Dict[Tuple[str, int, str], VolumeLayout] = {}
        self.ec_locations: Dict[int, Dict[str, ShardBits]] = {}  # vid -> url -> bits
        self.ec_collections: Dict[int, str] = {}
        # url -> node; membership changes take the lock, point reads
        # (nodes()/find_node snapshots) are GIL-atomic and may be stale
        self._nodes: Dict[str, DataNode] = {}  # guarded_by(self._lock, writes)
        self._lock = threading.RLock()
        self.next_volume_id = 1
        # subscribers to volume location deltas (KeepConnected analog)
        self.listeners: List = []

    # -- tree ---------------------------------------------------------------

    def get_or_create_dc(self, dc_id: str) -> DataCenter:
        dc = self.data_centers.get(dc_id)
        if dc is None:
            dc = DataCenter(dc_id)
            self.data_centers[dc_id] = dc
        return dc

    def nodes(self) -> List[DataNode]:
        return list(self._nodes.values())

    def find_node(self, url: str) -> Optional[DataNode]:
        return self._nodes.get(url)

    def free_slots(self) -> int:
        return sum(dc.free_slots() for dc in self.data_centers.values())

    # -- layouts ------------------------------------------------------------

    def layout_for(self, collection: str, replica_byte: int,
                   ttl: str = "") -> VolumeLayout:
        with self._lock:
            key = (collection, replica_byte, ttl)
            vl = self.layouts.get(key)
            if vl is None:
                rp = ReplicaPlacement.from_byte(replica_byte)
                vl = VolumeLayout(replica_count=rp.copy_count, ttl=ttl,
                                  volume_size_limit=self.volume_size_limit)
                self.layouts[key] = vl
            return vl

    # -- heartbeat ingest ----------------------------------------------------

    def sync_heartbeat(self, hb: dict, dc: str = "DefaultDataCenter",
                       rack: str = "DefaultRack") -> DataNode:
        """Full-state heartbeat from one volume server (dict shaped like
        Store.collect_heartbeat)."""
        with self._lock:
            url = f"{hb['ip']}:{hb['port']}"
            node = self._nodes.get(url)
            if node is None:
                node = self.get_or_create_dc(dc).get_or_create_rack(rack) \
                    .get_or_create_node(
                        url, hb["ip"], hb["port"],
                        hb.get("public_url", ""),
                        hb.get("max_volume_count", 8))
                self._nodes[url] = node
            node.max_volumes = hb.get("max_volume_count", node.max_volumes)
            self.sequence.set_max(hb.get("max_file_key", 0))

            new, deleted = node.update_volumes(hb.get("volumes", []))
            # re-register every current volume: register() is the
            # idempotent state sync (size growth past the limit, a
            # read_only flip, etc. must reach the layout every pulse)
            for v in node.volumes.values():
                self.register_volume(v, node)
            for v in deleted:
                self.unregister_volume(v, node)
            ec_changed = self._sync_ec(node, hb.get("ec_shards", []))
            heats = hb.get("volume_heats")
            if heats is not None or node.heat:
                # one dict-key check per pulse when heat is disabled;
                # the `or node.heat` arm clears a node whose operator
                # turned -heat.track off mid-flight. The gauge-registry
                # resync (a cluster-wide vid-set walk) runs only when
                # this node's heat MEMBERSHIP changed — values flow
                # through scrape-time callables regardless
                if node.update_heat(heats or []):
                    _sync_cluster_heat_gauge(self)
            if new or deleted or ec_changed:
                self._notify()
            return node

    def register_volume(self, info: VolumeInfo, dn: DataNode) -> None:
        with self._lock:
            if info.id >= self.next_volume_id:
                self.next_volume_id = info.id + 1
            self.layout_for(info.collection, info.replica_placement,
                            info.ttl).register(info, dn)

    def unregister_volume(self, info: VolumeInfo, dn: DataNode) -> None:
        with self._lock:
            self.layout_for(info.collection, info.replica_placement,
                            info.ttl).unregister(info.id, dn)

    def _sync_ec(self, node: DataNode, infos: List[dict]) -> bool:
        """Returns True when any shard location changed (drives the
        KeepConnected delta notification like normal volumes do)."""
        new, deleted = node.update_ec_shards(infos)
        for vid, by_url in list(self.ec_locations.items()):
            by_url.pop(node.url, None)
        for vid, bits in node.ec_shards.items():
            self.ec_locations.setdefault(vid, {})[node.url] = bits
            self.ec_collections[vid] = node.ec_collections.get(vid, "")
        self.ec_locations = {vid: by_url for vid, by_url
                             in self.ec_locations.items() if by_url}
        self.ec_collections = {vid: col for vid, col
                               in self.ec_collections.items()
                               if vid in self.ec_locations}
        return bool(new or deleted)

    def unregister_node(self, url: str) -> None:
        """Heartbeat stream broke: drop the node and its volumes
        (reference master_grpc_server.go:22-50)."""
        with self._lock:
            node = self._nodes.pop(url, None)
            if node is None:
                return
            for info in node.volumes.values():
                self.unregister_volume(info, node)
            for vid in list(node.ec_shards):
                by_url = self.ec_locations.get(vid)
                if by_url:
                    by_url.pop(url, None)
                    if not by_url:
                        self.ec_locations.pop(vid, None)
                        self.ec_collections.pop(vid, None)
            if node.rack is not None:
                node.rack.nodes.pop(node.id, None)
            if node.heat:
                node.heat = {}
                _sync_cluster_heat_gauge(self)
            self._notify()

    def reap_dead_nodes(self, max_silence: Optional[float] = None) -> List[str]:
        """Drop nodes that missed heartbeats (pull-based failure
        detection; the gRPC stream break is the push-based path)."""
        max_silence = max_silence or self.pulse_seconds * 5
        now = time.time()
        with self._lock:
            dead = [url for url, n in self._nodes.items()
                    if now - n.last_seen > max_silence]
            for url in dead:
                self.unregister_node(url)
        return dead

    # -- lookup / assign ------------------------------------------------------

    def lookup(self, vid: int, collection: str = "") -> List[DataNode]:
        """vid -> replica locations (normal volumes)."""
        with self._lock:
            for (col, _, _), vl in self.layouts.items():
                if collection and col != collection:
                    continue
                locs = vl.lookup(vid)
                if locs:
                    return locs
            return []

    def lookup_ec(self, vid: int) -> Dict[str, ShardBits]:
        with self._lock:
            return dict(self.ec_locations.get(vid, {}))

    # -- cluster heat map ------------------------------------------------------

    def cluster_heat(self) -> Dict[int, dict]:
        """vid -> {reads_window, ewma, servers}: the live cluster heat
        map summed over every node's heartbeat heat payload — what the
        lifecycle policy engine (and `cluster.heat`) decides from."""
        with self._lock:
            out: Dict[int, dict] = {}
            for n in self._nodes.values():
                for vid, (window, ewma) in n.heat.items():
                    rec = out.setdefault(
                        vid, {"reads_window": 0.0, "ewma": 0.0,
                              "servers": []})
                    rec["reads_window"] += window
                    rec["ewma"] += ewma
                    rec["servers"].append(n.url)
            return out

    def has_writable(self, collection: str, replica_byte: int,
                     ttl: str = "") -> bool:
        return self.layout_for(
            collection, replica_byte, ttl).writable_count > 0

    def pick_for_write(self, count: int = 1, collection: str = "",
                       replica_byte: int = 0, ttl: str = ""):
        """Assign a file id: (fid, count, DataNode list) or None.

        fid format mirrors the reference: "<vid>,<key_hex><cookie_hex8>".
        """
        vl = self.layout_for(collection, replica_byte, ttl)
        picked = vl.pick_for_write()
        if picked is None:
            return None
        vid, locs = picked
        key = self.sequence.next_batch(count)
        cookie = random.getrandbits(32)
        fid = f"{vid},{key:x}{cookie:08x}"
        return fid, count, locs

    def reserve_volume_ids(self, count: int) -> List[int]:
        with self._lock:
            first = self.next_volume_id
            self.next_volume_id += count
            return list(range(first, first + count))

    def adjust_max_volume_id(self, vid: int) -> None:
        """Raise the next-volume-id floor (raft MaxVolumeId command
        replay; reference topology.go UpAdjustMaxVolumeId)."""
        with self._lock:
            if vid >= self.next_volume_id:
                self.next_volume_id = vid + 1

    # -- deltas to subscribers ------------------------------------------------

    def _notify(self) -> None:
        for cb in list(self.listeners):
            try:
                cb()
            # lint: swallow-ok(evicting the failing listener IS the handling)
            except Exception:
                self.listeners.remove(cb)

    # -- map output -----------------------------------------------------------

    def to_map(self) -> dict:
        """Topology snapshot as plain data (the UI/shell view; the house
        test pattern fabricates these)."""
        with self._lock:
            return {
                "max_volume_count": sum(
                    n.max_volumes for n in self._nodes.values()),
                "free_slots": self.free_slots(),
                "data_centers": [{
                    "id": dc.id,
                    "racks": [{
                        "id": r.id,
                        "nodes": [{
                            "url": n.url,
                            "public_url": n.public_url,
                            "volumes": [v.to_dict()
                                        for v in n.volumes.values()],
                            "ec_shards": [{
                                "id": vid,
                                "collection":
                                    n.ec_collections.get(vid, ""),
                                "ec_index_bits": int(bits),
                            } for vid, bits in n.ec_shards.items()],
                            "max_volumes": n.max_volumes,
                        } for n in r.nodes.values()],
                    } for r in dc.racks.values()],
                } for dc in self.data_centers.values()],
            }
