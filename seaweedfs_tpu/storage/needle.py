"""Needle: one stored blob in a volume file.

On-disk layout (version 2/3; reference weed/storage/needle/
needle_read_write.go:33-157, all integers big-endian):

  header:  cookie(4) id(8) size(4)
  body:    dataSize(4) data flags(1)
           [nameSize(1) name]         if FLAG_HAS_NAME
           [mimeSize(1) mime]         if FLAG_HAS_MIME
           [lastModified(5)]          if FLAG_HAS_LAST_MODIFIED
           [ttl(2)]                   if FLAG_HAS_TTL
           [pairsSize(2) pairs]       if FLAG_HAS_PAIRS
  tail:    checksum(4) [appendAtNs(8) v3 only] padding(1..8)

`size` covers the body only; the record is padded so its total length is a
multiple of 8 (note the reference's formula yields 8 pad bytes, not 0, when
already aligned — we reproduce that for byte compatibility). The checksum
is CRC32-Castagnoli over `data` with the snappy-style mask
(reference weed/storage/needle/crc.go:24-26).
"""

from __future__ import annotations

import struct
import time
from dataclasses import dataclass, field
from typing import Optional

from seaweedfs_tpu.native import rs_native
from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.superblock import TTL

FLAG_IS_COMPRESSED = 0x01
FLAG_HAS_NAME = 0x02
FLAG_HAS_MIME = 0x04
FLAG_HAS_LAST_MODIFIED = 0x08
FLAG_HAS_TTL = 0x10
FLAG_HAS_PAIRS = 0x20
FLAG_IS_CHUNK_MANIFEST = 0x80

LAST_MODIFIED_BYTES = 5
TTL_BYTES = 2

VERSION2 = 2
VERSION3 = 3


def masked_crc(data: bytes) -> int:
    """CRC32C with the snappy rotation mask — the needle checksum."""
    c = rs_native.crc32c(data)
    return (((c >> 15) | (c << 17)) + 0xA282EAD8) & 0xFFFFFFFF


def padding_length(size: int, version: int = VERSION3) -> int:
    base = t.NEEDLE_HEADER_SIZE + size + t.NEEDLE_CHECKSUM_SIZE
    if version == VERSION3:
        base += t.TIMESTAMP_SIZE
    return t.NEEDLE_PADDING - (base % t.NEEDLE_PADDING)


def body_length(size: int, version: int = VERSION3) -> int:
    base = size + t.NEEDLE_CHECKSUM_SIZE + padding_length(size, version)
    if version == VERSION3:
        base += t.TIMESTAMP_SIZE
    return base


def actual_size(size: int, version: int = VERSION3) -> int:
    return t.NEEDLE_HEADER_SIZE + body_length(size, version)


class NeedleError(Exception):
    pass


class CookieMismatch(NeedleError):
    pass


class DataCorruptionError(NeedleError):
    """Stored bytes fail their checksum: silent corruption, not a
    protocol error. Typed so read paths and the scrub subsystem can
    route it to repair instead of treating it like a missing needle."""


def verify_needle_integrity(n: "Needle") -> None:
    """Raise DataCorruptionError unless n.data matches the stored
    masked CRC. The one integrity predicate shared by the read path
    (SEAWEED_VERIFY_READS) and the scrub scanner."""
    if n.size > 0 and n.checksum != masked_crc(n.data):
        raise DataCorruptionError(
            f"needle {n.id:x} crc mismatch: stored {n.checksum:08x} "
            f"!= computed {masked_crc(n.data):08x}")


@dataclass
class Needle:
    id: int = 0
    cookie: int = 0
    data: bytes = b""
    flags: int = 0
    name: bytes = b""
    mime: bytes = b""
    pairs: bytes = b""
    last_modified: int = 0  # unix seconds
    ttl: Optional[TTL] = None
    checksum: int = 0  # masked crc, filled on serialize/parse
    append_at_ns: int = 0
    size: int = field(default=0)  # body size as stored in the header

    # -- flag helpers --------------------------------------------------------

    @property
    def is_compressed(self) -> bool:
        return bool(self.flags & FLAG_IS_COMPRESSED)

    @property
    def is_chunk_manifest(self) -> bool:
        return bool(self.flags & FLAG_IS_CHUNK_MANIFEST)

    def _sync_flags(self) -> None:
        if self.name:
            self.flags |= FLAG_HAS_NAME
        if self.mime:
            self.flags |= FLAG_HAS_MIME
        if self.last_modified:
            self.flags |= FLAG_HAS_LAST_MODIFIED
        if self.ttl is not None and not self.ttl.is_empty:
            self.flags |= FLAG_HAS_TTL
        if self.pairs:
            self.flags |= FLAG_HAS_PAIRS

    # -- serialization -------------------------------------------------------

    def to_bytes(self, version: int = VERSION3) -> bytes:
        """Serialize, updating self.size/checksum/append_at_ns."""
        self._sync_flags()
        name = self.name[:255]
        mime = self.mime[:255]
        body = bytearray()
        if len(self.data) > 0:
            body += struct.pack(">I", len(self.data))
            body += self.data
            body.append(self.flags)
            if self.flags & FLAG_HAS_NAME:
                body.append(len(name))
                body += name
            if self.flags & FLAG_HAS_MIME:
                body.append(len(mime))
                body += mime
            if self.flags & FLAG_HAS_LAST_MODIFIED:
                body += struct.pack(">Q", self.last_modified)[8 - LAST_MODIFIED_BYTES:]
            if self.flags & FLAG_HAS_TTL:
                body += (self.ttl or TTL.empty()).to_bytes()
            if self.flags & FLAG_HAS_PAIRS:
                body += struct.pack(">H", len(self.pairs))
                body += self.pairs
        self.size = len(body)
        self.checksum = masked_crc(self.data)
        if version == VERSION3 and self.append_at_ns == 0:
            self.append_at_ns = time.time_ns()
        out = bytearray()
        out += struct.pack(">IQI", self.cookie, self.id, self.size)
        out += body
        out += struct.pack(">I", self.checksum)
        if version == VERSION3:
            out += struct.pack(">Q", self.append_at_ns)
        out += b"\x00" * padding_length(self.size, version)
        return bytes(out)

    @classmethod
    def from_bytes(cls, blob: bytes, version: int = VERSION3,
                   check_crc: bool = True) -> "Needle":
        """Parse a full needle record (header+body+tail) as written."""
        if len(blob) < t.NEEDLE_HEADER_SIZE:
            raise NeedleError("needle blob too short")
        cookie, nid, size_u = struct.unpack_from(">IQI", blob, 0)
        size = t.size_to_int32(size_u)
        if t.size_is_deleted(size):
            raise NeedleError(f"needle size {size} marks a tombstone")
        n = cls(id=nid, cookie=cookie, size=size)
        n._parse_body(blob[t.NEEDLE_HEADER_SIZE:t.NEEDLE_HEADER_SIZE + size])
        tail_off = t.NEEDLE_HEADER_SIZE + size
        (n.checksum,) = struct.unpack_from(">I", blob, tail_off)
        if version == VERSION3:
            (n.append_at_ns,) = struct.unpack_from(">Q", blob, tail_off + 4)
        if check_crc:
            verify_needle_integrity(n)
        return n

    @classmethod
    def from_disk_meta(cls, header: bytes, meta: bytes,
                       data_size: int,
                       version: int = VERSION3) -> "Needle":
        """Parse a needle from its header + post-payload bytes only —
        the zero-copy read path (Store.read_needle_span): the payload
        stays on disk and ships via sendfile, so only the two small
        metadata regions are read. ``meta`` starts at the flags byte
        (immediately after the payload) and runs through the checksum
        (+ appendAtNs on v3). ``data`` stays empty; callers use the
        span's length where read_needle callers use len(data)."""
        if len(header) < t.NEEDLE_HEADER_SIZE:
            raise NeedleError("needle blob too short")
        cookie, nid, size_u = struct.unpack_from(">IQI", header, 0)
        size = t.size_to_int32(size_u)
        if t.size_is_deleted(size):
            raise NeedleError(f"needle size {size} marks a tombstone")
        n = cls(id=nid, cookie=cookie, size=size)
        off = 0
        if size > 0:
            n.flags = meta[off]
            off += 1
            if n.flags & FLAG_HAS_NAME:
                ln = meta[off]
                off += 1
                n.name = meta[off:off + ln]
                off += ln
            if n.flags & FLAG_HAS_MIME:
                lm = meta[off]
                off += 1
                n.mime = meta[off:off + lm]
                off += lm
            if n.flags & FLAG_HAS_LAST_MODIFIED:
                n.last_modified = int.from_bytes(
                    meta[off:off + LAST_MODIFIED_BYTES], "big")
                off += LAST_MODIFIED_BYTES
            if n.flags & FLAG_HAS_TTL:
                n.ttl = TTL.from_bytes(meta[off:off + TTL_BYTES])
                off += TTL_BYTES
            if n.flags & FLAG_HAS_PAIRS:
                (ps,) = struct.unpack_from(">H", meta, off)
                off += 2
                n.pairs = meta[off:off + ps]
                off += ps
        (n.checksum,) = struct.unpack_from(">I", meta, off)
        if version == VERSION3:
            (n.append_at_ns,) = struct.unpack_from(">Q", meta, off + 4)
        # consistency guard: the attr walk must land exactly on the
        # checksum the size field promises (a torn/garbled record
        # would misparse silently otherwise)
        expect_attrs = size - 4 - data_size if size > 0 else 0
        if off != expect_attrs:
            raise NeedleError(
                f"needle {nid:x}: meta walk ended at {off}, "
                f"expected {expect_attrs}")
        return n

    def _parse_body(self, body: bytes) -> None:
        if not body:
            return
        (data_size,) = struct.unpack_from(">I", body, 0)
        off = 4
        self.data = body[off:off + data_size]
        off += data_size
        self.flags = body[off]
        off += 1
        if self.flags & FLAG_HAS_NAME:
            ln = body[off]
            off += 1
            self.name = body[off:off + ln]
            off += ln
        if self.flags & FLAG_HAS_MIME:
            lm = body[off]
            off += 1
            self.mime = body[off:off + lm]
            off += lm
        if self.flags & FLAG_HAS_LAST_MODIFIED:
            self.last_modified = int.from_bytes(
                body[off:off + LAST_MODIFIED_BYTES], "big")
            off += LAST_MODIFIED_BYTES
        if self.flags & FLAG_HAS_TTL:
            self.ttl = TTL.from_bytes(body[off:off + TTL_BYTES])
            off += TTL_BYTES
        if self.flags & FLAG_HAS_PAIRS:
            (ps,) = struct.unpack_from(">H", body, off)
            off += 2
            self.pairs = body[off:off + ps]
            off += ps

    # -- TTL -----------------------------------------------------------------

    def has_expired(self, now: Optional[float] = None) -> bool:
        if self.ttl is None or self.ttl.is_empty or not self.last_modified:
            return False
        now = time.time() if now is None else now
        return now >= self.last_modified + self.ttl.minutes * 60

    @property
    def etag(self) -> str:
        return f"{self.checksum:08x}"
