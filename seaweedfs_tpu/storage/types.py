"""Core on-disk scalar types and the file-id grammar.

Mirrors the reference's weed/storage/types (needle_types.go:34-39,
offset_4bytes.go / offset_5bytes.go) and weed/storage/needle/file_id.go:
  - NeedleId: 8 bytes big-endian
  - Offset: 4 bytes big-endian (default), in units of 8
    (NEEDLE_PADDING) -> 32GB volumes; setting
    SEAWEEDFS_TPU_5BYTE_OFFSET=1 in the environment selects the
    reference's `-tags 5BytesOffset` build variant (Makefile:18): a
    5th HIGH byte after the little-32 big-endian prefix -> 8TB
    volumes. Like the reference's build tag this is a
    process-lifetime, deployment-wide format choice — .idx files
    written by the two variants are incompatible.
  - Size: 4 bytes big-endian, int32 semantics; -1 (0xFFFFFFFF) = tombstone
  - fid string: "<volumeId>,<key hex><cookie 8-hex>"
"""

from __future__ import annotations

import os
import secrets
import struct
from dataclasses import dataclass

NEEDLE_ID_SIZE = 8
OFFSET_SIZE = 5 if os.environ.get("SEAWEEDFS_TPU_5BYTE_OFFSET") == "1" \
    else 4
SIZE_SIZE = 4
COOKIE_SIZE = 4
NEEDLE_HEADER_SIZE = COOKIE_SIZE + NEEDLE_ID_SIZE + SIZE_SIZE  # 16
NEEDLE_MAP_ENTRY_SIZE = NEEDLE_ID_SIZE + OFFSET_SIZE + SIZE_SIZE  # 16 or 17
TIMESTAMP_SIZE = 8
NEEDLE_PADDING = 8
NEEDLE_CHECKSUM_SIZE = 4
TOMBSTONE_SIZE = -1  # Size(-1) marks a deleted needle in the index
# (2^(8*OFFSET_SIZE)) padding units: 32GB at 4 bytes, 8TB at 5
MAX_POSSIBLE_VOLUME_SIZE = (1 << (8 * OFFSET_SIZE)) * NEEDLE_PADDING


def size_is_deleted(size: int) -> bool:
    return size < 0 or size == TOMBSTONE_SIZE


def size_is_valid(size: int) -> bool:
    return size > 0 and size != TOMBSTONE_SIZE


def size_to_int32(size: int) -> int:
    """Reinterpret a uint32 read from disk as int32 Size semantics."""
    return size - (1 << 32) if size >= (1 << 31) else size


def offset_units_to_bytes(units: int) -> bytes:
    """Padding-unit offset -> wire bytes. 4-byte: plain big-endian.
    5-byte: big-endian low 32 bits THEN the high byte (reference
    offset_5bytes.go OffsetToBytes — the prefix stays identical to the
    4-byte format for offsets under 32GB)."""
    if OFFSET_SIZE == 4:
        return struct.pack(">I", units)
    return struct.pack(">I", units & 0xFFFFFFFF) + bytes([units >> 32])


def bytes_to_offset_units(b: bytes) -> int:
    low = struct.unpack(">I", b[:4])[0]
    if OFFSET_SIZE == 4:
        return low
    return (b[4] << 32) | low


def offset_to_bytes(actual_offset: int) -> bytes:
    """Store actual byte offset / 8 as OFFSET_SIZE wire bytes."""
    if actual_offset % NEEDLE_PADDING != 0:
        raise ValueError(f"offset {actual_offset} not 8-byte aligned")
    return offset_units_to_bytes(actual_offset // NEEDLE_PADDING)


def bytes_to_offset(b: bytes) -> int:
    """Return the *actual* byte offset (stored unit * 8)."""
    return bytes_to_offset_units(b) * NEEDLE_PADDING


def new_cookie() -> int:
    return secrets.randbits(32)


@dataclass(frozen=True)
class FileId:
    """volumeId,keyHexCookieHex — the public blob address."""

    volume_id: int
    key: int
    cookie: int

    def __str__(self) -> str:
        return f"{self.volume_id},{self.key:x}{self.cookie:08x}"

    @property
    def needle_id_cookie(self) -> str:
        return f"{self.key:x}{self.cookie:08x}"

    @classmethod
    def parse(cls, fid: str) -> "FileId":
        fid = fid.strip()
        if "," not in fid:
            raise ValueError(f"bad fid {fid!r}: missing comma")
        vid_s, rest = fid.split(",", 1)
        # optional "_appendDelta" suffix used by chunked uploads
        delta = 0
        if "_" in rest:
            rest, delta_s = rest.split("_", 1)
            delta = int(delta_s)
        if len(rest) <= COOKIE_SIZE * 2:
            raise ValueError(f"bad fid {fid!r}: key+cookie too short")
        if len(rest) > (NEEDLE_ID_SIZE + COOKIE_SIZE) * 2:
            raise ValueError(f"bad fid {fid!r}: key+cookie too long")
        split = len(rest) - COOKIE_SIZE * 2
        key = int(rest[:split], 16) + delta
        cookie = int(rest[split:], 16)
        return cls(volume_id=int(vid_s), key=key, cookie=cookie)
