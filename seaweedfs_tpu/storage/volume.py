"""Volume: one append-only .dat + .idx pair.

Behavioral parity with the reference volume engine
(weed/storage/volume_read_write.go, volume_loading.go,
volume_checking.go): cookie-checked overwrites, tombstone deletes (an
empty needle appended to .dat + a size=-1 .idx entry), TTL expiry on
read, torn-tail truncation at load.

Python is fine here: the hot byte work (CRC) is native, and appends are
single `write` syscalls. The reference's async group-commit worker
(volume_read_write.go:331-405) is replaced by a per-volume lock; the
group-commit batching optimization can layer on later without format
changes.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.needle import (
    Needle, NeedleError, CookieMismatch, actual_size, VERSION3,
)
from seaweedfs_tpu.storage.needle_map import NeedleMap
from seaweedfs_tpu.storage.superblock import SuperBlock, ReplicaPlacement, TTL
from seaweedfs_tpu.storage import idx as idx_codec


class VolumeError(Exception):
    pass


class Volume:
    def __init__(self, dirname: str, collection: str, vid: int,
                 replica_placement: ReplicaPlacement = ReplicaPlacement(),
                 ttl: TTL = TTL.empty(),
                 create_if_missing: bool = True):
        self.dir = dirname
        self.collection = collection
        self.id = vid
        self.version = VERSION3
        self.read_only = False
        self.last_append_at_ns = 0
        self.last_modified_ts = 0
        self._lock = threading.RLock()
        base = self.file_name()
        self.dat_path = base + ".dat"
        self.idx_path = base + ".idx"
        existing = os.path.exists(self.dat_path)
        if not existing and not create_if_missing:
            raise VolumeError(f"volume file {self.dat_path} missing")
        if existing:
            self._load()
            if replica_placement != ReplicaPlacement() and \
                    replica_placement != self.super_block.replica_placement:
                # keep what's on disk; caller sees the difference via attrs
                pass
        else:
            self.super_block = SuperBlock(
                version=VERSION3, replica_placement=replica_placement, ttl=ttl)
            self._dat = open(self.dat_path, "w+b")
            self._dat.write(self.super_block.to_bytes())
            self._dat.flush()
            self.nm = NeedleMap(self.idx_path)

    # -- naming --------------------------------------------------------------

    def file_name(self) -> str:
        name = f"{self.collection}_{self.id}" if self.collection else str(self.id)
        return os.path.join(self.dir, name)

    @property
    def ttl(self) -> TTL:
        return self.super_block.ttl

    @property
    def replica_placement(self) -> ReplicaPlacement:
        return self.super_block.replica_placement

    # -- loading / integrity -------------------------------------------------

    def _load(self) -> None:
        from seaweedfs_tpu.storage.vacuum import recover_compaction
        recover_compaction(self.file_name())
        self._dat = open(self.dat_path, "r+b")
        header = self._dat.read(8)
        if len(header) < 8:
            raise VolumeError(f"{self.dat_path}: truncated superblock")
        self.super_block = SuperBlock.from_bytes(header)
        self.version = self.super_block.version
        self.nm = NeedleMap(self.idx_path)
        self._check_and_fix_integrity()

    def _check_and_fix_integrity(self) -> None:
        """Truncate a torn tail: the .dat must end exactly after the last
        needle recorded in the .idx (reference volume_checking.go:16-66).

        An absent/empty .idx means nothing is known about the volume —
        like the reference, do NOT truncate in that case (the .idx may
        simply be lost; `weed fix` / Volume.rebuild_index recovers it).
        """
        dat_size = os.path.getsize(self.dat_path)
        idx_size = os.path.getsize(self.idx_path) \
            if os.path.exists(self.idx_path) else 0
        if idx_size == 0:
            return
        with open(self.idx_path, "rb") as f:
            arr = idx_codec.parse_index_bytes(f.read())
        if not len(arr):
            return
        import numpy as np
        sizes = arr["size"].astype(np.int64)
        body = np.where(sizes < 0, 0, sizes)
        ends = arr["offset"] + [actual_size(int(s), self.version) for s in body]
        expected = int(max(ends.max(), 8))
        if dat_size > expected:
            self._dat.truncate(expected)
        elif dat_size < expected:
            raise VolumeError(
                f"{self.dat_path}: data file shorter ({dat_size}) than the "
                f"index implies ({expected})")

    # -- write path ----------------------------------------------------------

    def write_needle(self, n: Needle, fsync: bool = False) -> tuple[int, int]:
        """Append a needle; returns (offset, size). Cookie-checked overwrite."""
        if len(n.data) == 0:
            raise VolumeError(
                "zero-byte writes are not storable (indistinguishable from "
                "a delete marker); reject at the write path")
        with self._lock:
            if self.read_only:
                raise VolumeError(f"volume {self.id} is read-only")
            if n.ttl is None or n.ttl.is_empty:
                if not self.ttl.is_empty:
                    n.ttl = self.ttl
            existing = self.nm.get(n.id)
            if existing is not None:
                old = self._read_needle_at(existing.offset, existing.size,
                                           check_crc=False)
                if old.cookie != n.cookie:
                    raise CookieMismatch(
                        f"needle {n.id:x}: cookie mismatch {n.cookie:08x}")
            n.append_at_ns = time.time_ns()
            blob = n.to_bytes(self.version)
            offset = self._append_blob(blob, fsync)
            self.last_append_at_ns = n.append_at_ns
            if n.last_modified > self.last_modified_ts:
                self.last_modified_ts = n.last_modified
            self.nm.put(n.id, offset, n.size)
            return offset, n.size

    def _append_blob(self, blob: bytes, fsync: bool = False) -> int:
        self._dat.seek(0, os.SEEK_END)
        offset = self._dat.tell()
        if offset % t.NEEDLE_PADDING != 0:
            pad = t.NEEDLE_PADDING - offset % t.NEEDLE_PADDING
            self._dat.write(b"\x00" * pad)
            offset += pad
        if offset + len(blob) > t.MAX_POSSIBLE_VOLUME_SIZE:
            raise VolumeError(f"volume {self.id} exceeds max size")
        self._dat.write(blob)
        self._dat.flush()
        if fsync:
            os.fsync(self._dat.fileno())
        return offset

    def delete_needle(self, n: Needle) -> int:
        """Tombstone a needle; returns freed size (0 if absent)."""
        with self._lock:
            if self.read_only:
                raise VolumeError(f"volume {self.id} is read-only")
            nv = self.nm.get(n.id)
            if nv is None:
                return 0
            if n.cookie:
                old = self._read_needle_at(nv.offset, nv.size, check_crc=False)
                if old.cookie != n.cookie:
                    raise CookieMismatch(
                        f"needle {n.id:x}: delete cookie mismatch")
            freed = nv.size
            marker = Needle(id=n.id, cookie=n.cookie, data=b"")
            marker.append_at_ns = time.time_ns()
            blob = marker.to_bytes(self.version)
            offset = self._append_blob(blob)
            self.last_append_at_ns = marker.append_at_ns
            self.nm.delete(n.id, offset)
            return freed

    # -- read path -----------------------------------------------------------

    def read_needle(self, n: Needle) -> Needle:
        """Fill a needle by id; raises NeedleError if absent/expired,
        CookieMismatch if the cookie doesn't match."""
        with self._lock:
            nv = self.nm.get(n.id)
            if nv is None or not t.size_is_valid(nv.size):
                raise NeedleError(f"needle {n.id:x} not found")
            got = self._read_needle_at(nv.offset, nv.size)
        if n.cookie and got.cookie != n.cookie:
            raise CookieMismatch(
                f"needle {n.id:x}: cookie {n.cookie:08x} != {got.cookie:08x}")
        if got.has_expired():
            raise NeedleError(f"needle {n.id:x} expired")
        return got

    def _read_needle_at(self, offset: int, size: int,
                        check_crc: bool = True) -> Needle:
        length = actual_size(size, self.version)
        self._dat.seek(offset)
        blob = self._dat.read(length)
        if len(blob) < length:
            raise NeedleError(
                f"short read at {offset}: {len(blob)} < {length}")
        return Needle.from_bytes(blob, self.version, check_crc=check_crc)

    # -- scanning (vacuum / ec / export) -------------------------------------

    def scan_needles(self, include_deleted: bool = False):
        """Yield (offset, Needle) for every record in the .dat, in order.

        Opens its own read-only fd so a long-running scan (vacuum, EC
        encode, export) never races reads/writes on the shared handle.
        """
        import struct
        size = os.path.getsize(self.dat_path)
        offset = 8
        with open(self.dat_path, "rb") as f:
            while offset + t.NEEDLE_HEADER_SIZE <= size:
                f.seek(offset)
                header = f.read(t.NEEDLE_HEADER_SIZE)
                if len(header) < t.NEEDLE_HEADER_SIZE:
                    break
                cookie, nid, size_u = struct.unpack(">IQI", header)
                body_size = t.size_to_int32(size_u)
                if t.size_is_deleted(body_size):
                    body_size = 0
                length = actual_size(body_size, self.version)
                f.seek(offset)
                blob = f.read(length)
                if len(blob) < length:
                    break
                try:
                    n = Needle.from_bytes(blob, self.version, check_crc=False)
                    is_marker = len(n.data) == 0
                    if include_deleted or not is_marker:
                        yield offset, n
                except NeedleError:
                    pass
                offset += length

    # -- stats / lifecycle ---------------------------------------------------

    @property
    def content_size(self) -> int:
        return os.path.getsize(self.dat_path)

    @property
    def file_count(self) -> int:
        return len(self.nm)

    @property
    def deleted_count(self) -> int:
        return self.nm.deleted_count

    @property
    def deleted_size(self) -> int:
        return self.nm.deleted_size

    def garbage_ratio(self) -> float:
        cs = self.content_size
        return (self.nm.deleted_size / cs) if cs > 8 else 0.0

    def is_full(self, volume_size_limit: int) -> bool:
        return self.content_size >= volume_size_limit

    def sync(self) -> None:
        self._dat.flush()
        os.fsync(self._dat.fileno())
        self.nm.sync()

    def close(self) -> None:
        with self._lock:
            self._dat.flush()
            self._dat.close()
            self.nm.close()

    def destroy(self) -> None:
        self.close()
        for p in (self.dat_path, self.idx_path):
            if os.path.exists(p):
                os.remove(p)
