"""Volume: one append-only .dat + .idx pair.

Behavioral parity with the reference volume engine
(weed/storage/volume_read_write.go, volume_loading.go,
volume_checking.go): cookie-checked overwrites, tombstone deletes (an
empty needle appended to .dat + a size=-1 .idx entry), TTL expiry on
read, torn-tail truncation at load.

Python is fine here: the hot byte work (CRC) is native, and appends are
single `write` syscalls. Writes go through a per-volume group-commit
worker mirroring the reference's async write path
(volume_read_write.go:331-405): a single writer thread drains up to
128 queued requests / 4MB per batch, stages all appends into one
buffer, issues one write syscall + one flush (+ one fsync if any
request asked for it), then publishes index entries and wakes waiters.
A physical write error truncates the .dat back to the batch start
before failing the batch (truncate-on-error, :385-399).
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Optional

from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.util import wlog
from seaweedfs_tpu.storage.backend import (
    BackendError, BackendStorageFile, DiskFile, RemoteFile, get_backend,
    read_tier_info,
)
from seaweedfs_tpu.storage.needle import (
    Needle, NeedleError, CookieMismatch, actual_size, VERSION3,
    verify_needle_integrity,
)
from seaweedfs_tpu.storage.needle_map import make_needle_map
from seaweedfs_tpu.storage.superblock import SuperBlock, ReplicaPlacement, TTL
from seaweedfs_tpu.storage import idx as idx_codec


_log = wlog.logger("storage.volume")

# SEAWEED_VERIFY_READS=1: read_needle re-verifies the masked CRC of
# every needle it returns through the shared integrity predicate and
# raises the typed DataCorruptionError on mismatch. The record parse
# already CRC-checks `data`; the strict gate additionally covers any
# caller that parses with check_crc=False and keeps the corruption
# surface typed (corrupt != missing). Resolved once at import — the
# read path must not pay an environ lookup per needle; tests flip it
# with set_verify_reads().
_VERIFY_READS = os.environ.get("SEAWEED_VERIFY_READS", "") not in ("", "0")


def set_verify_reads(on: bool) -> None:
    global _VERIFY_READS
    _VERIFY_READS = bool(on)


def verify_reads_enabled() -> bool:
    return _VERIFY_READS


class VolumeError(Exception):
    pass


class _WriteRequest:
    """One queued write/delete riding the group-commit worker."""

    __slots__ = ("kind", "needle", "fsync", "event", "result", "error")

    def __init__(self, kind: str, needle: "Needle", fsync: bool = False):
        self.kind = kind
        self.needle = needle
        self.fsync = fsync
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None

    def complete(self, result=None, error: Optional[BaseException] = None):
        self.result = result
        self.error = error
        self.event.set()

    def wait(self):
        # indefinite, like the reference's channel receive: a timeout
        # here would abandon a request that the worker later commits
        # anyway (ghost write). The worker always completes every
        # request, including on stop().
        self.event.wait()
        if self.error is not None:
            raise self.error
        return self.result


class _GroupCommitWriter:
    """Single writer thread per volume with batched group commit.

    Mirrors the reference's asyncWrite worker
    (weed/storage/volume_read_write.go:331-405): requests queue up while
    a batch is in flight; each drain takes at most MAX_BATCH_REQS
    requests or MAX_BATCH_BYTES of payload, stages every append into one
    contiguous buffer, and commits it with a single write()+flush()
    (+fsync if any request requires it). Index entries are published
    only after the bytes are durably staged, so readers (which take the
    volume lock) never observe an index entry pointing at unwritten
    data. On a physical write error the .dat is truncated back to the
    batch start offset and every request in the batch fails.
    """

    MAX_BATCH_REQS = 128
    MAX_BATCH_BYTES = 4 * 1024 * 1024

    def __init__(self, volume: "Volume"):
        self.volume = volume
        # backlog() peeks lock-free (deque len is GIL-atomic; the
        # worker-routing heuristic tolerates staleness); stop()'s
        # post-join drain runs after the writer thread exited
        self._queue: collections.deque[_WriteRequest] = collections.deque()  # guarded_by(self._cond, writes)
        self._cond = threading.Condition()
        self._stopped = False
        # lint: gate-ok(constructed lazily by _get_writer on the first async write) # lint: thread-ok(group-commit writer; requests rendezvous on futures at the submit seam)
        self._thread = threading.Thread(
            target=self._run, name=f"vol-{volume.id}-writer", daemon=True)
        self._thread.start()

    def backlog(self) -> int:
        return len(self._queue)

    def submit(self, req: _WriteRequest):
        with self._cond:
            if self._stopped:
                raise VolumeError(
                    f"volume {self.volume.id}: writer is stopped")
            self._queue.append(req)
            self._cond.notify()
        return req.wait()

    def stop(self):
        with self._cond:
            self._stopped = True
            self._cond.notify()
        self._thread.join(timeout=10)
        # fail anything still queued
        while self._queue:
            # lint: guard-ok(post-join drain: the writer thread has exited and submit refuses once stopped)
            self._queue.popleft().complete(
                error=VolumeError("volume closed"))

    def _drain(self) -> Optional[list[_WriteRequest]]:
        with self._cond:
            while not self._queue and not self._stopped:
                self._cond.wait()
            if not self._queue:
                return None
            batch, payload = [], 0
            while self._queue and len(batch) < self.MAX_BATCH_REQS and \
                    payload < self.MAX_BATCH_BYTES:
                req = self._queue.popleft()
                batch.append(req)
                payload += len(req.needle.data)
            return batch

    def _run(self):
        while True:
            batch = self._drain()
            if batch is None:
                return
            try:
                self.volume._apply_batch(batch)
            except BaseException as e:  # never kill the worker thread
                for req in batch:
                    if not req.event.is_set():
                        req.complete(error=e)


class Volume:
    def __init__(self, dirname: str, collection: str, vid: int,
                 replica_placement: ReplicaPlacement = ReplicaPlacement(),
                 ttl: TTL = TTL.empty(),
                 create_if_missing: bool = True,
                 async_write: bool = True,
                 needle_map_kind: str = "memory"):
        self.dir = dirname
        self.collection = collection
        self.id = vid
        self.needle_map_kind = needle_map_kind
        self.version = VERSION3
        self.read_only = False
        self.last_append_at_ns = 0
        self.last_modified_ts = 0
        self._lock = threading.RLock()
        self.async_write = async_write
        # _use_worker's routing peek is lock-free (a stale writer only
        # mis-routes one request to the inline path, which is valid)
        self._writer: Optional[_GroupCommitWriter] = None  # guarded_by(self._writer_lock, writes)
        self._writer_lock = threading.Lock()
        base = self.file_name()
        self.dat_path = base + ".dat"
        self.idx_path = base + ".idx"
        existing = os.path.exists(self.dat_path) or \
            read_tier_info(base) is not None
        if not existing and not create_if_missing:
            raise VolumeError(f"volume file {self.dat_path} missing")
        if existing:
            self._load()
            if replica_placement != ReplicaPlacement() and \
                    replica_placement != self.super_block.replica_placement:
                # keep what's on disk; caller sees the difference via attrs
                pass
        else:
            self.super_block = SuperBlock(
                version=VERSION3, replica_placement=replica_placement, ttl=ttl)
            self._dat: BackendStorageFile = DiskFile(self.dat_path,
                                                     create=True)
            self._dat.write_at(self.super_block.to_bytes(), 0)
            self.nm = make_needle_map(self.idx_path, self.needle_map_kind)

    # -- naming --------------------------------------------------------------

    def file_name(self) -> str:
        name = f"{self.collection}_{self.id}" if self.collection else str(self.id)
        return os.path.join(self.dir, name)

    @property
    def ttl(self) -> TTL:
        return self.super_block.ttl

    @property
    def replica_placement(self) -> ReplicaPlacement:
        return self.super_block.replica_placement

    # -- loading / integrity -------------------------------------------------

    def _load(self) -> None:
        from seaweedfs_tpu.storage.vacuum import recover_compaction
        recover_compaction(self.file_name())
        tier = read_tier_info(self.file_name())
        if tier is not None and not os.path.exists(self.dat_path):
            # cloud-tiered: the .dat lives in an object store; reads go
            # through ranged GETs, the volume is sealed read-only
            # (reference volume_tier.go LoadRemoteFile)
            self._dat = RemoteFile(get_backend(tier["backend"]),
                                   tier["key"], tier["size"])
            self.read_only = True
        else:
            self._dat = DiskFile(self.dat_path)
            if tier is not None:
                # tiered with keep_local: serve reads from the faster
                # local copy but stay sealed — new writes would silently
                # diverge from the remote object the .tier file points at
                self.read_only = True
        header = self._dat.read_at(8, 0)
        if len(header) < 8:
            raise VolumeError(f"{self.dat_path}: truncated superblock")
        self.super_block = SuperBlock.from_bytes(header)
        self.version = self.super_block.version
        self.nm = make_needle_map(self.idx_path, self.needle_map_kind)
        if not self._dat.is_remote:
            self._check_and_fix_integrity()
        self._restore_last_append_ns()

    def _restore_last_append_ns(self) -> None:
        """Recover the newest record's appendAtNs from the last .idx
        entry (the reference reads lastAppendAtNs at load too) — the
        quiet-period guard in ec.encode and incremental backup both
        depend on it surviving a restart."""
        import struct
        if not os.path.exists(self.idx_path):
            return
        size = os.path.getsize(self.idx_path)
        n_entries = size // t.NEEDLE_MAP_ENTRY_SIZE
        if n_entries == 0:
            return
        with open(self.idx_path, "rb") as f:
            f.seek((n_entries - 1) * t.NEEDLE_MAP_ENTRY_SIZE)
            entry = f.read(t.NEEDLE_MAP_ENTRY_SIZE)
        _, offset, _ = idx_codec.parse_entry(entry)
        header = self._dat.read_at(t.NEEDLE_HEADER_SIZE, offset)
        if len(header) < t.NEEDLE_HEADER_SIZE:
            return
        _, _, size_u = struct.unpack(">IQI", header)
        body = t.size_to_int32(size_u)
        if t.size_is_deleted(body):
            body = 0
        ts_off = offset + t.NEEDLE_HEADER_SIZE + body + \
            t.NEEDLE_CHECKSUM_SIZE
        blob = self._dat.read_at(8, ts_off)
        if len(blob) == 8:
            self.last_append_at_ns = struct.unpack(">Q", blob)[0]
            self.last_modified_ts = self.last_append_at_ns // 1_000_000_000

    def _check_and_fix_integrity(self) -> None:
        """Truncate a torn tail: the .dat must end exactly after the last
        needle recorded in the .idx (reference volume_checking.go:16-66).

        An absent/empty .idx means nothing is known about the volume —
        like the reference, do NOT truncate in that case (the .idx may
        simply be lost; `weed fix` / Volume.rebuild_index recovers it).
        """
        dat_size = self._dat.size()
        idx_size = os.path.getsize(self.idx_path) \
            if os.path.exists(self.idx_path) else 0
        if idx_size == 0:
            return
        with open(self.idx_path, "rb") as f:
            arr = idx_codec.parse_index_bytes(f.read())
        if not len(arr):
            return
        import numpy as np
        sizes = arr["size"].astype(np.int64)
        body = np.where(sizes < 0, 0, sizes)
        ends = arr["offset"] + [actual_size(int(s), self.version) for s in body]
        expected = int(max(ends.max(), 8))
        if dat_size > expected:
            self._dat.truncate(expected)
        elif dat_size < expected:
            raise VolumeError(
                f"{self.dat_path}: data file shorter ({dat_size}) than the "
                f"index implies ({expected})")

    # -- write path ----------------------------------------------------------

    def write_needle(self, n: Needle, fsync: bool = False) -> tuple[int, int]:
        """Append a needle; returns (offset, size). Cookie-checked overwrite.

        Routing is adaptive: fsync'd writes (and anything arriving while
        the worker has a backlog) ride the group-commit worker so many
        requests share one fsync; uncontended non-durable writes take
        the direct locked path, which is cheaper than a thread handoff.
        Either way the call blocks until the bytes are committed, so
        callers observe synchronous semantics.
        """
        if len(n.data) == 0:
            raise VolumeError(
                "zero-byte writes are not storable (indistinguishable from "
                "a delete marker); reject at the write path")
        req = _WriteRequest("write", n, fsync)
        if self._use_worker(fsync):
            return self._get_writer().submit(req)
        self._apply_batch([req])
        return req.wait()

    def delete_needle(self, n: Needle) -> int:
        """Tombstone a needle; returns freed size (0 if absent)."""
        req = _WriteRequest("delete", n)
        if self._use_worker(False):
            return self._get_writer().submit(req)
        self._apply_batch([req])
        return req.wait()

    def _use_worker(self, fsync: bool) -> bool:
        if not self.async_write:
            return False
        if fsync:
            return True
        w = self._writer
        return w is not None and w.backlog() > 0

    def _get_writer(self) -> _GroupCommitWriter:
        with self._writer_lock:
            if self._writer is None:
                self._writer = _GroupCommitWriter(self)
            return self._writer

    def _lookup_for_batch(self, key: int, pending: dict):
        """Intra-batch index view: staged-but-unpublished entries first,
        then the real needle map. Returns (offset, size) or None."""
        if key in pending:
            return pending[key]
        nv = self.nm.get(key)
        if nv is None or not t.size_is_valid(nv.size):
            return None
        return (nv.offset, nv.size)

    def _read_old_needle(self, offset: int, size: int, batch_start: int,
                         buf: bytearray) -> Needle:
        """Read a pre-existing needle for a cookie check. If it was
        staged earlier in the same batch it lives in `buf`, not on disk."""
        if offset >= batch_start:
            start = offset - batch_start
            blob = bytes(buf[start:start + actual_size(size, self.version)])
            return Needle.from_bytes(blob, self.version, check_crc=False)
        return self._read_needle_at(offset, size, check_crc=False)

    def _apply_batch(self, batch: list[_WriteRequest]) -> None:
        """Commit a batch of write/delete requests with one physical
        append. See _GroupCommitWriter for the protocol."""
        with self._lock:
            batch_start = self._dat.size()
            buf = bytearray()
            staged = []  # (req, publish_fn, result)
            pending: dict[int, Optional[tuple[int, int]]] = {}
            any_fsync = False
            for req in batch:
                try:
                    if self.read_only:
                        raise VolumeError(f"volume {self.id} is read-only")
                    if req.kind == "write":
                        staged.append(self._stage_write(
                            req, batch_start, buf, pending))
                        any_fsync = any_fsync or req.fsync
                    else:
                        item = self._stage_delete(
                            req, batch_start, buf, pending)
                        if item is None:
                            req.complete(result=0)
                        else:
                            staged.append(item)
                except BaseException as e:
                    req.complete(error=e)
            if buf:
                try:
                    self._dat.write_at(buf, batch_start)
                    if any_fsync:
                        self._dat.sync()
                except (OSError, BackendError) as e:
                    # truncate-on-error: roll the .dat back to the batch
                    # start so no index entry ever points at torn bytes
                    # (reference volume_read_write.go:385-399)
                    try:
                        self._dat.truncate(batch_start)
                    except OSError:
                        pass
                    err = VolumeError(
                        f"volume {self.id}: batch write failed: {e}")
                    for req, _, _ in staged:
                        req.complete(error=err)
                    return
            for req, publish, result in staged:
                try:
                    publish()
                except OSError as e:
                    req.complete(error=VolumeError(
                        f"volume {self.id}: index publish failed: {e}"))
                    continue
                req.complete(result=result)
            try:
                # .idx entries are buffered; flush once per batch. A
                # failure here (e.g. ENOSPC) leaves the bytes buffered —
                # the in-memory map is consistent and a later flush or
                # sync() retries, so acked writes stay readable.
                self.nm.flush()
            except OSError as e:
                _log.warning("volume %d: idx flush failed (will retry "
                             "on next batch/sync): %s", self.id, e)

    def _stage_write(self, req: _WriteRequest, batch_start: int,
                     buf: bytearray, pending: dict):
        n = req.needle
        if n.ttl is None or n.ttl.is_empty:
            if not self.ttl.is_empty:
                n.ttl = self.ttl
        existing = self._lookup_for_batch(n.id, pending)
        if existing is not None:
            old = self._read_old_needle(existing[0], existing[1],
                                        batch_start, buf)
            if old.cookie != n.cookie:
                raise CookieMismatch(
                    f"needle {n.id:x}: cookie mismatch {n.cookie:08x}")
        n.append_at_ns = time.time_ns()
        blob = n.to_bytes(self.version)
        offset = self._stage_blob(batch_start, buf, blob)
        pending[n.id] = (offset, n.size)

        def publish(n=n, offset=offset):
            self.nm.put(n.id, offset, n.size)
            if n.append_at_ns > self.last_append_at_ns:
                self.last_append_at_ns = n.append_at_ns
            if n.last_modified > self.last_modified_ts:
                self.last_modified_ts = n.last_modified

        return req, publish, (offset, n.size)

    def _stage_delete(self, req: _WriteRequest, batch_start: int,
                      buf: bytearray, pending: dict):
        n = req.needle
        existing = self._lookup_for_batch(n.id, pending)
        if existing is None:
            return None
        if n.cookie:
            old = self._read_old_needle(existing[0], existing[1],
                                        batch_start, buf)
            if old.cookie != n.cookie:
                raise CookieMismatch(
                    f"needle {n.id:x}: delete cookie mismatch")
        freed = existing[1]
        marker = Needle(id=n.id, cookie=n.cookie, data=b"")
        marker.append_at_ns = time.time_ns()
        blob = marker.to_bytes(self.version)
        offset = self._stage_blob(batch_start, buf, blob)
        pending[n.id] = None

        def publish(marker=marker, offset=offset):
            self.nm.delete(marker.id, offset)
            if marker.append_at_ns > self.last_append_at_ns:
                self.last_append_at_ns = marker.append_at_ns

        return req, publish, freed

    def _stage_blob(self, batch_start: int, buf: bytearray,
                    blob: bytes) -> int:
        tail = batch_start + len(buf)
        if tail % t.NEEDLE_PADDING != 0:
            pad = t.NEEDLE_PADDING - tail % t.NEEDLE_PADDING
            buf += b"\x00" * pad
            tail += pad
        if tail + len(blob) > t.MAX_POSSIBLE_VOLUME_SIZE:
            raise VolumeError(f"volume {self.id} exceeds max size")
        buf += blob
        return tail

    # -- read path -----------------------------------------------------------

    def read_needle(self, n: Needle) -> Needle:
        """Fill a needle by id; raises NeedleError if absent/expired,
        CookieMismatch if the cookie doesn't match."""
        with self._lock:
            nv = self.nm.get(n.id)
            if nv is None or not t.size_is_valid(nv.size):
                raise NeedleError(f"needle {n.id:x} not found")
            got = self._read_needle_at(nv.offset, nv.size)
        if n.cookie and got.cookie != n.cookie:
            raise CookieMismatch(
                f"needle {n.id:x}: cookie {n.cookie:08x} != {got.cookie:08x}")
        if got.has_expired():
            raise NeedleError(f"needle {n.id:x} expired")
        if _VERIFY_READS:
            verify_needle_integrity(got)
        return got

    def read_needle_span(self, n: Needle):
        """Zero-copy read: needle metadata from two small preads, the
        payload left on disk. Returns (needle, FileSpan) — the needle
        carries cookie/flags/name/mime/checksum/ttl but EMPTY data;
        the span (a dup'd fd + payload offset/length) is the caller's
        to sendfile and close. Returns None when this volume cannot
        serve spans (remote/cloud-tiered .dat, or SEAWEED_VERIFY_READS
        demands a payload CRC check — zero-copy by definition never
        reads the payload, so the strict gate routes callers back to
        read_needle). Raises the same NeedleError/CookieMismatch
        family as read_needle. Integrity note: this path trades
        read-time CRC verification for the copy-free send; the scrub
        subsystem owns at-rest integrity."""
        from seaweedfs_tpu.util.http_server import FileSpan
        if _VERIFY_READS:
            return None
        with self._lock:
            dat = self._dat
            if dat is None or dat.is_remote or \
                    not isinstance(dat, DiskFile):
                return None
            nv = self.nm.get(n.id)
            if nv is None or not t.size_is_valid(nv.size):
                raise NeedleError(f"needle {n.id:x} not found")
            offset = nv.offset
            hdr = dat.read_at(t.NEEDLE_HEADER_SIZE + 4, offset)
            if len(hdr) < t.NEEDLE_HEADER_SIZE:
                raise NeedleError(
                    f"short read at {offset}: {len(hdr)} < "
                    f"{t.NEEDLE_HEADER_SIZE}")
            size = t.size_to_int32(
                int.from_bytes(hdr[12:16], "big"))
            if size > 0:
                if len(hdr) < t.NEEDLE_HEADER_SIZE + 4:
                    raise NeedleError(
                        f"short read at {offset}: {len(hdr)} < "
                        f"{t.NEEDLE_HEADER_SIZE + 4}")
                data_size = int.from_bytes(hdr[16:20], "big")
                data_off = offset + t.NEEDLE_HEADER_SIZE + 4
            else:
                data_size = 0
                data_off = offset + t.NEEDLE_HEADER_SIZE
            meta_off = data_off + data_size
            # attrs + checksum (+ts on v3); the padding tail is
            # irrelevant to the parse
            meta_len = (size - 4 - data_size if size > 0 else 0) + \
                4 + (t.TIMESTAMP_SIZE if self.version == VERSION3
                     else 0)
            meta = dat.read_at(meta_len, meta_off)
            if len(meta) < meta_len:
                raise NeedleError(
                    f"short read at {meta_off}: {len(meta)} < "
                    f"{meta_len}")
            got = Needle.from_disk_meta(hdr, meta, data_size,
                                        self.version)
            span_fd = os.dup(dat.fileno())
        span = FileSpan(span_fd, data_off, data_size)
        try:
            if n.cookie and got.cookie != n.cookie:
                raise CookieMismatch(
                    f"needle {n.id:x}: cookie {n.cookie:08x} != "
                    f"{got.cookie:08x}")
            if got.has_expired():
                raise NeedleError(f"needle {n.id:x} expired")
        except NeedleError:
            span.close()
            raise
        return got, span

    def _read_needle_at(self, offset: int, size: int,
                        check_crc: bool = True) -> Needle:
        length = actual_size(size, self.version)
        blob = self._dat.read_at(length, offset)
        if len(blob) < length:
            raise NeedleError(
                f"short read at {offset}: {len(blob)} < {length}")
        return Needle.from_bytes(blob, self.version, check_crc=check_crc)

    # -- scanning (vacuum / ec / export) -------------------------------------

    def scan_needles(self, include_deleted: bool = False):
        """Yield (offset, Needle) for every record in the .dat, in order.

        Opens its own read-only fd so a long-running scan (vacuum, EC
        encode, export) never races reads/writes on the shared handle.
        """
        import struct
        if self._dat.is_remote:
            raise VolumeError(
                f"volume {self.id} is cloud-tiered; download it first "
                "(VolumeTierMoveDatFromRemote) before scanning")
        size = os.path.getsize(self.dat_path)
        offset = 8
        with open(self.dat_path, "rb") as f:
            while offset + t.NEEDLE_HEADER_SIZE <= size:
                f.seek(offset)
                header = f.read(t.NEEDLE_HEADER_SIZE)
                if len(header) < t.NEEDLE_HEADER_SIZE:
                    break
                cookie, nid, size_u = struct.unpack(">IQI", header)
                body_size = t.size_to_int32(size_u)
                if t.size_is_deleted(body_size):
                    body_size = 0
                length = actual_size(body_size, self.version)
                f.seek(offset)
                blob = f.read(length)
                if len(blob) < length:
                    break
                try:
                    n = Needle.from_bytes(blob, self.version, check_crc=False)
                    is_marker = len(n.data) == 0
                    if include_deleted or not is_marker:
                        yield offset, n
                except (NeedleError, struct.error, IndexError, ValueError):
                    # a garbled record must not abort the scan: torn
                    # size fields die in struct.unpack/_parse_body, not
                    # just as clean NeedleErrors — skip it like one
                    pass
                offset += length

    # -- stats / lifecycle ---------------------------------------------------

    def configure_replication(self, rp: ReplicaPlacement) -> None:
        """Rewrite the superblock's replica-placement byte in place
        (reference store.go:431 ConfigureVolume → super_block byte 1).
        Remote (cloud-tiered) volumes are sealed; their superblock lives
        in the object store and is not rewritten."""
        with self._lock:
            if self._dat.is_remote:
                raise VolumeError(
                    f"volume {self.id} is cloud-tiered; download it first")
            self.super_block = SuperBlock(
                version=self.super_block.version,
                replica_placement=rp,
                ttl=self.super_block.ttl,
                compaction_revision=self.super_block.compaction_revision)
            self._dat.write_at(self.super_block.to_bytes(), 0)
            self._dat.sync()

    @property
    def content_size(self) -> int:
        return self._dat.size()

    @property
    def is_remote(self) -> bool:
        return self._dat.is_remote

    @property
    def file_count(self) -> int:
        return len(self.nm)

    @property
    def deleted_count(self) -> int:
        return self.nm.deleted_count

    @property
    def deleted_size(self) -> int:
        return self.nm.deleted_size

    def garbage_ratio(self) -> float:
        cs = self.content_size
        return (self.nm.deleted_size / cs) if cs > 8 else 0.0

    def is_full(self, volume_size_limit: int) -> bool:
        return self.content_size >= volume_size_limit

    def sync(self) -> None:
        self._dat.sync()
        self.nm.sync()

    def close(self) -> None:
        with self._writer_lock:
            writer, self._writer = self._writer, None
        if writer is not None:
            writer.stop()
        with self._lock:
            self._dat.close()
            self.nm.close()

    def destroy(self) -> None:
        from seaweedfs_tpu.storage.backend import tier_info_path
        self.close()
        self.nm.destroy()  # removes .idx (and the .nmkv dir for kv kind)
        for p in (self.dat_path, tier_info_path(self.file_name())):
            if os.path.exists(p):
                os.remove(p)
