"""Volume superblock, replica placement grammar, and TTL encoding.

Reference formats: weed/storage/super_block/super_block.go:12-38 (8-byte
header), replica_placement.go:8-31 ("xyz" = DC/rack/server extra copies),
weed/storage/needle/volume_ttl.go (2-byte count+unit TTL).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

SUPER_BLOCK_SIZE = 8

_TTL_UNITS = {0: "", 1: "m", 2: "h", 3: "d", 4: "w", 5: "M", 6: "y"}
_TTL_UNIT_CODES = {v: k for k, v in _TTL_UNITS.items() if v}
_TTL_MINUTES = {0: 0, 1: 1, 2: 60, 3: 60 * 24, 4: 60 * 24 * 7,
                5: 60 * 24 * 30, 6: 60 * 24 * 365}


@dataclass(frozen=True)
class TTL:
    count: int = 0
    unit: int = 0

    @classmethod
    def empty(cls) -> "TTL":
        return cls(0, 0)

    @property
    def is_empty(self) -> bool:
        return self.count == 0 or self.unit == 0

    @property
    def minutes(self) -> int:
        return self.count * _TTL_MINUTES.get(self.unit, 0)

    def to_bytes(self) -> bytes:
        return bytes([self.count & 0xFF, self.unit & 0xFF])

    @classmethod
    def from_bytes(cls, b: bytes) -> "TTL":
        if len(b) < 2 or b[0] == 0:
            return cls.empty()
        return cls(b[0], b[1])

    @classmethod
    def parse(cls, s: str) -> "TTL":
        """"3m", "4h", "5d", "6w", "7M", "8y" — empty string = no TTL."""
        if not s:
            return cls.empty()
        unit = _TTL_UNIT_CODES.get(s[-1])
        if unit is None:
            raise ValueError(f"bad ttl unit in {s!r}")
        count = int(s[:-1])
        if not 0 <= count <= 255:
            raise ValueError(f"ttl count {count} out of range")
        return cls(count, unit)

    def __str__(self) -> str:
        if self.is_empty:
            return ""
        return f"{self.count}{_TTL_UNITS[self.unit]}"


@dataclass(frozen=True)
class ReplicaPlacement:
    """"xyz": x extra copies in other DCs, y in other racks, z on other
    servers in the same rack. Total copies = x+y+z+1."""

    diff_dc: int = 0
    diff_rack: int = 0
    same_rack: int = 0

    @classmethod
    def parse(cls, s: str) -> "ReplicaPlacement":
        if len(s) != 3 or not s.isdigit():
            raise ValueError(f"bad replica placement {s!r}")
        x, y, z = (int(c) for c in s)
        if max(x, y, z) > 2:
            raise ValueError(f"replica placement digits must be <= 2: {s!r}")
        return cls(diff_dc=x, diff_rack=y, same_rack=z)

    @classmethod
    def from_byte(cls, b: int) -> "ReplicaPlacement":
        return cls.parse(f"{b:03d}")

    def to_byte(self) -> int:
        return self.diff_dc * 100 + self.diff_rack * 10 + self.same_rack

    @property
    def copy_count(self) -> int:
        return self.diff_dc + self.diff_rack + self.same_rack + 1

    def __str__(self) -> str:
        return f"{self.diff_dc}{self.diff_rack}{self.same_rack}"


@dataclass
class SuperBlock:
    version: int = 3
    replica_placement: ReplicaPlacement = ReplicaPlacement()
    ttl: TTL = TTL.empty()
    compaction_revision: int = 0

    def to_bytes(self) -> bytes:
        b = bytearray(SUPER_BLOCK_SIZE)
        b[0] = self.version
        b[1] = self.replica_placement.to_byte()
        b[2:4] = self.ttl.to_bytes()
        struct.pack_into(">H", b, 4, self.compaction_revision)
        return bytes(b)

    @classmethod
    def from_bytes(cls, b: bytes) -> "SuperBlock":
        if len(b) < SUPER_BLOCK_SIZE:
            raise ValueError("superblock too short")
        return cls(
            version=b[0],
            replica_placement=ReplicaPlacement.from_byte(b[1]),
            ttl=TTL.from_bytes(b[2:4]),
            compaction_revision=struct.unpack_from(">H", b, 4)[0],
        )
