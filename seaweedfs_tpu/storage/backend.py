"""Backend storage abstraction: where a volume's .dat bytes live.

Mirrors the reference SPI (weed/storage/backend/backend.go:15-74):

- ``BackendStorageFile`` — positional-IO handle for one volume data
  file (ReadAt/WriteAt/Truncate/Sync/GetStat).  ``DiskFile`` is the
  local implementation (os.pread/os.pwrite — thread-safe, no shared
  seek pointer); ``RemoteFile`` serves reads for a cloud-tiered volume
  straight from an object store (reference
  backend/s3_backend/s3_sessions.go + s3_backend.go ranged reads).
- ``BackendStorage`` — one configured object-store target that sealed
  volume files can be moved to (reference ``BackendStorage`` interface:
  CopyFile/DownloadFile/DeleteFile).  Instances are registered under
  ``scheme.id`` names exactly like the reference's
  ``[storage.backend.s3.default]`` master config sections
  (backend.go:48-74).

The in-process ``MemoryBackendStorage`` stands in for S3 in tests; the
S3-compatible implementation lives in storage/backend_s3.py.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Callable, Dict, Optional

from seaweedfs_tpu.resilience import failpoint as _failpoint


class BackendError(Exception):
    pass


# ---------------------------------------------------------------------------
# BackendStorageFile: positional IO on one volume data file
# ---------------------------------------------------------------------------


class BackendStorageFile:
    """Positional-IO interface over a volume's data bytes
    (reference backend/backend.go:15-23)."""

    def read_at(self, size: int, offset: int) -> bytes:
        raise NotImplementedError

    def write_at(self, data, offset: int) -> int:
        raise NotImplementedError

    def truncate(self, size: int) -> None:
        raise NotImplementedError

    def sync(self) -> None:
        raise NotImplementedError

    def size(self) -> int:
        raise NotImplementedError

    def name(self) -> str:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError

    @property
    def is_remote(self) -> bool:
        return False


class DiskFile(BackendStorageFile):
    """Local file via pread/pwrite — no shared seek pointer, so readers
    never race the writer for the fd position (the reference gets this
    from Go's ReadAt/WriteAt contracts)."""

    def __init__(self, path: str, create: bool = False):
        flags = os.O_RDWR | (os.O_CREAT if create else 0)
        self._fd = os.open(path, flags)
        self._path = path
        # size() reads lock-free (int load is atomic; a concurrent
        # extension may be invisible for one call, same as stat racing
        # a write); extensions/truncates serialize on the lock
        self._size = os.fstat(self._fd).st_size  # guarded_by(self._size_lock, writes)
        self._size_lock = threading.Lock()

    def read_at(self, size: int, offset: int) -> bytes:
        return os.pread(self._fd, size, offset)

    def write_at(self, data, offset: int) -> int:
        # pwrite may return a short count (e.g. ENOSPC mid-write); loop
        # so callers get all-or-exception — the volume's
        # truncate-on-error path depends on partial writes raising
        if _failpoint._armed:
            # injected torn write (short), bit flip (corrupt), EIO
            # (error) or stall (delay) — the scrub/crash tests' way of
            # making disk failure modes happen on demand
            data = _failpoint.mangle("backend.write_at", data,
                                     path=self._path)
        view = memoryview(bytes(data) if not isinstance(
            data, (bytes, bytearray, memoryview)) else data)
        total = len(view)
        written = 0
        while written < total:
            n = os.pwrite(self._fd, view[written:], offset + written)
            if n <= 0:
                raise OSError(
                    f"pwrite returned {n} at {offset + written} "
                    f"({self._path})")
            written += n
            with self._size_lock:
                if offset + written > self._size:
                    self._size = offset + written
        return written

    def truncate(self, size: int) -> None:
        os.ftruncate(self._fd, size)
        with self._size_lock:
            self._size = size

    def sync(self) -> None:
        os.fsync(self._fd)

    def size(self) -> int:
        return self._size

    def name(self) -> str:
        return self._path

    def fileno(self) -> int:
        """Raw fd for zero-copy consumers (the volume read path dup()s
        it into a FileSpan so a concurrent close/compact-swap can't
        invalidate an in-flight sendfile)."""
        return self._fd

    def close(self) -> None:
        if self._fd >= 0:
            os.close(self._fd)
            self._fd = -1


class RemoteFile(BackendStorageFile):
    """Read-only view of a cloud-tiered volume .dat: every read_at is a
    ranged GET against the owning BackendStorage (reference
    s3_backend.go ReadAt). Writes are rejected — tiered volumes are
    sealed."""

    def __init__(self, backend: "BackendStorage", key: str, size: int):
        self.backend = backend
        self.key = key
        self._size = size

    def read_at(self, size: int, offset: int) -> bytes:
        return self.backend.read_range(self.key, offset, size)

    def write_at(self, data, offset: int) -> int:
        raise BackendError(f"{self.name()}: tiered volume is read-only")

    def truncate(self, size: int) -> None:
        raise BackendError(f"{self.name()}: tiered volume is read-only")

    def sync(self) -> None:
        pass

    def size(self) -> int:
        return self._size

    def name(self) -> str:
        return f"{self.backend.name}:{self.key}"

    def close(self) -> None:
        pass

    @property
    def is_remote(self) -> bool:
        return True


# ---------------------------------------------------------------------------
# BackendStorage: a configured object-store target
# ---------------------------------------------------------------------------


class BackendStorage:
    """One object-store target for sealed volume files
    (reference backend/backend.go:32-46)."""

    name: str = ""

    def copy_file(self, local_path: str, key: str,
                  progress: Optional[Callable[[int], None]] = None) -> int:
        """Upload local_path under key; returns total bytes."""
        raise NotImplementedError

    def download_file(self, key: str, local_path: str,
                      progress: Optional[Callable[[int], None]] = None) -> int:
        """Download key to local_path; returns total bytes."""
        raise NotImplementedError

    def read_range(self, key: str, offset: int, length: int) -> bytes:
        raise NotImplementedError

    def delete_file(self, key: str) -> None:
        raise NotImplementedError


class MemoryBackendStorage(BackendStorage):
    """In-process object store — the test stand-in for S3 (keeps tier
    and backup tests hermetic; the real S3 backend shares the SPI)."""

    def __init__(self, name: str = "memory.default"):
        self.name = name
        self._objects: Dict[str, bytes] = {}
        self._lock = threading.Lock()

    def copy_file(self, local_path, key, progress=None):
        with open(local_path, "rb") as f:
            data = f.read()
        with self._lock:
            self._objects[key] = data
        if progress:
            progress(len(data))
        return len(data)

    def download_file(self, key, local_path, progress=None):
        with self._lock:
            data = self._objects.get(key)
        if data is None:
            raise BackendError(f"{self.name}: no object {key}")
        with open(local_path, "wb") as f:
            f.write(data)
        if progress:
            progress(len(data))
        return len(data)

    def read_range(self, key, offset, length):
        with self._lock:
            data = self._objects.get(key)
        if data is None:
            raise BackendError(f"{self.name}: no object {key}")
        return data[offset:offset + length]

    def delete_file(self, key):
        with self._lock:
            self._objects.pop(key, None)

    def object_size(self, key) -> Optional[int]:
        with self._lock:
            data = self._objects.get(key)
        return None if data is None else len(data)


# ---------------------------------------------------------------------------
# Registry (reference backend.go:48-74 LoadConfiguration / factory map)
# ---------------------------------------------------------------------------

_factories: Dict[str, Callable[[str, dict], BackendStorage]] = {}
_backends: Dict[str, BackendStorage] = {}
_registry_lock = threading.Lock()


def register_backend_factory(scheme: str,
                             factory: Callable[[str, dict], BackendStorage]):
    _factories[scheme] = factory


def load_configuration(conf: dict) -> None:
    """conf maps backend name -> properties, e.g.
    ``{"s3.default": {"endpoint": ..., "bucket": ...},
       "memory.test": {}}``; the scheme is the name up to the first dot
    (reference master.toml [storage.backend.<scheme>.<id>])."""
    for name, props in (conf or {}).items():
        scheme = name.split(".", 1)[0]
        factory = _factories.get(scheme)
        if factory is None:
            raise BackendError(f"unknown storage backend scheme {scheme!r}")
        register_backend(factory(name, props or {}))


def register_backend(backend: BackendStorage) -> BackendStorage:
    with _registry_lock:
        _backends[backend.name] = backend
    return backend


def get_backend(name: str) -> BackendStorage:
    with _registry_lock:
        b = _backends.get(name)
    if b is None:
        raise BackendError(f"storage backend {name!r} is not configured")
    return b


def clear_backends() -> None:
    """Test hook."""
    with _registry_lock:
        _backends.clear()


def _memory_factory(name: str, props: dict) -> BackendStorage:
    return MemoryBackendStorage(name)


register_backend_factory("memory", _memory_factory)

# the "s3" scheme registers itself on import (kept in its own module so
# this one stays dependency-light)
from seaweedfs_tpu.storage import backend_s3  # noqa: E402,F401  # lint: dead-ok(side-effect import registers the s3 backend)


# ---------------------------------------------------------------------------
# Tier metadata file (<base>.tier): which backend holds the .dat
# (the reference records this in the .vif volume-info protobuf)
# ---------------------------------------------------------------------------


def tier_info_path(base_name: str) -> str:
    return base_name + ".tier"


def write_tier_info(base_name: str, backend_name: str, key: str,
                    size: int) -> None:
    info = {"backend": backend_name, "key": key, "size": size}
    tmp = tier_info_path(base_name) + ".tmp"
    with open(tmp, "w") as f:
        json.dump(info, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, tier_info_path(base_name))


def read_tier_info(base_name: str) -> Optional[dict]:
    p = tier_info_path(base_name)
    if not os.path.exists(p):
        return None
    with open(p) as f:
        return json.load(f)


def remove_tier_info(base_name: str) -> None:
    p = tier_info_path(base_name)
    if os.path.exists(p):
        os.remove(p)


# ---------------------------------------------------------------------------
# EC tier metadata file (<base>.ectier): which backend holds this
# server's .ecNN shard files — the erasure-coded sibling of the .tier
# sidecar above. `shards` maps shard id -> {key, size}; the .ecx/.ecj
# index always stays local (like the .idx on a tiered .dat), so needle
# lookups keep their speed and only bulk shard reads pay the remote
# round trip.
# ---------------------------------------------------------------------------


def ec_tier_info_path(base_name: str) -> str:
    return base_name + ".ectier"


def write_ec_tier_info(base_name: str, backend_name: str,
                       shards: dict) -> None:
    info = {"backend": backend_name,
            "shards": {str(sid): rec for sid, rec in shards.items()}}
    tmp = ec_tier_info_path(base_name) + ".tmp"
    with open(tmp, "w") as f:
        json.dump(info, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, ec_tier_info_path(base_name))


def read_ec_tier_info(base_name: str) -> Optional[dict]:
    p = ec_tier_info_path(base_name)
    if not os.path.exists(p):
        return None
    with open(p) as f:
        info = json.load(f)
    info["shards"] = {int(sid): rec
                      for sid, rec in info.get("shards", {}).items()}
    return info


def remove_ec_tier_info(base_name: str) -> None:
    p = ec_tier_info_path(base_name)
    if os.path.exists(p):
        os.remove(p)
