""".idx file codec: an append log of (key, offset, size) entries.

Reference: weed/storage/idx/walk.go:12-50. Entries are big-endian:
key(8) offset(OFFSET_SIZE, unit of 8 bytes) size(4, int32 semantics) —
16 bytes in the default build, 17 with the 5-byte-offset variant
(types.py SEAWEEDFS_TPU_5BYTE_OFFSET). A tombstone is size == -1
(0xFFFFFFFF); its offset points at the delete marker appended to
the .dat file.

Parsing is vectorized with numpy (a 1M-entry .idx parses in ~10ms), which
replaces the reference's streaming Go loop.
"""

from __future__ import annotations

import struct
from typing import Callable, Iterator, Tuple

import numpy as np

from seaweedfs_tpu.storage import types as t

_KEY = struct.Struct(">Q")
_SIZE = struct.Struct(">I")


def entry_to_bytes(key: int, actual_offset: int, size: int) -> bytes:
    return _KEY.pack(key) + \
        t.offset_units_to_bytes(actual_offset // t.NEEDLE_PADDING) + \
        _SIZE.pack(size & 0xFFFFFFFF)


def parse_entry(b: bytes) -> Tuple[int, int, int]:
    key = _KEY.unpack(b[:8])[0]
    off_u = t.bytes_to_offset_units(b[8:8 + t.OFFSET_SIZE])
    size_u = _SIZE.unpack(b[8 + t.OFFSET_SIZE:
                            8 + t.OFFSET_SIZE + 4])[0]
    return key, off_u * t.NEEDLE_PADDING, t.size_to_int32(size_u)


def parse_index_bytes(buf: bytes) -> np.ndarray:
    """Parse a whole .idx blob into a structured array.

    Returns a record array with fields key(u8), offset(i8, actual bytes),
    size(i4). Truncates any torn trailing partial entry.
    """
    es = t.NEEDLE_MAP_ENTRY_SIZE
    usable = len(buf) - (len(buf) % es)
    raw = np.frombuffer(buf[:usable], dtype=np.uint8).reshape(-1, es)
    keys = raw[:, :8].copy().view(">u8").reshape(-1)
    offsets = raw[:, 8:12].copy().view(">u4").reshape(-1).astype(np.int64)
    if t.OFFSET_SIZE == 5:
        # 5th byte carries bits 32..39 (reference offset_5bytes.go)
        offsets |= raw[:, 12].astype(np.int64) << 32
    offsets *= t.NEEDLE_PADDING
    so = 8 + t.OFFSET_SIZE
    sizes = raw[:, so:so + 4].copy().view(">u4").reshape(-1).astype(np.int64)
    sizes = np.where(sizes >= (1 << 31), sizes - (1 << 32), sizes).astype(np.int32)
    out = np.zeros(len(keys), dtype=[("key", np.uint64), ("offset", np.int64),
                                     ("size", np.int32)])
    out["key"] = keys.astype(np.uint64)
    out["offset"] = offsets
    out["size"] = sizes
    return out


def walk_index_file(path: str,
                    fn: Callable[[int, int, int], None]) -> None:
    """Replay (key, actual_offset, size) for each entry, in append order."""
    with open(path, "rb") as f:
        buf = f.read()
    for key, offset, size in iter_index_bytes(buf):
        fn(key, offset, size)


def iter_index_bytes(buf: bytes) -> Iterator[Tuple[int, int, int]]:
    arr = parse_index_bytes(buf)
    for i in range(len(arr)):
        yield int(arr["key"][i]), int(arr["offset"][i]), int(arr["size"][i])
