"""Incremental volume backup / tail: ship the .dat delta since a timestamp.

Behavioral parity with the reference (weed/storage/volume_backup.go,
weed/server/volume_grpc_tail.go):

- ``sync_status`` — tail offset + compaction revision + idx size, the
  handshake a follower uses to decide between incremental catch-up and
  full resync (volume_backup.go:19-33).
- ``binary_search_by_append_at_ns`` — the .idx is an append-ordered
  array, so appendAtNs is monotonic along it; binary-search entries,
  reading each probe's appendAtNs from the .dat record it points at
  (volume_backup.go:170-218).
- ``incremental_backup`` — the follower asks the source for all bytes
  after its own last appendAtNs, appends them raw at its EOF, then
  re-scans the appended region to extend its needle map
  (volume_backup.go:65-118).
- ``scan_dat_from`` / tail streaming — needle-at-a-time replay used by
  VolumeTailSender/Receiver (volume_grpc_tail.go:17-113).
"""

from __future__ import annotations

import os
import struct
from typing import Iterator, Tuple

from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage import idx as idx_codec
from seaweedfs_tpu.storage.needle import Needle, NeedleError, actual_size
from seaweedfs_tpu.storage.volume import Volume, VolumeError
from seaweedfs_tpu.util import wlog

_log = wlog.logger("storage.backup")


def sync_status(v: Volume) -> dict:
    """VolumeSyncStatus payload (reference volume_backup.go:19-33)."""
    idx_size = os.path.getsize(v.idx_path) if os.path.exists(v.idx_path) \
        else 0
    return {
        "volume_id": v.id,
        "collection": v.collection,
        "replication": str(v.replica_placement),
        "ttl": str(v.ttl),
        "tail_offset": v.content_size,
        "compact_revision": v.super_block.compaction_revision,
        "idx_file_size": idx_size,
    }


def _read_append_at_ns(v: Volume, offset: int) -> int:
    """appendAtNs of the record at .dat offset: read the 16-byte header
    for the size, then just the trailing 8-byte timestamp — NOT the
    whole record (a binary-search probe on a large-needle or
    cloud-tiered volume must not fetch megabytes per probset;
    volume_backup.go:155-168 reads header + body the same two-step
    way)."""
    header = v._dat.read_at(t.NEEDLE_HEADER_SIZE, offset)
    if len(header) < t.NEEDLE_HEADER_SIZE:
        raise VolumeError(f"short header read at {offset}")
    _, _, size_u = struct.unpack(">IQI", header)
    body = t.size_to_int32(size_u)
    if t.size_is_deleted(body):
        body = 0
    # VERSION3 record tail: ... data | 4B checksum | 8B appendAtNs | pad
    ts_off = offset + t.NEEDLE_HEADER_SIZE + body + t.NEEDLE_CHECKSUM_SIZE
    blob = v._dat.read_at(8, ts_off)
    if len(blob) < 8:
        raise VolumeError(f"short timestamp read at {ts_off}")
    return struct.unpack(">Q", blob)[0]


def last_append_at_ns(v: Volume) -> int:
    """appendAtNs of the newest record (via the last .idx entry;
    volume_backup.go:111-153). 0 for an empty volume."""
    if not os.path.exists(v.idx_path):
        return 0
    size = os.path.getsize(v.idx_path)
    if size < t.NEEDLE_MAP_ENTRY_SIZE:
        return 0
    entry_count = size // t.NEEDLE_MAP_ENTRY_SIZE
    with open(v.idx_path, "rb") as f:
        f.seek((entry_count - 1) * t.NEEDLE_MAP_ENTRY_SIZE)
        key, offset, esize = idx_codec.parse_entry(
            f.read(t.NEEDLE_MAP_ENTRY_SIZE))
    return _read_append_at_ns(v, offset)


def binary_search_by_append_at_ns(v: Volume,
                                  since_ns: int) -> Tuple[int, bool]:
    """First .dat offset whose record has appendAtNs > since_ns.

    Returns (offset, is_last): is_last=True means nothing is newer.
    The .idx is append-ordered, hence sorted by appendAtNs
    (volume_backup.go:170-218).
    """
    if not os.path.exists(v.idx_path):
        return 0, True
    file_size = os.path.getsize(v.idx_path)
    entry_count = file_size // t.NEEDLE_MAP_ENTRY_SIZE
    if entry_count == 0:
        return 0, True
    with open(v.idx_path, "rb") as f:
        def entry_offset(m: int) -> int:
            f.seek(m * t.NEEDLE_MAP_ENTRY_SIZE)
            _, offset, _ = idx_codec.parse_entry(
                f.read(t.NEEDLE_MAP_ENTRY_SIZE))
            return offset

        lo, hi = 0, entry_count
        while lo < hi:
            mid = (lo + hi) // 2
            m_ns = _read_append_at_ns(v, entry_offset(mid))
            if m_ns <= since_ns:
                lo = mid + 1
            else:
                hi = mid
        if lo == entry_count:
            return 0, True
        return entry_offset(lo), False


def scan_dat_from(v: Volume, offset: int,
                  include_deleted: bool = True
                  ) -> Iterator[Tuple[int, Needle]]:
    """Yield (offset, needle) for records at/after a .dat offset,
    tolerating a torn tail (the tail-stream scanner,
    volume_grpc_tail.go:96-143)."""
    size = v.content_size
    while offset + t.NEEDLE_HEADER_SIZE <= size:
        header = v._dat.read_at(t.NEEDLE_HEADER_SIZE, offset)
        if len(header) < t.NEEDLE_HEADER_SIZE:
            return
        _, _, size_u = struct.unpack(">IQI", header)
        body = t.size_to_int32(size_u)
        if t.size_is_deleted(body):
            body = 0
        length = actual_size(body, v.version)
        blob = v._dat.read_at(length, offset)
        if len(blob) < length:
            return
        try:
            n = Needle.from_bytes(blob, v.version, check_crc=False)
        except NeedleError:
            return
        if include_deleted or len(n.data) > 0:
            yield offset, n
        offset += length


def read_dat_range(v: Volume, offset: int, chunk: int = 1 << 20
                   ) -> Iterator[bytes]:
    """Raw .dat bytes from offset to EOF in chunks (the
    VolumeIncrementalCopy stream payload; the bytes are not chunked on
    needle boundaries, volume_backup.go:86-99)."""
    end = v.content_size
    while offset < end:
        data = v._dat.read_at(min(chunk, end - offset), offset)
        if not data:
            return
        yield data
        offset += len(data)


def apply_incremental(v: Volume, chunks) -> int:
    """Follower side of incremental backup: append raw delta bytes at
    EOF, then extend the needle map by scanning just the appended
    region (volume_backup.go:100-118). Returns bytes appended."""
    with v._lock:
        start = v.content_size
        write_offset = start
        for chunk in chunks:
            if not chunk:
                continue
            v._dat.write_at(chunk, write_offset)
            write_offset += len(chunk)
        appended = write_offset - start
        if appended == 0:
            return 0
        for offset, n in scan_dat_from(v, start):
            if len(n.data) == 0:
                v.nm.delete(n.id, offset)
            else:
                v.nm.put(n.id, offset, n.size)
            if n.append_at_ns > v.last_append_at_ns:
                v.last_append_at_ns = n.append_at_ns
        v.nm.flush()
        v._dat.sync()
    return appended


def incremental_backup(v: Volume, source_stub) -> int:
    """Catch a local replica up from a source volume server over the
    VolumeIncrementalCopy stream (volume_backup.go:65-118).

    The caller is responsible for the compact-revision / size sanity
    checks (command/backup.go does them in the reference; our CLI
    `backup` command mirrors that).
    """
    from seaweedfs_tpu.pb import volume_server_pb2
    since = last_append_at_ns(v)
    stream = source_stub.VolumeIncrementalCopy(
        volume_server_pb2.VolumeIncrementalCopyRequest(
            volume_id=v.id, since_ns=since))
    return apply_incremental(v, (resp.file_content for resp in stream))
