"""Volume-server storage engine: on-disk formats and the volume store.

Format-compatible with the reference (/root/reference weed/storage):
needle blobs in append-only .dat files, 16-byte .idx entries, 8-byte
superblock — all big-endian, needles padded to 8 bytes, CRC32-Castagnoli
checksums with the snappy-style mask.
"""

from seaweedfs_tpu.storage.types import (
    NEEDLE_PADDING, NEEDLE_HEADER_SIZE, NEEDLE_MAP_ENTRY_SIZE,
    TOMBSTONE_SIZE, FileId, size_is_deleted, size_is_valid,
)
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.superblock import SuperBlock, ReplicaPlacement, TTL
from seaweedfs_tpu.storage.volume import Volume
from seaweedfs_tpu.storage.store import Store
from seaweedfs_tpu.storage.disk_location import DiskLocation
