"""S3-compatible cloud-tier backend for sealed volume .dat files.

Reference parity: weed/storage/backend/s3_backend/s3_backend.go:23-100
(upload/download a volume .dat to S3, ranged reads for the tiered read
path). Uses the stdlib SigV4 client (util/s3_client.py) instead of an
AWS SDK, so it works against any S3-compatible endpoint — including
this package's own s3api gateway (which the tests use as the server).

Config (reference master.toml [storage.backend.s3.default]):
    endpoint, access_key, secret_key, bucket, region.
"""

from __future__ import annotations

from seaweedfs_tpu.storage import backend as bk
from seaweedfs_tpu.util.s3_client import S3Client, S3Error


class S3BackendStorage(bk.BackendStorage):
    def __init__(self, name: str, props: dict):
        self.name = name
        missing = [k for k in ("endpoint", "bucket") if not props.get(k)]
        if missing:
            raise bk.BackendError(
                f"backend {name}: missing config {missing}")
        self.bucket = props["bucket"]
        self.client = S3Client(
            props["endpoint"],
            access_key=props.get("access_key", ""),
            secret_key=props.get("secret_key", ""),
            region=props.get("region", "us-east-1"))

    def copy_file(self, local_path, key, progress=None):
        try:
            return self.client.upload_file(local_path, self.bucket, key,
                                           progress=progress)
        except S3Error as e:
            raise bk.BackendError(f"{self.name}: upload {key}: {e}") from e

    def download_file(self, key, local_path, progress=None):
        try:
            return self.client.download_file(self.bucket, key, local_path,
                                             progress=progress)
        except S3Error as e:
            raise bk.BackendError(f"{self.name}: download {key}: {e}") from e

    def read_range(self, key, offset, length):
        if length <= 0:
            return b""
        try:
            return self.client.get_object(
                self.bucket, key, byte_range=(offset, offset + length - 1))
        except S3Error as e:
            raise bk.BackendError(f"{self.name}: read {key}: {e}") from e

    def delete_file(self, key):
        try:
            self.client.delete_object(self.bucket, key)
        except S3Error as e:
            raise bk.BackendError(f"{self.name}: delete {key}: {e}") from e


bk.register_backend_factory(
    "s3", lambda name, props: S3BackendStorage(name, props))
